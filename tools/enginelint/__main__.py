"""CLI: ``python -m tools.enginelint <paths> [--strict] [--rule RLnnn]``.

Exit codes: 0 clean, 1 findings (or, with --strict, reason-less
suppressions), 2 usage error.
"""
from __future__ import annotations

import argparse
import sys

from tools.enginelint import run_lint


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="enginelint",
        description="AST-based engine-specific lint for spark_rapids_tpu")
    ap.add_argument("paths", nargs="+", help="files or directories to lint")
    ap.add_argument("--strict", action="store_true",
                    help="also fail suppressions that carry no written "
                         "reason")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="RLnnn", help="run only these rules")
    ap.add_argument("--list-suppressed", action="store_true",
                    help="print suppressed findings with their reasons")
    args = ap.parse_args(argv)

    rules = None
    if args.rule:
        from tools.enginelint.rules import RULES
        unknown = [r for r in args.rule if r.upper() not in RULES]
        if unknown:
            ap.error(f"unknown rule(s): {', '.join(unknown)}")
        rules = {r.upper(): RULES[r.upper()] for r in args.rule}

    findings = run_lint(args.paths, rules=rules)
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    bad_suppressions = [f for f in suppressed if not f.reason]

    for f in active:
        print(f.render())
    if args.strict:
        for f in bad_suppressions:
            print(f"{f.path}:{f.line}: {f.rule} suppression carries no "
                  "written reason — use "
                  f"'# enginelint: disable={f.rule} (why it is safe)'")
    if args.list_suppressed:
        for f in suppressed:
            print(f"{f.render()}  # reason: {f.reason or '<none>'}")

    print(f"enginelint: {len(active)} finding(s), {len(suppressed)} "
          f"suppressed ({len(bad_suppressions)} without reason)",
          file=sys.stderr)
    if active or (args.strict and bad_suppressions):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""The enginelint rule catalog (RL001-RL005).

Every rule encodes ONE engine contract (docs/developer-guide.md has the
catalog with rationale).  A rule is a callable
``rule(ctx: FileContext, registry) -> list[Finding]``; ``registry`` is
the cross-file state from :func:`collect_registry` (today: the fault
point registry for RL005).  Rules are heuristic by design — a correct
site a heuristic cannot prove safe takes a per-line suppression WITH a
written reason, which is itself enforced by ``--strict``.
"""
from __future__ import annotations

import ast
import re

from tools.enginelint import FileContext, Finding

__all__ = ["RULES", "collect_registry"]

_ENGINE_PREFIX = "spark_rapids_tpu/"


def _in_engine(ctx: FileContext) -> bool:
    return _ENGINE_PREFIX in ctx.rel or ctx.rel.startswith("spark_rapids_tpu")


def _engine_rel(ctx: FileContext) -> str:
    """Path relative to the spark_rapids_tpu package root ('' outside)."""
    i = ctx.rel.find("spark_rapids_tpu/")
    return ctx.rel[i + len("spark_rapids_tpu/"):] if i >= 0 else ""


# ---------------------------------------------------------------------------
# RL001: broad except that can swallow a terminal lifecycle exception
# ---------------------------------------------------------------------------

_BROAD = ("Exception", "BaseException")


def _names_broad(expr) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in _BROAD
    if isinstance(expr, ast.Attribute):
        return expr.attr in _BROAD
    if isinstance(expr, ast.Tuple):
        return any(_names_broad(e) for e in expr.elts)
    return False


def _handler_guarded(handler: ast.ExceptHandler) -> bool:
    """True when the handler body provably re-raises or discriminates on
    terminality: any ``raise``, any reference to ``terminal`` /
    ``is_terminal`` (getattr string, attribute, or name), or a call to a
    ``*reraise*`` helper."""
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Constant) and node.value == "terminal":
            return True
        if isinstance(node, ast.Attribute) and "terminal" in node.attr:
            return True
        if isinstance(node, ast.Name) and (
                "terminal" in node.id or "reraise" in node.id):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else \
                fn.attr if isinstance(fn, ast.Attribute) else ""
            if "reraise" in name or "terminal" in name:
                return True
    return False


def rl001(ctx: FileContext, registry) -> list:
    if not _in_engine(ctx):
        return []
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is not None and not _names_broad(node.type):
            continue
        if _handler_guarded(node):
            continue
        out.append(Finding(
            "RL001", ctx.rel, node.lineno,
            "broad except may swallow a terminal lifecycle exception "
            "(QueryCancelled/QueryDeadlineExceeded/MapOutputLostError): "
            "re-raise, guard on getattr(e, 'terminal', False), or "
            "suppress with the reason the swallow is safe"))
    return out


# ---------------------------------------------------------------------------
# RL002: raw jax.jit at module/class scope outside compile_cache.py
# ---------------------------------------------------------------------------

def rl002(ctx: FileContext, registry) -> list:
    """jax.jit evaluated at import time (module or class scope,
    including decorators on top-level defs) builds an unguarded wrapper:
    it bypasses the CPU compile guard and the map-pressure purge."""
    if not _in_engine(ctx) or _engine_rel(ctx) == "exec/compile_cache.py":
        return []
    aliases = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            aliases.update(a.asname or a.name for a in node.names
                           if a.name == "jit")

    def is_jit(expr) -> bool:
        if isinstance(expr, ast.Attribute) and expr.attr == "jit" and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("jax", "_jax"):
            return True
        return isinstance(expr, ast.Name) and expr.id in aliases

    hits: list[int] = []

    def scan(node) -> None:
        """Import-time expression scan: descend everywhere EXCEPT into
        function/lambda bodies (those run at call time)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in child.decorator_list:
                    if is_jit(dec) or (isinstance(dec, ast.Call)
                                       and is_jit(dec.func)):
                        hits.append(dec.lineno)
                continue
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.Call) and is_jit(child.func):
                hits.append(child.lineno)
            scan(child)

    scan(ctx.tree)
    return [Finding(
        "RL002", ctx.rel, line,
        "raw jax.jit at module/class scope: route through "
        "compile_cache.guarded_jit/shared_jit so the kernel passes the "
        "CPU compile guard and the map-pressure purge (the PR 7 "
        "SIGSEGV fix silently regresses otherwise)")
        for line in sorted(set(hits))]


# ---------------------------------------------------------------------------
# RL003: host-sync calls in exec hot paths outside transition modules
# ---------------------------------------------------------------------------

#: modules whose PURPOSE is the host<->device boundary
_RL003_WHITELIST = {"exec/core.py", "exec/transitions.py",
                    "exec/compile_cache.py"}


def rl003(ctx: FileContext, registry) -> list:
    rel = _engine_rel(ctx)
    if not rel.startswith("exec/") or rel in _RL003_WHITELIST:
        return []
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "block_until_ready":
            what = ".block_until_ready()"
        elif isinstance(fn, ast.Attribute) and fn.attr == "device_get" and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in ("jax", "_jax"):
            what = "jax.device_get()"
        else:
            continue
        out.append(Finding(
            "RL003", ctx.rel, node.lineno,
            f"host sync ({what}) in an exec hot path: each call stalls "
            "the dispatch pipeline; batch syncs into one stacked "
            "transfer or suppress documenting why this single sync is "
            "load-bearing"))
    return out


# ---------------------------------------------------------------------------
# RL004: unbounded loops without a lifecycle/cancel checkpoint
# ---------------------------------------------------------------------------

#: dispatch/drain/retry surface; exec/lifecycle.py IMPLEMENTS the
#: checkpoints so its own wait loops are excluded
_RL004_SCOPE = ("exec/", "shuffle/", "memory/")
_RL004_EXCLUDED = {"exec/lifecycle.py"}
_BUDGET_NAME = re.compile(r"retries|attempt", re.I)


def _loop_checkpointed(loop: ast.While) -> bool:
    has_raise = False
    has_budget_name = False
    for node in ast.walk(loop):
        if isinstance(node, ast.Raise):
            has_raise = True
        if isinstance(node, ast.Name):
            if "lifecycle" in node.id or node.id == "lc":
                return True
            if _BUDGET_NAME.search(node.id):
                has_budget_name = True
        if isinstance(node, ast.Attribute):
            if node.attr in ("check_cancel", "lifecycle"):
                return True
            if _BUDGET_NAME.search(node.attr):
                has_budget_name = True
    # a retry ladder bounded by an attempt budget that raises past it
    return has_raise and has_budget_name


def rl004(ctx: FileContext, registry) -> list:
    rel = _engine_rel(ctx)
    if not rel.startswith(_RL004_SCOPE) or rel in _RL004_EXCLUDED:
        return []
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.While):
            continue
        t = node.test
        unbounded = isinstance(t, ast.Constant) and t.value in (True, 1)
        if not unbounded or _loop_checkpointed(node):
            continue
        out.append(Finding(
            "RL004", ctx.rel, node.lineno,
            "unbounded loop in a dispatch/drain/retry path with no "
            "lifecycle/cancel checkpoint: a cancelled or "
            "deadline-exceeded query cannot interrupt it; call "
            "lifecycle.check()/ctx.check_cancel() per iteration, bound "
            "it by a retry budget, or suppress with the reason it "
            "terminates"))
    return out


# ---------------------------------------------------------------------------
# RL005: fault-injection point names vs the faults.py registry
# ---------------------------------------------------------------------------

def collect_registry(ctxs) -> dict:
    """Cross-file pre-pass: KNOWN_POINTS from faults.py plus every
    ``*.check("point", ...)`` call site in the scanned set."""
    known: dict[str, tuple] = {}   # point -> (rel, line) of declaration
    used: dict[str, list] = {}     # point -> [(rel, line), ...]
    faults_file = None
    for ctx in ctxs:
        if _engine_rel(ctx) == "faults.py":
            faults_file = ctx
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "check" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                used.setdefault(node.args[0].value, []).append(
                    (ctx.rel, node.lineno))
    if faults_file is not None:
        for node in ast.walk(faults_file.tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
                    for t in node.targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        known[sub.value] = (faults_file.rel, sub.lineno)
    return {"known": known, "used": used,
            "have_faults_file": faults_file is not None}


def rl005(ctx: FileContext, registry) -> list:
    if registry is None or not registry.get("have_faults_file") or \
            not _in_engine(ctx):
        return []
    known = registry["known"]
    used = registry["used"]
    out = []
    for point, sites in used.items():
        for rel, line in sites:
            if rel == ctx.rel and point not in known:
                out.append(Finding(
                    "RL005", ctx.rel, line,
                    f"fault-injection point '{point}' is not registered "
                    "in faults.KNOWN_POINTS: a fault plan naming it "
                    "would silently never fire"))
    if _engine_rel(ctx) == "faults.py":
        for point, (rel, line) in sorted(known.items()):
            if point not in used:
                out.append(Finding(
                    "RL005", ctx.rel, line,
                    f"registered fault point '{point}' has no "
                    "faults.check() call site: dead registry entry or a "
                    "renamed injection site"))
    return out


RULES = {"RL001": rl001, "RL002": rl002, "RL003": rl003,
         "RL004": rl004, "RL005": rl005}

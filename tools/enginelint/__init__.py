"""enginelint: AST-based engine-specific lint for spark_rapids_tpu.

The engine's correctness rests on conventions no general linter knows:
terminal lifecycle exceptions must never be swallowed, every
module-level jit must route through the compile cache's guarded
wrappers, hot exec paths must not sync to host, dispatch/drain/retry
loops must hit a cancellation checkpoint, and fault-injection point
names must match the registry.  Each rule here encodes one of those
contracts over the Python AST — stdlib only, no engine import, so the
lint runs in any environment (including premerge before jax loads).

Usage::

    python -m tools.enginelint spark_rapids_tpu/ [--strict]

Per-line suppression (same line as the finding, or the immediately
preceding comment-only line)::

    except Exception:  # enginelint: disable=RL001 (diag is best-effort)

``--strict`` additionally fails any suppression that carries no written
reason, so every accepted violation documents WHY it is safe.  The rule
catalog lives in tools/enginelint/rules.py and the invariant each rule
enforces in docs/developer-guide.md.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

__all__ = ["Finding", "FileContext", "lint_file", "lint_source",
           "run_lint", "iter_py_files", "SUPPRESS_RE"]

SUPPRESS_RE = re.compile(
    r"#\s*enginelint:\s*disable=([A-Za-z0-9_,]+)\s*(?:\(([^)]*)\))?")


@dataclass
class Finding:
    """One rule violation (or, in strict mode, one bad suppression)."""
    rule: str
    path: str          # repo-relative path
    line: int
    message: str
    suppressed: bool = False
    reason: str | None = None

    def render(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}{tag} {self.message}"


@dataclass
class FileContext:
    """Parsed view of one source file handed to every rule."""
    path: str                      # absolute
    rel: str                       # repo-relative, forward slashes
    source: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)
    # line -> {rule_or_ALL: reason_or_None}
    suppressions: dict = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, rel: str, source: str) -> "FileContext":
        ctx = cls(path=path, rel=rel, source=source,
                  tree=ast.parse(source, filename=rel),
                  lines=source.splitlines())
        for i, text in enumerate(ctx.lines, start=1):
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            reason = (m.group(2) or "").strip() or None
            per = ctx.suppressions.setdefault(i, {})
            for rule in m.group(1).split(","):
                per[rule.strip().upper()] = reason
        return ctx

    def suppression_for(self, rule: str, line: int):
        """(found, reason) for ``rule`` at ``line``: same line, or an
        immediately preceding comment-only line."""
        for cand in (line, line - 1):
            per = self.suppressions.get(cand)
            if per is None:
                continue
            if cand == line - 1 and \
                    not self.lines[cand - 1].lstrip().startswith("#"):
                continue  # trailing comment of the PREVIOUS statement
            for key in (rule, "ALL"):
                if key in per:
                    return True, per[key]
        return False, None


def iter_py_files(paths) -> list[str]:
    out = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(os.path.abspath(p))
            continue
        for base, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            out.extend(os.path.join(base, f) for f in sorted(files)
                       if f.endswith(".py"))
    return out


def _relpath(path: str, root: str | None) -> str:
    root = root or os.getcwd()
    try:
        rel = os.path.relpath(path, root)
    except ValueError:
        rel = path
    return rel.replace(os.sep, "/")


def lint_source(source: str, rel: str, rules=None,
                registry=None) -> list[Finding]:
    """Lint one in-memory source blob (unit tests); suppressions are
    applied, suppressed findings returned with ``suppressed=True``."""
    from tools.enginelint.rules import RULES
    ctx = FileContext.parse(rel, rel, source)
    return _apply(ctx, rules or RULES, registry)


def lint_file(path: str, rel: str, rules=None, registry=None) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    ctx = FileContext.parse(path, rel, source)
    from tools.enginelint.rules import RULES
    return _apply(ctx, rules or RULES, registry)


def _apply(ctx: FileContext, rules, registry) -> list[Finding]:
    out: list[Finding] = []
    for rule in rules.values():
        for f in rule(ctx, registry):
            f.suppressed, f.reason = ctx.suppression_for(f.rule, f.line)
            out.append(f)
    out.sort(key=lambda f: (f.line, f.rule))
    return out


def run_lint(paths, root: str | None = None,
             rules=None) -> list[Finding]:
    """Lint every .py file under ``paths``.  Returns ALL findings —
    callers filter on ``suppressed`` / ``reason``.  Cross-file state
    (the fault-point registry for RL005) is collected in a first pass
    over the same file set."""
    from tools.enginelint.rules import RULES, collect_registry
    rules = rules or RULES
    files = iter_py_files(paths)
    ctxs = []
    for path in files:
        rel = _relpath(path, root)
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            ctxs.append(FileContext.parse(path, rel, source))
        except SyntaxError as e:
            raise SystemExit(f"enginelint: cannot parse {rel}: {e}")
    registry = collect_registry(ctxs)
    findings: list[Finding] = []
    for ctx in ctxs:
        findings.extend(_apply(ctx, rules, registry))
    return findings

"""Query-history forensics CLI over the JSONL log written by
``spark.rapids.obs.history.dir`` (obs/history.py) — the operator-facing
analog of browsing the Spark history server.

Deliberately engine-free (pure stdlib, no spark_rapids_tpu imports): it
must work on a laptop against a log scp'd off a serving box where the
engine (and jax) are not installed.

    python -m tools.history [--dir DIR] list [-n N]
    python -m tools.history [--dir DIR] show QUERY_ID [--profile]
    python -m tools.history [--dir DIR] diff QUERY_ID1 QUERY_ID2
    python -m tools.history [--dir DIR] top [-n N]

``list`` prints the newest entries (state, tenant, wall, when); ``show``
pretty-prints one entry (query_id prefix match, newest wins) —
``--profile`` renders its stored operator cost table instead; ``diff``
compares two queries' analyzed plans (unified diff) and registry deltas
— the "why did the same query get slow" tool; ``top`` ranks plan
fingerprints by median wall and flags regressions (recent median
drifted >2x vs the prior window).
"""
from __future__ import annotations

import argparse
import difflib
import json
import os
import sys
import time

HISTORY_FILE = "query_history.jsonl"


def _read(directory: str) -> list[dict]:
    path = os.path.join(directory, HISTORY_FILE)
    out: list[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except FileNotFoundError:
        raise SystemExit(f"no history log at {path} "
                         "(is spark.rapids.obs.history.dir set?)")
    return out


def _find(entries: list[dict], qid: str) -> dict:
    hits = [e for e in entries if str(e.get("query_id", "")).startswith(qid)]
    if not hits:
        raise SystemExit(f"no history entry matches query_id {qid!r}")
    return hits[-1]  # newest wins on prefix ambiguity


def _when(e: dict) -> str:
    ts = e.get("submitted_unix_s")
    if not ts:
        return "?"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))


def _fmt_wall(e: dict) -> str:
    w = e.get("wall_s")
    return "-" if w is None else f"{w:.3f}s"


def cmd_list(entries: list[dict], n: int) -> int:
    rows = entries[-n:]
    if not rows:
        print("history log is empty")
        return 0
    print(f"{'query_id':<18} {'state':<18} {'tenant':<10} "
          f"{'wall':>9}  submitted")
    for e in rows:
        extra = ""
        if e.get("served_from_cache"):
            extra = "  [cache hit]"
        err = e.get("error") or {}
        if err.get("type"):
            extra = f"  [{err['type']}]"
        print(f"{str(e.get('query_id', '?')):<18} "
              f"{str(e.get('state', '?')):<18} "
              f"{str(e.get('tenant', '?')):<10} "
              f"{_fmt_wall(e):>9}  {_when(e)}{extra}")
    return 0


def cmd_show(entries: list[dict], qid: str,
             profile: bool = False) -> int:
    e = _find(entries, qid)
    if profile:
        return _show_profile(e)
    plan = e.pop("plan_analyzed", None)
    print(json.dumps(e, indent=2, sort_keys=True))
    if plan:
        print("\n-- analyzed plan " + "-" * 40)
        print(plan)
    return 0


def _show_profile(e: dict) -> int:
    """Render the stored operator cost table (entry["profile"], written
    by obs/profile.py when spark.rapids.obs.profile.enabled was on):
    top-level operators by device seconds, attributed members indented
    under their container."""
    prof = e.get("profile")
    if not prof:
        print(f"query {e.get('query_id')} has no stored profile "
              "(was spark.rapids.obs.profile.enabled on?)")
        return 1
    ops = prof.get("operators") or {}
    meter = e.get("metering") or {}
    print(f"query_id={e.get('query_id')}  state={e.get('state')}  "
          f"wall={_fmt_wall(e)}")
    print(f"device_seconds={prof.get('device_seconds')}  "
          f"hbm_byte_seconds={prof.get('hbm_byte_seconds')}"
          + (f"  metered_device_s={meter.get('device_seconds')}"
             if meter else ""))
    print(f"\n{'operator':<44} {'device_s':>10} {'wall_s':>10} "
          f"{'batches':>8} {'rows':>12}")
    tops = sorted((e2 for e2 in ops.values() if not e2.get("parent")),
                  key=lambda e2: -float(e2.get("device_s", 0.0)))
    kids: dict = {}
    for e2 in ops.values():
        par = e2.get("parent")
        if par:
            kids.setdefault(par, []).append(e2)

    def line(e2: dict, indent: str = "") -> None:
        print(f"{indent + str(e2.get('op', '?')):<44} "
              f"{float(e2.get('device_s', 0.0)):>10.6f} "
              f"{float(e2.get('wall_s', 0.0)):>10.6f} "
              f"{int(e2.get('batches', 0)):>8d} "
              f"{int(e2.get('rows', 0)):>12d}")

    for t in tops:
        line(t)
        # a container's key is its label; members carry it as parent
        label = next((k for k, v in ops.items() if v is t), None)
        for m in sorted(kids.get(label, []),
                        key=lambda e2: -float(e2.get("device_s", 0.0))):
            line(m, indent="  ")
    return 0


def _median(xs: list) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def cmd_top(entries: list[dict], n: int) -> int:
    """Slowest plan fingerprints by median wall over FINISHED runs,
    regression-flagged when the recent half's median drifted >2x vs
    the prior half (needs >=2 samples in each half)."""
    groups: dict = {}
    for e in entries:
        fp = e.get("plan_fingerprint")
        if not fp or e.get("state") != "FINISHED":
            continue
        w = e.get("wall_s")
        if not isinstance(w, (int, float)) or w < 0:
            continue
        g = groups.setdefault(fp, {"walls": [], "devs": [],
                                   "tenants": set(), "last": e})
        g["walls"].append(float(w))
        g["last"] = e
        g["tenants"].add(str(e.get("tenant") or "default"))
        dev = (e.get("metering") or {}).get("device_seconds")
        if isinstance(dev, (int, float)):
            g["devs"].append(float(dev))
    if not groups:
        print("no FINISHED fingerprinted entries in the log")
        return 0
    rows = []
    for fp, g in groups.items():
        walls = g["walls"]  # log order == time order
        half = len(walls) // 2
        regressed = False
        if half >= 2:
            prior, recent = walls[:half], walls[half:]
            regressed = _median(recent) > 2.0 * _median(prior)
        rows.append((_median(walls), fp, g, regressed))
    rows.sort(key=lambda r: -r[0])
    print(f"{'fingerprint':<18} {'runs':>5} {'median':>9} "
          f"{'device_s':>9} {'tenants':<16} flag")
    for med, fp, g, regressed in rows[:n]:
        dev = f"{_median(g['devs']):.4f}" if g["devs"] else "-"
        flag = "REGRESSED(>2x)" if regressed else ""
        print(f"{fp[:16]:<18} {len(g['walls']):>5} {med:>8.3f}s "
              f"{dev:>9} {','.join(sorted(g['tenants']))[:16]:<16} "
              f"{flag}")
    return 0


def _counters(e: dict) -> dict:
    return (e.get("registry_delta") or {}).get("counters") or {}


def cmd_diff(entries: list[dict], qid_a: str, qid_b: str) -> int:
    a, b = _find(entries, qid_a), _find(entries, qid_b)
    ida, idb = a.get("query_id", qid_a), b.get("query_id", qid_b)
    print(f"A: {ida}  state={a.get('state')}  wall={_fmt_wall(a)}  "
          f"submitted={_when(a)}")
    print(f"B: {idb}  state={b.get('state')}  wall={_fmt_wall(b)}  "
          f"submitted={_when(b)}")
    if a.get("plan_fingerprint") != b.get("plan_fingerprint"):
        print("plan fingerprints DIFFER")
    pa = (a.get("plan_analyzed") or "").splitlines(keepends=True)
    pb = (b.get("plan_analyzed") or "").splitlines(keepends=True)
    if pa or pb:
        diff = list(difflib.unified_diff(pa, pb, fromfile=f"plan {ida}",
                                         tofile=f"plan {idb}"))
        if diff:
            print("\n-- analyzed plan diff " + "-" * 35)
            sys.stdout.writelines(diff)
        else:
            print("analyzed plans are identical")
    ca, cb = _counters(a), _counters(b)
    keys = sorted(set(ca) | set(cb))
    moved = [(k, ca.get(k, 0), cb.get(k, 0)) for k in keys
             if ca.get(k, 0) != cb.get(k, 0)]
    if moved:
        print("\n-- registry delta diff " + "-" * 34)
        print(f"{'counter':<44} {'A':>12} {'B':>12}")
        for k, va, vb in moved:
            print(f"{k:<44} {va:>12g} {vb:>12g}")
    else:
        print("registry counter deltas are identical")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.history",
        description="Inspect the engine's query-history log.")
    p.add_argument("--dir", default=".",
                   help="history directory (spark.rapids.obs.history.dir; "
                        "default: cwd)")
    sub = p.add_subparsers(dest="cmd", required=True)
    pl = sub.add_parser("list", help="newest entries")
    pl.add_argument("-n", type=int, default=20)
    ps = sub.add_parser("show", help="one entry in full")
    ps.add_argument("query_id")
    ps.add_argument("--profile", action="store_true",
                    help="render the stored operator cost table")
    pd = sub.add_parser("diff", help="compare two queries")
    pd.add_argument("query_id_a")
    pd.add_argument("query_id_b")
    pt = sub.add_parser("top",
                        help="slowest fingerprints by median wall, "
                             "regressions flagged")
    pt.add_argument("-n", type=int, default=10)
    args = p.parse_args(argv)
    entries = _read(args.dir)
    if args.cmd == "list":
        return cmd_list(entries, args.n)
    if args.cmd == "show":
        return cmd_show(entries, args.query_id, profile=args.profile)
    if args.cmd == "top":
        return cmd_top(entries, args.n)
    return cmd_diff(entries, args.query_id_a, args.query_id_b)


if __name__ == "__main__":
    raise SystemExit(main())

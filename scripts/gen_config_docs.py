"""Regenerate docs/configs.md from the conf registry."""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from spark_rapids_tpu.conf import generate_docs  # noqa: E402

out = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "configs.md")
os.makedirs(os.path.dirname(out), exist_ok=True)
with open(out, "w") as f:
    f.write(generate_docs())
print(f"wrote {out}")

"""Drive expression device kernels on the real TPU chip and cross-check
against the host oracle."""
import math
import numpy as np
import jax
import spark_rapids_tpu
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import col, lit, bind, eval_host
from spark_rapids_tpu.expr.core import eval_device
from spark_rapids_tpu.expr import arithmetic as A, predicates as P, conditional as C
from spark_rapids_tpu.expr import strings as S, datetime_ops as D, math_ops as M
from spark_rapids_tpu.expr.cast import Cast
from spark_rapids_tpu.expr.hashing import Murmur3Hash
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.columnar.batch import ColumnBatch

assert jax.default_backend() == "tpu", jax.default_backend()

def schema(**kw):
    return T.Schema([T.StructField(k, v) for k, v in kw.items()])

def run_both(expr, data, sch, approx=False):
    hb = HostBatch.from_pydict(data, sch)
    bound = bind(expr, sch)
    hres = eval_host(bound, hb).to_list()
    db = hb.to_device()
    f = jax.jit(lambda b: eval_device(bound, b))
    dcol = f(db)
    out = ColumnBatch([dcol], db.num_rows, schema(r=bound.dtype))
    dres = HostBatch.from_device(out).columns[0].to_list()
    for i, (h, d) in enumerate(zip(hres, dres)):
        if h is None or d is None:
            assert h is None and d is None, (expr, i, h, d)
        elif isinstance(h, float):
            if math.isnan(h):
                assert isinstance(d, float) and math.isnan(d), (expr, i, h, d)
            elif math.isinf(h) or not approx and False:
                assert h == d, (expr, i, h, d)
            elif approx:
                assert abs(d - h) <= 1e-9 * max(1, abs(h)), (expr, i, h, d)
            else:
                assert h == d, (expr, i, h, d)
        else:
            assert h == d, (expr, i, h, d)

ISCH = schema(a=T.IntegerType(), b=T.IntegerType())
IDATA = {"a": [1, None, 3, -7, 2147483647, 0, -2147483648],
         "b": [2, 5, None, 3, 1, 0, -1]}
DSCH = schema(x=T.DoubleType(), y=T.DoubleType())
DDATA = {"x": [1.5, None, float("nan"), -0.0, float("inf"), 2.0, -3.5, 1e-30, 1e30],
         "y": [0.5, 2.0, 1.0, 0.0, float("nan"), None, 2.0, 1.0, 2.0]}
SSCH = schema(s=T.StringType(), t=T.StringType())
SDATA = {"s": ["hello", "", None, "Hello World", "abc", "  pad  ", "héllo"],
         "t": ["he", "x", "y", "World", None, "pad", "llo"]}

run_both(col("a") + col("b"), IDATA, ISCH); print("add ok")
run_both(col("a") / col("b"), IDATA, ISCH, approx=True); print("div ok")
run_both(col("a") % col("b"), IDATA, ISCH); print("mod ok")
run_both(A.IntegralDivide(col("a"), col("b")), IDATA, ISCH)
run_both(col("x") > col("y"), DDATA, DSCH); print("cmp ok")
run_both(col("x") == col("x"), DDATA, DSCH)
run_both((col("a") > lit(0)) & (col("b") > lit(0)), IDATA, ISCH)
run_both(col("a").isin(1, 3, 99), IDATA, ISCH); print("in ok")
run_both(C.If(col("a") > col("b"), col("a"), col("b")), IDATA, ISCH)
run_both(C.CaseWhen([(col("a") > lit(0), lit("pos"))], lit("other")), IDATA, ISCH)
run_both(C.Coalesce(col("a"), col("b"), lit(-1)), IDATA, ISCH); print("cond ok")
run_both(Cast(col("x"), T.IntegerType()), DDATA, DSCH)
run_both(Cast(col("x"), T.LongType()), DDATA, DSCH); print("cast ok")
run_both(S.Upper(col("s")), {"s": ["hello", "aBc", None, "Hello World", "abc", "  pad  ", "hxllo"], "t": SDATA["t"]}, SSCH)  # ASCII-only: device case-map is ASCII (documented incompat)
run_both(S.Length(col("s")), SDATA, SSCH)
run_both(col("s").substr(2, 3), SDATA, SSCH)
run_both(S.Concat(col("s"), lit("_"), col("t")), SDATA, SSCH)
run_both(col("s").startswith(col("t")), SDATA, SSCH)
run_both(col("s").contains(col("t")), SDATA, SSCH)
run_both(col("s").like("%llo%"), SDATA, SSCH)
run_both(S.StringTrim(col("s")), SDATA, SSCH); print("strings ok")
import datetime as dt
DTS = schema(d=T.DateType())
run_both(D.Year(col("d")), {"d": [dt.date(2020,2,29), dt.date(1582,10,15), None]}, DTS)
run_both(D.DayOfWeek(col("d")), {"d": [dt.date(2020,2,29), dt.date(1969,7,20), None]}, DTS)
print("datetime ok")
run_both(M.Floor(col("x")), DDATA, DSCH)
run_both(M.Round(col("x"), 1), DDATA, DSCH, approx=True)
run_both(M.Log(col("x")), DDATA, DSCH, approx=True); print("math ok")
run_both(Murmur3Hash(col("a"), col("b")), IDATA, ISCH)
# TPU f64 compute is a float32-pair (~48 mantissa bits): murmur3 of
# doubles is exact only for values representable in 48 bits (documented
# incompat for the general case)
run_both(Murmur3Hash(col("x")), {"x": [1.5, None, float("nan"), -0.0, float("inf"), 2.0, -3.5, 0.25, 123456.0], "y": DDATA["y"]}, DSCH)
run_both(Murmur3Hash(col("s")), SDATA, SSCH); print("murmur3 ok")
print("ALL TPU EXPR CHECKS PASSED")

"""Drive expression device kernels on the real TPU chip and cross-check
against the host oracle."""
import math
import numpy as np
import jax
import spark_rapids_tpu
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import col, lit, bind, eval_host
from spark_rapids_tpu.expr.core import eval_device
from spark_rapids_tpu.expr import arithmetic as A, predicates as P, conditional as C
from spark_rapids_tpu.expr import strings as S, datetime_ops as D, math_ops as M
from spark_rapids_tpu.expr.cast import Cast
from spark_rapids_tpu.expr.hashing import Murmur3Hash
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.columnar.batch import ColumnBatch

assert jax.default_backend() == "tpu", jax.default_backend()

def schema(**kw):
    return T.Schema([T.StructField(k, v) for k, v in kw.items()])

def run_both(expr, data, sch, approx=False):
    hb = HostBatch.from_pydict(data, sch)
    bound = bind(expr, sch)
    hres = eval_host(bound, hb).to_list()
    db = hb.to_device()
    f = jax.jit(lambda b: eval_device(bound, b))
    dcol = f(db)
    out = ColumnBatch([dcol], db.num_rows, schema(r=bound.dtype))
    dres = HostBatch.from_device(out).columns[0].to_list()
    for i, (h, d) in enumerate(zip(hres, dres)):
        if h is None or d is None:
            assert h is None and d is None, (expr, i, h, d)
        elif isinstance(h, float):
            if math.isnan(h):
                assert isinstance(d, float) and math.isnan(d), (expr, i, h, d)
            elif math.isinf(h) or not approx and False:
                assert h == d, (expr, i, h, d)
            elif approx:
                assert abs(d - h) <= 1e-9 * max(1, abs(h)), (expr, i, h, d)
            else:
                assert h == d, (expr, i, h, d)
        else:
            assert h == d, (expr, i, h, d)

ISCH = schema(a=T.IntegerType(), b=T.IntegerType())
IDATA = {"a": [1, None, 3, -7, 2147483647, 0, -2147483648],
         "b": [2, 5, None, 3, 1, 0, -1]}
DSCH = schema(x=T.DoubleType(), y=T.DoubleType())
DDATA = {"x": [1.5, None, float("nan"), -0.0, float("inf"), 2.0, -3.5, 1e-30, 1e30],
         "y": [0.5, 2.0, 1.0, 0.0, float("nan"), None, 2.0, 1.0, 2.0]}
SSCH = schema(s=T.StringType(), t=T.StringType())
SDATA = {"s": ["hello", "", None, "Hello World", "abc", "  pad  ", "héllo"],
         "t": ["he", "x", "y", "World", None, "pad", "llo"]}

run_both(col("a") + col("b"), IDATA, ISCH); print("add ok")
run_both(col("a") / col("b"), IDATA, ISCH, approx=True); print("div ok")
run_both(col("a") % col("b"), IDATA, ISCH); print("mod ok")
run_both(A.IntegralDivide(col("a"), col("b")), IDATA, ISCH)
run_both(col("x") > col("y"), DDATA, DSCH); print("cmp ok")
run_both(col("x") == col("x"), DDATA, DSCH)
run_both((col("a") > lit(0)) & (col("b") > lit(0)), IDATA, ISCH)
run_both(col("a").isin(1, 3, 99), IDATA, ISCH); print("in ok")
run_both(C.If(col("a") > col("b"), col("a"), col("b")), IDATA, ISCH)
run_both(C.CaseWhen([(col("a") > lit(0), lit("pos"))], lit("other")), IDATA, ISCH)
run_both(C.Coalesce(col("a"), col("b"), lit(-1)), IDATA, ISCH); print("cond ok")
run_both(Cast(col("x"), T.IntegerType()), DDATA, DSCH)
run_both(Cast(col("x"), T.LongType()), DDATA, DSCH); print("cast ok")
run_both(S.Upper(col("s")), {"s": ["hello", "aBc", None, "Hello World", "abc", "  pad  ", "hxllo"], "t": SDATA["t"]}, SSCH)  # ASCII-only: device case-map is ASCII (documented incompat)
run_both(S.Length(col("s")), SDATA, SSCH)
run_both(col("s").substr(2, 3), SDATA, SSCH)
run_both(S.Concat(col("s"), lit("_"), col("t")), SDATA, SSCH)
run_both(col("s").startswith(col("t")), SDATA, SSCH)
run_both(col("s").contains(col("t")), SDATA, SSCH)
run_both(col("s").like("%llo%"), SDATA, SSCH)
run_both(S.StringTrim(col("s")), SDATA, SSCH); print("strings ok")
import datetime as dt
DTS = schema(d=T.DateType())
run_both(D.Year(col("d")), {"d": [dt.date(2020,2,29), dt.date(1582,10,15), None]}, DTS)
run_both(D.DayOfWeek(col("d")), {"d": [dt.date(2020,2,29), dt.date(1969,7,20), None]}, DTS)
print("datetime ok")
run_both(M.Floor(col("x")), DDATA, DSCH)
run_both(M.Round(col("x"), 1), DDATA, DSCH, approx=True)
run_both(M.Log(col("x")), DDATA, DSCH, approx=True); print("math ok")
run_both(Murmur3Hash(col("a"), col("b")), IDATA, ISCH)
# TPU f64 compute is a float32-pair (~48 mantissa bits): murmur3 of
# doubles is exact only for values representable in 48 bits (documented
# incompat for the general case)
run_both(Murmur3Hash(col("x")), {"x": [1.5, None, float("nan"), -0.0, float("inf"), 2.0, -3.5, 0.25, 123456.0], "y": DDATA["y"]}, DSCH)
run_both(Murmur3Hash(col("s")), SDATA, SSCH); print("murmur3 ok")


# ---------------------------------------------------------------------------
# f64-pair error quantification (VERDICT r3 item 10)
#
# On TPU, f64 compute is emulated as a float32 pair (~48 mantissa bits,
# f32 exponent range — docs/compatibility.md).  Quantify the actual
# aggregate-level error at TPC-DS-like scale: sum/avg/min/max over
# doubles of several magnitude distributions, device vs the host numpy
# oracle, max relative error per op recorded in
# artifacts/f64_pair_error.json.  The reference ships the analogous
# caveat as `incompat` flags + approximate_float test marks
# (RapidsConf.scala:461-492).
# ---------------------------------------------------------------------------
import json
import os

from spark_rapids_tpu.ops.segmented import AggSpec, sorted_group_by

def agg_err_cases():
    rng = np.random.default_rng(42)
    n = 1_000_000
    yield "uniform_0_1", rng.random(n)
    yield "tpcds_prices", np.round(rng.random(n) * 300.0, 2)
    yield "wide_magnitude", rng.random(n) * np.exp(rng.normal(0, 20, n))
    yield "mixed_sign_cancel", rng.normal(0, 1e6, n)
    yield "large_48bit_edge", (rng.integers(0, 2**53, n).astype(np.float64))

def quantify_f64_pair():
    report = {}
    for name, data in agg_err_cases():
        keys = (np.arange(len(data)) % 64).astype(np.int32)
        sch = schema(k=T.IntegerType(), v=T.DoubleType())
        hb = HostBatch.from_pydict({"k": keys, "v": data}, sch)
        db = hb.to_device()
        specs = [AggSpec("sum", 1), AggSpec("avg", 1),
                 AggSpec("min", 1), AggSpec("max", 1)]
        out = jax.jit(lambda b: sorted_group_by(b, [0], specs))(db)
        res = HostBatch.from_device(
            ColumnBatch(out.columns, out.num_rows, out.schema))
        got_k = np.asarray(res.columns[0].data)
        got = {op: np.asarray(res.columns[1 + i].data)
               for i, op in enumerate(("sum", "avg", "min", "max"))}
        order = np.argsort(got_k)
        ops_err = {}
        for op in ("sum", "avg", "min", "max"):
            want = np.zeros(64)
            for g in range(64):
                seg = data[keys == g]
                want[g] = {"sum": seg.sum(), "avg": seg.mean(),
                           "min": seg.min(), "max": seg.max()}[op]
            have = got[op][order]
            rel = np.abs(have - want) / np.maximum(np.abs(want), 1e-300)
            ops_err[op] = float(rel.max())
        report[name] = ops_err
        print(f"f64 agg err [{name}]: " + ", ".join(
            f"{op}={e:.3e}" for op, e in ops_err.items()))
    # murmur3-over-doubles divergence count (48-bit mantissa ceiling)
    rng = np.random.default_rng(7)
    vals = rng.integers(0, 2**53, 100_000).astype(np.float64)
    sch = schema(x=T.DoubleType())
    hb = HostBatch.from_pydict({"x": vals}, sch)
    bound = bind(Murmur3Hash([col("x")], 42), sch)
    hres = np.asarray(eval_host(bound, hb).data)
    db = hb.to_device()
    dcol = jax.jit(lambda b: eval_device(bound, b))(db)
    dres = np.asarray(HostBatch.from_device(ColumnBatch(
        [dcol], db.num_rows, schema(r=bound.dtype))).columns[0].data)
    diverged = int((hres != dres).sum())
    report["murmur3_double_53bit"] = {
        "diverged_rows": diverged, "total_rows": len(vals),
        "diverged_frac": diverged / len(vals)}
    print(f"murmur3 over >48-bit doubles: {diverged}/{len(vals)} diverge")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "f64_pair_error.json")
    with open(path, "w") as f:
        json.dump({"backend": jax.default_backend(), "report": report},
                  f, indent=1, sort_keys=True)
    print("wrote", path)

quantify_f64_pair()
print("ALL TPU EXPR CHECKS PASSED")

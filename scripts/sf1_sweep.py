"""Full 99-query TPC-DS SF1 verified sweep with per-query checkpointing
and ORACLE TIME CAPPING.

Round-4 verdict item 3: the sweep stopped at q71 because the heaviest
numpy oracles run >30min each at SF1, silently excluding the quarter of
the suite most likely to regress.  Here every query reports:

* device_warm_s — median of 3 in-process device-engine iterations
  (XLA:CPU backend; iteration 0's compile cost is discarded),
* oracle_s + ok — the SF1 numpy oracle, run in a KILLABLE subprocess
  under SWEEP_ORACLE_CAP_S (default 400s).  When the cap fires, the
  query is instead VERIFIED at SF0.1 (cheap oracle, same plan) and the
  record carries ``oracle_capped`` plus ``speedup_lb = cap / device``
  — an honest lower bound, never reported as an exact speedup.

Writes one JSON line per query to the checkpoint (a killed run resumes)
and assembles bench_results_sf1_cpu.json at the end.  Usage:

    JAX_PLATFORMS=cpu python scripts/sf1_sweep.py [checkpoint.jsonl]
"""
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spark_rapids_tpu.bench.runner import run_benchmark  # noqa: E402
from spark_rapids_tpu.bench.tpcds_queries import QUERIES  # noqa: E402

CKPT = sys.argv[1] if len(sys.argv) > 1 else "/tmp/sf1_sweep_ckpt.jsonl"
DATA = ".bench_data/sf1"
DATA_SMALL = ".bench_data/sf0.1"
OUT = "bench_results_sf1_cpu.json"
ORACLE_CAP_S = float(os.environ.get("SWEEP_ORACLE_CAP_S", "400"))

_ORACLE_CODE = """
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.bench.runner import _collect_rows, _plan_of, _rows_match
from spark_rapids_tpu.bench.tpcds_queries import build_query
name, data, rows_path = sys.argv[1], sys.argv[2], sys.argv[3]
with open(rows_path) as f:
    device_rows = [tuple(r) for r in json.load(f)]
# date/timestamp cells serialized via str(); normalize the oracle side
# identically before comparison
import datetime
def _norm_cells(rows):
    return [tuple(str(x) if isinstance(x, (datetime.date, datetime.datetime))
                  else x for x in r) for r in rows]
s = TpuSession({})
df = build_query(name, s, data)
plan = _plan_of(df)
t0 = time.perf_counter()
oracle = _collect_rows(df, "host", plan)
dt = time.perf_counter() - t0
print("ORACLE_RESULT:" + json.dumps(
    {"oracle_s": round(dt, 4),
     "ok": _rows_match(device_rows, _norm_cells(oracle))}))
"""


def _oracle_subprocess(name: str, device_rows) -> dict | None:
    """SF1 oracle under the cap; None when the cap fires."""
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump([list(r) for r in device_rows], f,
                  default=str)
        rows_path = f.name
    try:
        p = subprocess.Popen(
            [sys.executable, "-c", _ORACLE_CODE, name, DATA, rows_path],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            start_new_session=True)
        try:
            out, _ = p.communicate(timeout=ORACLE_CAP_S)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), 9)
            except (ProcessLookupError, PermissionError):
                p.kill()
            p.communicate()
            return None
        for line in (out or "").splitlines():
            if line.startswith("ORACLE_RESULT:"):
                return json.loads(line[len("ORACLE_RESULT:"):])
        return {"oracle_s": None, "ok": False,
                "error": f"oracle rc={p.returncode} with no result"}
    finally:
        os.unlink(rows_path)


def _sweep_one(name: str) -> dict:
    rec = {"query": name}
    try:
        r = run_benchmark(DATA, 1.0, [name], iterations=3, verify=False,
                          generate=False)[0]
        if "error" in r:
            return {**rec, "ok": False, "error": r["error"]}
        times = sorted(r.get("device_s_all") or [0])
        rec["device_warm_s"] = times[len(times) // 2]
        rec["rows"] = r.get("rows")
        from spark_rapids_tpu.session import TpuSession
        from spark_rapids_tpu.bench.runner import (_collect_rows, _plan_of)
        from spark_rapids_tpu.bench.tpcds_queries import build_query
        s = TpuSession({})
        df = build_query(name, s, DATA)
        device_rows = _collect_rows(df, "device", _plan_of(df))
        orc = _oracle_subprocess(name, device_rows)
        if orc is not None and orc.get("oracle_s") is not None:
            rec["oracle_s"] = orc["oracle_s"]
            rec["ok"] = orc["ok"]
            rec["speedup"] = round(orc["oracle_s"] /
                                   max(rec["device_warm_s"], 1e-9), 2)
        elif orc is not None:
            # CRASHED oracle (not a timeout): record the failure
            # honestly, never as an oracle_capped lower bound
            rec["ok"] = False
            rec["error"] = orc.get("error", "oracle crashed")
        else:
            # cap fired: verify the plan at SF0.1 and report the bound
            small = run_benchmark(DATA_SMALL, 0.1, [name], iterations=1,
                                  verify=True, generate=False)[0]
            rec["ok"] = bool(small.get("ok"))
            rec["oracle_capped"] = ORACLE_CAP_S
            rec["verified_at_sf"] = 0.1
            rec["speedup_lb"] = round(ORACLE_CAP_S /
                                      max(rec["device_warm_s"], 1e-9), 2)
            if "error" in small:
                rec["verify_error"] = small["error"]
    except Exception as e:  # noqa: BLE001 - per-query isolation
        rec["ok"] = False
        rec["error"] = f"{type(e).__name__}: {e}"
    return rec


def main():
    done = {}
    if os.path.exists(CKPT):
        with open(CKPT) as f:
            for line in f:
                r = json.loads(line)
                done[r["query"]] = r
        print(f"resuming: {len(done)} queries already recorded", flush=True)
    queries = sorted(QUERIES, key=lambda q: int(q[1:]))
    t0 = time.time()
    with open(CKPT, "a") as ck:
        for name in queries:
            if name in done:
                continue
            rec = _sweep_one(name)
            ck.write(json.dumps(rec) + "\n")
            ck.flush()
            done[name] = rec
            print(f"{name}: ok={rec.get('ok')} "
                  f"speedup={rec.get('speedup', rec.get('speedup_lb'))}"
                  f"{' (lb)' if 'speedup_lb' in rec else ''}", flush=True)
    recs = [done[q] for q in queries]
    oks = [r for r in recs if r.get("ok")]
    exact = sorted(r["speedup"] for r in oks if "speedup" in r)
    lbs = [r for r in oks if "speedup_lb" in r]
    out = {
        "description": (
            "TPC-DS SF1 sweep, device engine (XLA:CPU backend, warm "
            "persistent in-process compile cache, median of 3 "
            "iterations) vs single-threaded numpy host oracle; 1-core "
            "build VM.  Device==oracle verified per query at SF1; "
            "queries whose SF1 oracle exceeded the "
            f"{ORACLE_CAP_S:.0f}s cap are verified at SF0.1 instead "
            "and report speedup_lb = cap/device (a lower bound, "
            "excluded from median_speedup)."),
        "generated_by": "scripts/sf1_sweep.py (iterations=3, capped "
                        "oracle)",
        "host_cpus": os.cpu_count(),
        "summary": {
            "verified": len(oks), "total": len(QUERIES),
            "oracle_capped": len(lbs),
            "median_speedup": exact[len(exact) // 2] if exact else None,
            "min_speedup": exact[0] if exact else None,
            "max_speedup": exact[-1] if exact else None,
            "min_speedup_lb": min((r["speedup_lb"] for r in lbs),
                                  default=None),
            "wall_s": round(time.time() - t0, 1),
        },
        "queries": recs,
    }
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out["summary"]), flush=True)


if __name__ == "__main__":
    main()

"""Full TPC-DS SF1 verified sweep with per-query checkpointing.

Writes one JSON line per query to the checkpoint as it goes (a crashed
or killed run resumes where it left off) and assembles
bench_results_sf1_cpu.json at the end.  Usage:

    JAX_PLATFORMS=cpu python scripts/sf1_sweep.py [checkpoint.jsonl]
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spark_rapids_tpu.bench.runner import run_benchmark  # noqa: E402
from spark_rapids_tpu.bench.tpcds_queries import QUERIES  # noqa: E402

CKPT = sys.argv[1] if len(sys.argv) > 1 else "/tmp/sf1_sweep_ckpt.jsonl"
DATA = ".bench_data/sf1"
OUT = "bench_results_sf1_cpu.json"


def main():
    done = {}
    if os.path.exists(CKPT):
        with open(CKPT) as f:
            for line in f:
                r = json.loads(line)
                done[r["query"]] = r
        print(f"resuming: {len(done)} queries already recorded",
              flush=True)
    queries = sorted(QUERIES, key=lambda q: int(q[1:]))
    assemble_only = os.environ.get("SWEEP_ASSEMBLE_ONLY") == "1"
    if assemble_only:
        queries = [q for q in queries if q in done]
    t0 = time.time()
    with open(CKPT, "a") as ck:
        for name in queries:
            if name in done:
                continue
            r = run_benchmark(DATA, 1.0, [name], iterations=2,
                              verify=True, generate=False)[0]
            times = r.get("device_s_all") or [0]
            rec = {"query": name, "ok": r.get("ok"),
                   "rows": r.get("rows"),
                   "device_warm_s": min(times),
                   "oracle_s": r.get("oracle_s")}
            if r.get("oracle_s"):
                rec["speedup"] = round(r["oracle_s"] /
                                       max(min(times), 1e-9), 2)
            if "error" in r:
                rec["error"] = r["error"]
            ck.write(json.dumps(rec) + "\n")
            ck.flush()
            done[name] = rec
            print(f"{name}: ok={rec['ok']} "
                  f"speedup={rec.get('speedup')}", flush=True)
    recs = [done[q] for q in queries]
    oks = [r for r in recs if r.get("ok")]
    sp = sorted(r["speedup"] for r in oks if r.get("speedup"))
    out = {
        "description": (
            "TPC-DS SF1 differential sweep, device engine (XLA:CPU "
            "backend, warm persistent compile cache, best of 2 "
            "iterations) vs single-threaded numpy host oracle; 1-core "
            "build VM. Device==oracle verified per query. Queries "
            "missing from this record were cut by the round's wall "
            "clock (the q72-class numpy oracles run >30min each at "
            "SF1), not by failures — SF0.01 verification for all 99 "
            "is artifacts/tpcds_99_sf001_verify.txt."),
        "generated_by": "scripts/sf1_sweep.py (iterations=2, verify)",
        "host_cpus": os.cpu_count(),
        "summary": {"verified": len(oks), "total": len(QUERIES),
                    "median_speedup": sp[len(sp) // 2] if sp else None,
                    "min_speedup": sp[0] if sp else None,
                    "max_speedup": sp[-1] if sp else None,
                    "wall_s": round(time.time() - t0, 1)},
        "queries": recs,
    }
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out["summary"]))


if __name__ == "__main__":
    main()

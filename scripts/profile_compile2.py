"""Sort compile time vs capacity + mitigation probes."""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"  # force-assign: shell pins axon
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")  # env alone cannot stop the axon hook
import jax.numpy as jnp
from jax import lax


def compile_of(f, *args):
    lowered = jax.jit(f).lower(*args)
    t0 = time.time()
    lowered.compile()
    return time.time() - t0


def main():
    for logcap in (10, 14, 16, 18, 20):
        cap = 1 << logcap
        a = jnp.zeros(cap, jnp.int32)
        t = compile_of(lambda x: lax.sort([x], num_keys=1, is_stable=True), a)
        print(f"sort 1op cap=2^{logcap}: {t:.2f}s", flush=True)

    cap = 1 << 18
    a = jnp.zeros(cap, jnp.int32)
    b = jnp.zeros(cap, jnp.int32)
    # is_stable=False
    t = compile_of(lambda x: lax.sort([x], num_keys=1, is_stable=False), a)
    print(f"sort 1op unstable: {t:.2f}s", flush=True)
    # jnp.sort / argsort
    t = compile_of(lambda x: jnp.argsort(x), a)
    print(f"argsort: {t:.2f}s", flush=True)
    # sort_key_val
    t = compile_of(lambda x, y: lax.sort_key_val(x, y), a, b)
    print(f"sort_key_val: {t:.2f}s", flush=True)
    # 2D sort along axis (batch of rows)
    m = jnp.zeros((8, cap // 8), jnp.int32)
    t = compile_of(lambda x: lax.sort(x, dimension=1, is_stable=True), m)
    print(f"sort 2d: {t:.2f}s", flush=True)


if __name__ == "__main__":
    main()

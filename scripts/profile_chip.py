"""On-chip phase profiler for one TPC-DS query (default q6).

Answers VERDICT round-4 item 1: WHERE does the on-chip wall time go?
Breaks a device run into the phases the engine can actually trade
against each other:

  * host decode + staging (arrow -> padded numpy matrices)
  * H2D transfer bytes + seconds (jnp.asarray at batch construction)
  * device compute (everything else inside collect)
  * per-operator totalTime map (inclusive, reference GpuMetricNames)

Usage:  python scripts/profile_chip.py [--sf 1] [--query q6] [--iters 2]
Writes a JSON record to artifacts/profile_chip_<query>_sf<sf>.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# transfer instrumentation: wrap the three DeviceColumn constructors'
# jnp.asarray calls by patching jnp.asarray inside the column module
TRANSFER = {"bytes": 0, "seconds": 0.0, "calls": 0}
PUT = {"bytes": 0, "seconds": 0.0, "calls": 0}
GET = {"seconds": 0.0, "calls": 0}
STAGING = {"seconds": 0.0}


def _instrument():
    import numpy as _np
    import jax
    import jax.numpy as jnp

    real_asarray = jnp.asarray

    def timed_asarray(x, *a, **kw):
        # only time true H2D transfers (host numpy -> device); tracer /
        # device-array passthroughs are not transfers
        if not isinstance(x, (_np.ndarray, _np.generic, int, float, bool)):
            return real_asarray(x, *a, **kw)
        t0 = time.perf_counter()
        out = real_asarray(x, *a, **kw)
        try:
            out.block_until_ready()
        except AttributeError:
            pass
        TRANSFER["seconds"] += time.perf_counter() - t0
        TRANSFER["bytes"] += getattr(out, "nbytes", 0)
        TRANSFER["calls"] += 1
        return out

    jnp.asarray = timed_asarray

    real_put = jax.device_put

    def timed_put(x, *a, **kw):
        t0 = time.perf_counter()
        out = real_put(x, *a, **kw)
        try:
            out.block_until_ready()
        except AttributeError:
            pass
        PUT["seconds"] += time.perf_counter() - t0
        PUT["bytes"] += getattr(out, "nbytes", 0)
        PUT["calls"] += 1
        return out

    jax.device_put = timed_put
    # the pack builder binds jax.device_put at call time (module attr),
    # so patching the jax module attribute covers it

    real_get = jax.device_get

    def timed_get(x, *a, **kw):
        t0 = time.perf_counter()
        out = real_get(x, *a, **kw)
        GET["seconds"] += time.perf_counter() - t0
        GET["calls"] += 1
        return out

    jax.device_get = timed_get

    # staging: time ColumnBatch.from_arrow minus its transfer part
    from spark_rapids_tpu.columnar.batch import ColumnBatch
    real_from_arrow = ColumnBatch.__dict__["from_arrow"].__func__

    def timed_from_arrow(rb, capacity=None, string_widths=None, codec=None):
        t0 = time.perf_counter()
        xfer0 = TRANSFER["seconds"] + PUT["seconds"]
        out = real_from_arrow(rb, capacity, string_widths, codec)
        dt = time.perf_counter() - t0
        STAGING["seconds"] += dt - (TRANSFER["seconds"] + PUT["seconds"]
                                    - xfer0)
        return out

    ColumnBatch.from_arrow = staticmethod(timed_from_arrow)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=1.0)
    ap.add_argument("--query", default="q6")
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--suite", default="tpcds")
    args = ap.parse_args()

    import jax
    from spark_rapids_tpu.runtime import enable_compilation_cache
    enable_compilation_cache()
    backend = jax.default_backend()
    print(f"backend: {backend}", flush=True)

    data_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".bench_data", f"sf{args.sf:g}")
    if args.suite == "tpch":
        from spark_rapids_tpu.bench.tpch_gen import generate_tpch as gen
        from spark_rapids_tpu.bench.tpch_queries import (
            build_tpch_query as build_query)
    else:
        from spark_rapids_tpu.bench.tpcds_gen import generate_tpcds as gen
        from spark_rapids_tpu.bench.tpcds_queries import build_query
    gen(data_dir, sf=args.sf)

    _instrument()

    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.bench.runner import _collect_rows, _plan_of
    session = TpuSession({})
    df = build_query(args.query, session, data_dir)
    plan = _plan_of(df)

    record = {"query": args.query, "sf": args.sf, "backend": backend,
              "iters": []}
    for it in range(args.iters):
        TRANSFER.update(bytes=0, seconds=0.0, calls=0)
        PUT.update(bytes=0, seconds=0.0, calls=0)
        GET.update(seconds=0.0, calls=0)
        STAGING["seconds"] = 0.0
        metrics: dict = {}
        t0 = time.perf_counter()
        rows = _collect_rows(df, "device", plan, metrics_out=metrics)
        wall = time.perf_counter() - t0
        rec = {
            "iter": it, "wall_s": round(wall, 3), "rows": len(rows),
            "h2d_bytes": TRANSFER["bytes"] + PUT["bytes"],
            "h2d_s": round(TRANSFER["seconds"] + PUT["seconds"], 3),
            "h2d_mbps": round((TRANSFER["bytes"] + PUT["bytes"]) / 1e6 /
                              max(TRANSFER["seconds"] + PUT["seconds"],
                                  1e-9), 1),
            "h2d_calls": TRANSFER["calls"] + PUT["calls"],
            "scalar_asarray_calls": TRANSFER["calls"],
            "scalar_asarray_s": round(TRANSFER["seconds"], 3),
            "d2h_calls": GET["calls"],
            "d2h_s": round(GET["seconds"], 3),
            "staging_s": round(STAGING["seconds"], 3),
            "other_s": round(wall - TRANSFER["seconds"] - PUT["seconds"] -
                             GET["seconds"] - STAGING["seconds"], 3),
            "op_totalTime": {k: round(v.get("totalTime", 0.0), 3)
                             for k, v in sorted(metrics.items())},
        }
        record["iters"].append(rec)
        print(json.dumps(rec), flush=True)

    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", f"profile_chip_{args.query}_sf{args.sf:g}.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    print(f"wrote {out}", flush=True)


if __name__ == "__main__":
    main()

#!/bin/bash
# Round-5 tunnel watch: probe the axon TPU tunnel every ~10 min, append one
# line per attempt to artifacts/tpu_probe_r5.log.  Evidence trail per
# VERDICT round-4 item 1 ("if the tunnel stays wedged all round, commit the
# probe log trail"), and a cheap way to notice the moment it comes up.
cd "$(dirname "$0")/.."
LOG=artifacts/tpu_probe_r5.log
mkdir -p artifacts
while true; do
  STAMP=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  if OUT=$(timeout 130 python -c "
import faulthandler
faulthandler.dump_traceback_later(120, exit=True)
import jax
assert jax.default_backend() == 'tpu', jax.default_backend()
print(jax.devices())
" 2>&1); then
    if echo "$OUT" | grep -q "Tpu\|TPU"; then
      echo "$STAMP UP $OUT" >> "$LOG"
      touch artifacts/TPU_UP
    else
      echo "$STAMP odd: $OUT" | head -1 >> "$LOG"
    fi
  else
    RC=$?
    if [ "$RC" -eq 124 ]; then
      echo "$STAMP WEDGED (probe timed out in get_backend)" >> "$LOG"
    else
      # fast nonzero exit = jax initialized but not on the TPU (e.g.
      # a cpu fallback) — responsive environment, NOT a wedge
      echo "$STAMP DOWN rc=$RC: $(echo "$OUT" | tail -1)" >> "$LOG"
    fi
    rm -f artifacts/TPU_UP
  fi
  sleep 600
done

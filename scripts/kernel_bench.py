"""Per-kernel on-chip microbenchmark: times the engine's core kernel
shapes standalone with a true device sync, so the q6 wall time can be
attributed to specific programs (VERDICT round-4 item 1).

Covers the primitives the TPC-DS execution path is built from, at the
scan batch capacity (4M):
  * lax.sort: i32 / (i32,u32) pair / s64 / f32 / f64 keys + payload
  * searchsorted: s64 and i32, 4M probes into 256K sorted keys
  * 1-D gather / scatter-set / segment_sum at 4M
  * s64 / f64 elementwise arithmetic vs 32-bit
  * cumsum i32/s64
Each item reports cold (compile+run) and warm-best-of-2 seconds.

IMPORTANT: block_until_ready() is a no-op over the tunneled backend —
sync is forced by jax.device_get of one output element.

Usage: python scripts/kernel_bench.py [--cap 4194304] [--out FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cap", type=int, default=1 << 22)
    ap.add_argument("--build", type=int, default=1 << 18)
    ap.add_argument("--out", default=None)
    ap.add_argument("--platform", default=None,
                    help="cpu forces XLA:CPU (the axon site hook re-pins "
                         "jax at the tunnel whatever JAX_PLATFORMS says; "
                         "config.update after import is authoritative)")
    args = ap.parse_args()

    import spark_rapids_tpu  # noqa: F401  (x64 config)
    import jax
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        jax.config.update("jax_platforms", args.platform)
    from spark_rapids_tpu.runtime import enable_compilation_cache
    enable_compilation_cache()
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    backend = jax.default_backend()
    print(f"backend: {backend}  cap: {args.cap}", flush=True)
    N, B = args.cap, args.build
    rng = np.random.default_rng(0)

    k64 = rng.integers(0, 1 << 20, N).astype(np.int64)
    k32 = k64.astype(np.int32)
    hi = (k64 >> 32).astype(np.int32)
    lo = (k64 & 0xFFFFFFFF).astype(np.uint32)
    f64 = rng.random(N)
    f32 = f64.astype(np.float32)
    iota = np.arange(N, dtype=np.int32)
    bkeys = np.sort(rng.integers(0, 1 << 20, B).astype(np.int64))
    idx = rng.integers(0, N, N).astype(np.int32)
    seg = np.sort(rng.integers(0, 64, N).astype(np.int32))

    results = []

    def timeit(label, fn, *arrs):
        f = jax.jit(fn)
        dargs = [jnp.asarray(a) for a in arrs]

        def sync(r):
            leaves = jax.tree_util.tree_leaves(r)
            x = leaves[0]
            return jax.device_get(x.ravel()[0] if x.ndim else x)

        t0 = time.perf_counter()
        sync(f(*dargs))
        cold = time.perf_counter() - t0
        ts = []
        for _ in range(2):
            t0 = time.perf_counter()
            sync(f(*dargs))
            ts.append(time.perf_counter() - t0)
        rec = {"label": label, "cold_s": round(cold, 3),
               "warm_s": round(min(ts), 4)}
        results.append(rec)
        print(json.dumps(rec), flush=True)

    timeit("sort_i32_payload", lambda k, i: lax.sort(
        [k, i], num_keys=1, is_stable=True), k32, iota)
    timeit("sort_i32pair_payload", lambda h, l, i: lax.sort(
        [h, l, i], num_keys=2, is_stable=True), hi, lo, iota)
    timeit("sort_s64_payload", lambda k, i: lax.sort(
        [k, i], num_keys=1, is_stable=True), k64, iota)
    timeit("sort_f32_payload", lambda k, i: lax.sort(
        [k, i], num_keys=1, is_stable=True), f32, iota)
    timeit("sort_f64_payload", lambda k, i: lax.sort(
        [k, i], num_keys=1, is_stable=True), f64, iota)
    timeit("searchsorted_s64_4Mx256K", lambda s, q: jnp.searchsorted(
        s, q), bkeys, k64)
    timeit("searchsorted_i32_4Mx256K", lambda s, q: jnp.searchsorted(
        s.astype(jnp.int32), q.astype(jnp.int32)), bkeys, k64)
    timeit("gather1d_i32", lambda d, i: d[i], k32, idx)
    timeit("gather1d_s64", lambda d, i: d[i], k64, idx)
    timeit("gather1d_f64", lambda d, i: d[i], f64, idx)
    timeit("scatter_set_i32", lambda d, i: jnp.zeros(
        N, jnp.int32).at[i].set(d, mode="drop"), k32, idx)
    timeit("segment_sum_i64_capseg", lambda d, s: jax.ops.segment_sum(
        d, s, num_segments=N), k64, seg)
    timeit("segment_sum_i64_64seg", lambda d, s: jax.ops.segment_sum(
        d, s, num_segments=64), k64, seg)
    timeit("cumsum_i32", lambda d: jnp.cumsum(d.astype(jnp.int32)), k32)
    timeit("cumsum_s64", lambda d: jnp.cumsum(d), k64)
    timeit("elemwise_s64", lambda a: (a * 3 + 7) ^ (a >> 5), k64)
    timeit("elemwise_i32", lambda a: (a * 3 + 7) ^ (a >> 5), k32)
    timeit("elemwise_f64", lambda a: a * 1.5 + a * a, f64)
    timeit("elemwise_f32", lambda a: a * 1.5 + a * a, f32)
    timeit("sum_f64", lambda a: jnp.sum(a), f64)
    timeit("where_cmp_s64", lambda a, b: jnp.where(a < b, a, b),
           k64, np.flip(k64).copy())

    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", f"kernel_bench_{backend}.json")
    with open(out, "w") as f:
        json.dump({"backend": backend, "cap": N, "results": results}, f,
                  indent=1)
    print(f"wrote {out}", flush=True)


if __name__ == "__main__":
    main()

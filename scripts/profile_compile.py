"""Isolate WHY sorted_group_by costs ~38s to compile on XLA:CPU.

Times jit.lower() and lowered.compile() for progressively simpler programs at
one capacity, to find the compile hog (suspect: variadic lax.sort).
"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"  # force-assign: shell pins axon
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")  # env alone cannot stop the axon hook
import jax.numpy as jnp
from jax import lax
import numpy as np

CAP = 1 << 18


def timeit(name, fn):
    t0 = time.time()
    out = fn()
    print(f"{name}: {time.time()-t0:.2f}s", flush=True)
    return out


def compile_of(f, *args):
    lowered = jax.jit(f).lower(*args)
    n_lines = len(lowered.as_text().splitlines())
    t0 = time.time()
    lowered.compile()
    return time.time() - t0, n_lines


def main():
    i64 = jnp.zeros(CAP, jnp.int64)
    i32 = jnp.zeros(CAP, jnp.int32)
    f64 = jnp.zeros(CAP, jnp.float64)
    u8 = jnp.zeros(CAP, jnp.uint8)
    strmat = jnp.zeros((CAP, 16), jnp.uint8)

    # 1. single-key sort
    t, n = compile_of(lambda a: lax.sort([a], num_keys=1, is_stable=True), i64)
    print(f"sort 1 op (i64): {t:.2f}s ({n} hlo lines)", flush=True)

    # 2. two-operand sort (key + payload)
    t, n = compile_of(lambda a, b: lax.sort([a, b], num_keys=1, is_stable=True), i64, i32)
    print(f"sort 2 ops key=1: {t:.2f}s ({n})", flush=True)

    # 3. variadic sort, 4 keys
    t, n = compile_of(lambda a, b, c, d, e: lax.sort([a, b, c, d, e], num_keys=4, is_stable=True),
                      u8, i64, u8, f64, i32)
    print(f"sort 5 ops key=4: {t:.2f}s ({n})", flush=True)

    # 4. variadic sort, 8 keys (string-ish)
    ops = [u8] + [i32] * 6 + [i32]
    t, n = compile_of(lambda *a: lax.sort(list(a), num_keys=7, is_stable=True), *ops)
    print(f"sort 8 ops key=7: {t:.2f}s ({n})", flush=True)

    # 5. segment_sum alone
    t, n = compile_of(lambda x, s: jax.ops.segment_sum(x, s, num_segments=CAP), i64, i32)
    print(f"segment_sum: {t:.2f}s ({n})", flush=True)

    # 6. scatter .at[].set
    t, n = compile_of(lambda x, p: jnp.zeros(CAP, jnp.int64).at[p].set(x, mode="drop"), i64, i32)
    print(f"scatter set: {t:.2f}s ({n})", flush=True)

    # 7. the real sorted_group_by
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import ColumnBatch
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.ops.segmented import AggSpec, sorted_group_by

    key = DeviceColumn(i32, jnp.ones(CAP, jnp.bool_), T.IntegerType())
    val = DeviceColumn(f64, jnp.ones(CAP, jnp.bool_), T.DoubleType())
    schema = T.Schema([T.StructField("k", T.IntegerType()), T.StructField("v", T.DoubleType())])
    batch = ColumnBatch([key, val], jnp.asarray(CAP, jnp.int32), schema)

    def gb(b):
        return sorted_group_by(b, [0], [AggSpec("sum", 1), AggSpec("count", 1)])

    t, n = compile_of(gb, batch)
    print(f"sorted_group_by int key: {t:.2f}s ({n})", flush=True)

    # 8. group-by with a string key
    skey = DeviceColumn(strmat, jnp.ones(CAP, jnp.bool_), T.StringType(), i32)
    schema2 = T.Schema([T.StructField("k", T.StringType()), T.StructField("v", T.DoubleType())])
    batch2 = ColumnBatch([skey, val], jnp.asarray(CAP, jnp.int32), schema2)
    t, n = compile_of(gb, batch2)
    print(f"sorted_group_by str key: {t:.2f}s ({n})", flush=True)


if __name__ == "__main__":
    main()

"""Regenerate tests/api_surface.json — the public-API snapshot.

Reference: api_validation/ (ApiValidation.scala:26-60) reflection-diffs
each Gpu exec's constructor against its Spark counterpart to catch API
drift.  Standalone analog: snapshot the engine's own public surface
(conf keys, exec constructor signatures, expression registry, DataFrame
methods) so accidental drift fails a test and intentional change is an
explicit regeneration of this file.
"""
import inspect
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def collect_surface() -> dict:
    import importlib
    import pkgutil

    import spark_rapids_tpu
    from spark_rapids_tpu.conf import registered_entries
    from spark_rapids_tpu.exec.core import PlanNode
    from spark_rapids_tpu.expr.core import Expression
    from spark_rapids_tpu.session import DataFrame, TpuSession

    for m in pkgutil.walk_packages(spark_rapids_tpu.__path__,
                                   "spark_rapids_tpu."):
        if "._native" in m.name:
            continue
        try:
            importlib.import_module(m.name)
        except ImportError:
            pass

    def subclasses(base):
        out = {}
        for c in _walk_subclasses(base):
            try:
                sig = str(inspect.signature(c.__init__))
            except (TypeError, ValueError):
                sig = "?"
            out[f"{c.__module__}.{c.__name__}"] = sig
        return dict(sorted(out.items()))

    def methods(cls):
        return sorted(n for n, v in vars(cls).items()
                      if not n.startswith("_") and callable(v)
                      or isinstance(v, property) and not n.startswith("_"))

    return {
        "conf_keys": sorted(registered_entries()),
        "execs": subclasses(PlanNode),
        "expressions": sorted(
            f"{c.__module__}.{c.__name__}"
            for c in _walk_subclasses(Expression)),
        "dataframe_methods": methods(DataFrame),
        "session_methods": methods(TpuSession),
    }


def _walk_subclasses(base):
    seen = set()
    stack = list(base.__subclasses__())
    while stack:
        c = stack.pop()
        if c in seen or not c.__module__.startswith("spark_rapids_tpu"):
            continue
        seen.add(c)
        stack.extend(c.__subclasses__())
        yield c


if __name__ == "__main__":
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "api_surface.json")
    with open(out, "w") as f:
        json.dump(collect_surface(), f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {out}")

"""Validate observability JSON artifacts against ci/obs_schema.json.

Hand-rolled validator for the dependency-free subset of JSON Schema the
checked-in schema uses (type / required / properties / items / enum /
additionalProperties-as-schema, with list-form ``type`` for nullables) —
the CI image carries no jsonschema package, and the gate must not grow a
dependency just to check its own output.

Usage:
    python scripts/validate_obs.py <trace|metrics|bundle|history|histogram|profile> <file.json> ...

Exit 0 when every file validates; 1 with a path-qualified error line per
violation otherwise.  Also importable: ``validate(instance, schema)``
returns a list of error strings.
"""
import json
import os
import sys

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
    # bool is an int subclass in Python; excluded explicitly below
    "integer": int,
    "number": (int, float),
    "null": type(None),
}


def _type_ok(instance, t: str) -> bool:
    if not isinstance(instance, _TYPES[t]):
        return False
    if t in ("integer", "number") and isinstance(instance, bool):
        return False
    return True


def validate(instance, schema: dict, path: str = "$") -> list[str]:
    """Errors (empty = valid) for ``instance`` against the schema subset."""
    errs: list[str] = []
    t = schema.get("type")
    if t is not None:
        # list form means "any of these": the nullable-field idiom
        # ("type": ["number", "null"]) used by the history schema
        names = t if isinstance(t, list) else [t]
        if not any(_type_ok(instance, n) for n in names):
            errs.append(f"{path}: expected {'/'.join(names)}, "
                        f"got {type(instance).__name__}")
            return errs  # child checks would only cascade
    if "enum" in schema and instance not in schema["enum"]:
        errs.append(f"{path}: {instance!r} not in {schema['enum']}")
    if isinstance(instance, dict):
        for key in schema.get("required", ()):
            if key not in instance:
                errs.append(f"{path}: missing required key {key!r}")
        props = schema.get("properties", {})
        for key, sub in props.items():
            if key in instance:
                errs.extend(validate(instance[key], sub, f"{path}.{key}"))
        # schema-valued additionalProperties constrains every key NOT
        # named in properties (the open-keyed histogram maps); the
        # boolean form is not used by obs_schema.json and is ignored
        extra = schema.get("additionalProperties")
        if isinstance(extra, dict):
            for key, val in instance.items():
                if key not in props:
                    errs.extend(validate(val, extra, f"{path}.{key}"))
    if isinstance(instance, list) and "items" in schema:
        for i, item in enumerate(instance):
            errs.extend(validate(item, schema["items"], f"{path}[{i}]"))
    return errs


def load_schema(kind: str) -> dict:
    schema_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ci", "obs_schema.json")
    with open(schema_path) as f:
        schemas = json.load(f)
    if kind not in schemas or kind.startswith("_"):
        raise SystemExit(f"unknown schema kind {kind!r}; "
                         f"want one of {[k for k in schemas if not k.startswith('_')]}")
    return schemas[kind]


def validate_file(kind: str, path: str) -> list[str]:
    with open(path) as f:
        return validate(json.load(f), load_schema(kind))


def main(argv: list[str]) -> int:
    if len(argv) < 2:
        print(__doc__)
        return 2
    kind, files = argv[0], argv[1:]
    bad = 0
    for p in files:
        errs = validate_file(kind, p)
        if errs:
            bad += 1
            for e in errs[:20]:
                print(f"{p}: {e}")
            if len(errs) > 20:
                print(f"{p}: ... {len(errs) - 20} more")
        else:
            print(f"{p}: ok ({kind})")
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))

"""Benchmark: TPC-DS-q6-shaped columnar step, device vs CPU oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Always prints that line, even on failure: ALL device work (backend init
AND the timed runs) happens on a daemon worker thread under a deadline,
so a tunnel hang at any point still yields a JSON line (the reference
treats init failure as fail-fast, Plugin.scala:146-153). A small smoke
size runs first; if only the smoke size completes, the line is labeled
with the smoke row count — a smoke number is never reported under the
full-size metric name.

The tracked north star (BASELINE.json) is >=4x speedup over CPU Spark on
TPC-DS; this bench measures the framework's hot path (scan-resident
filter -> group-by aggregate, SURVEY.md §3.3) on the device vs the
single-threaded CPU oracle engine on identical data, so
vs_baseline = speedup / 4.0. (Oracle is NOT CPU Spark — interim proxy.)
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback

import numpy as np

INIT_TIMEOUT_S = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "180"))
TOTAL_TIMEOUT_S = float(os.environ.get("BENCH_TOTAL_TIMEOUT_S", "600"))
SMOKE_ROWS = 1 << 16
FULL_ROWS = 1 << 20


def _metric_name(rows: int) -> str:
    tag = "1M" if rows == FULL_ROWS else f"{rows // 1024}k"
    return f"q6like_filter_groupby_speedup_vs_cpu_oracle_{tag}_rows"


def _emit(value: float, rows: int, error: str | None = None):
    rec = {
        "metric": _metric_name(rows),
        "value": round(float(value), 3),
        "unit": "x",
        "vs_baseline": round(float(value) / 4.0, 3),
    }
    if error:
        rec["error"] = error[:500]
    print(json.dumps(rec))
    sys.stdout.flush()


def _run_size(n: int) -> float:
    """Run the q6-shaped step at n rows; return device-vs-oracle speedup."""
    import jax
    from __graft_entry__ import SCHEMA, _SPECS, _make_host_batch, \
        _q6_condition, query_step
    from spark_rapids_tpu.expr.core import bind, eval_host
    from spark_rapids_tpu.ops.host_kernels import host_filter, host_group_by

    # host data first, uploaded once; never device_get the device inputs —
    # under the axon tunnel a fetched array degrades later executions to a
    # re-upload per call.
    hb = _make_host_batch(n, seed=3)
    batch = hb.to_device(capacity=n)

    step = jax.jit(query_step)
    out = step(batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(out))  # compile+warm
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = step(batch)
        jax.block_until_ready(jax.tree_util.tree_leaves(out))
        times.append(time.perf_counter() - t0)
    dev_t = float(np.median(times))

    cond = bind(_q6_condition(), SCHEMA)

    def host_step(b):
        c = eval_host(cond, b)
        kept = host_filter(b, c.data.astype(bool) & c.validity)
        return host_group_by(kept, [0], list(_SPECS))

    h0 = time.perf_counter()
    hout = host_step(hb)
    host_t = time.perf_counter() - h0

    assert hout.num_rows == out.host_num_rows(), \
        (hout.num_rows, out.host_num_rows())
    return host_t / dev_t


def main() -> None:
    state: dict = {}

    def _work():
        try:
            import jax
            jax.devices()
            state["init"] = True
            state["smoke"] = _run_size(SMOKE_ROWS)
            state["full"] = _run_size(FULL_ROWS)
        except BaseException as e:  # noqa: BLE001 - reported via JSON line
            state["error"] = \
                f"{type(e).__name__}: {e} | {traceback.format_exc(limit=3)}"

    t = threading.Thread(target=_work, daemon=True)
    t.start()
    t.join(INIT_TIMEOUT_S)
    if t.is_alive() and "init" not in state:
        _emit(0.0, FULL_ROWS,
              error=f"jax backend init did not return in {INIT_TIMEOUT_S}s")
        os._exit(1)
    t.join(max(0.0, TOTAL_TIMEOUT_S - INIT_TIMEOUT_S))
    hung = t.is_alive()
    err = state.get("error")
    if hung:
        err = (err or "") + f" benchmark exceeded {TOTAL_TIMEOUT_S}s deadline"
    if "full" in state:
        _emit(state["full"], FULL_ROWS, error=err)
        rc = 0
    elif "smoke" in state:
        _emit(state["smoke"], SMOKE_ROWS,
              error=err or "full-size run did not complete")
        rc = 0
    else:
        _emit(0.0, FULL_ROWS, error=err or "no result")
        rc = 1
    # worker thread may still hold native state; exit hard so a hung
    # atexit teardown can't eat the already-printed JSON line.
    os._exit(rc)


if __name__ == "__main__":
    main()

"""Benchmark: TPC-DS q6 (BASELINE configs[0]) device vs CPU oracle.

Prints one JSON line per metric:
  {"metric": "tpcds_q6_sf..._speedup_vs_cpu_oracle", "value": N, ...}
  {"metric": "tpch_multichip_scaling_sf...", "value": N, "ladder": [...]}
  {"metric": "tpch_cluster_scaling_sf...", "value": N, "ladder": [...]}
  {"metric": "tpch_multistream_qph_sf...", "value": N, "ladder": [...]}
  {"metric": "tpch_storm_p99_slo_sf...", "value": N, "report": {...}}

The cluster line is the driver/worker runtime ladder
(spark_rapids_tpu/cluster): q6 + q3 at 1/2/4 local worker processes
(spark.rapids.cluster.mode=local[N]) with map-side shuffle work
sharded over the pool and per-worker registry deltas in each rung's
observability block.

The third line is the serving-tier THROUGHPUT ladder
(spark_rapids_tpu/bench/throughput.py): N ∈ {1,2,4,8} concurrent
tenant streams through ONE session, distinct query permutations per
stream, warm queries-per-hour per rung with cache-hit and fairness
counters, every stream's rows verified against the host oracle.

The storm line is the CONTROL-PLANE rung
(spark_rapids_tpu/bench/storm.py): web/etl/batch tenants share one
bottlenecked session; every fixed admission configuration in a
maxConcurrent x workers grid misses at least one self-calibrated p99
SLO, while the closed loop (spark.rapids.control.enabled=true) meets
the served tenants' SLOs by shedding exactly the storm tenant.  value
= min(slo/p99) over served tenants in the closed-loop run.

The second line is the pod-scale device-count ladder: TPC-H q6, q3,
q13 and q18 at 1/2/4/8 mesh devices
(spark.rapids.tpu.mesh.deviceCount), wall time and scaling efficiency
t1/(n*tn) per rung — q13/q18 exercise shard-resident multi-join
regions, not just scan->filter->agg.  Setting
SPARK_RAPIDS_BENCH_MESH_DEVICES=N additionally runs the PRIMARY q6
ladder itself over an N-device mesh, so a multichip harness run stops
reporting healthy-but-idle devices.

Runs a scale-factor ladder (SF0.1 smoke -> SF1 -> SF10) of TPC-DS q6
through the real engine (parquet scan -> joins -> filter -> group-by ->
having -> sort -> limit, spark_rapids_tpu.bench.runner), verifying each
rung against the host oracle.  The emitted line is the LARGEST rung that
completed, labeled with its scale factor — a smoke number is never
reported under a bigger-SF metric name.

Robustness (round-1 failure mode: the tunneled TPU backend can hang
indefinitely inside PJRT init or any device call, and a hung thread
cannot be killed): every rung runs in its OWN subprocess under a
deadline, so a wedged backend is killed, not waited on.  If no rung
completes on the TPU backend at all, the ladder re-runs on the CPU
backend and the result is honestly labeled `backend: "cpu_fallback"` —
a real measurement of the same engine is better evidence than a zero.
(The reference treats executor init failure as fail-fast-and-relaunch,
Plugin.scala:146-153; the relaunch analog here is the fallback ladder.)

vs_baseline = speedup / 4.0 against BASELINE.json's >=4x-vs-CPU-Spark
target.  The oracle is this repo's single-threaded numpy engine, NOT
CPU Spark — an interim proxy, stated in the metric name.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TOTAL_TIMEOUT_S = float(os.environ.get("BENCH_TOTAL_TIMEOUT_S", "540"))
# reserved for the CPU-fallback ladder while the TPU ladder has not yet
# produced a single successful rung
FALLBACK_RESERVE_S = float(os.environ.get("BENCH_FALLBACK_RESERVE_S", "200"))
# quick backend-liveness probe budget: a wedged tunnel hangs jax.devices()
# forever inside PJRT client creation, so spending ~1 min here saves the
# whole rung timeout (round-3 failure mode: 360s burned discovering the
# hang, leaving no budget for a labeled-honest CPU ladder)
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "75"))
MAX_SF = float(os.environ.get("BENCH_SF", "10"))
DATA_DIR = os.environ.get("BENCH_DATA_DIR",
                          os.path.join(os.path.dirname(
                              os.path.abspath(__file__)), ".bench_data"))
# smoke rung is SF0.1, the smallest scale where q6 produces result rows —
# a 0-row "device == oracle" comparison verifies nothing (round-2 verdict)
LADDER = [sf for sf in (0.1, 1.0, 10.0) if sf <= MAX_SF] or [0.1]

# pod-scale knob: when set (>1) every bench rung runs the engine over an
# n-device mesh (spark.rapids.tpu.mesh.deviceCount=n), so a multichip
# harness run stops reporting healthy-but-IDLE devices — the devices it
# probes are the devices the measured plan executes on
MESH_DEVICES = int(os.environ.get("SPARK_RAPIDS_BENCH_MESH_DEVICES", "0")
                   or "0")
# device-count scaling ladder (MULTICHIP metric): q6 + q3 + q13 + q18 at
# 1/2/4/8 devices, wall time and scaling efficiency per rung — q13/q18
# keep multi-join pipelines (joins absorbed into mesh regions) honest
MULTICHIP_QUERIES = ("q6", "q3", "q13", "q18")
MULTICHIP_LADDER = tuple(
    int(x) for x in os.environ.get("BENCH_MULTICHIP_LADDER",
                                   "1,2,4,8").split(",") if x.strip())
MULTICHIP_SF = float(os.environ.get("BENCH_MULTICHIP_SF", "0.1"))
MULTICHIP_TIMEOUT_S = float(os.environ.get("BENCH_MULTICHIP_TIMEOUT_S",
                                           "420"))
# multi-stream THROUGHPUT ladder (serving-tier metric): N concurrent
# tenant streams through one session, queries-per-hour per rung, warm
# (result cache + compile cache primed), per-stream oracle-verified
THROUGHPUT_SF = float(os.environ.get("BENCH_THROUGHPUT_SF", "0.1"))
THROUGHPUT_STREAMS = tuple(
    int(x) for x in os.environ.get("BENCH_THROUGHPUT_STREAMS",
                                   "1,2,4,8").split(",") if x.strip())
THROUGHPUT_QUERIES = ("q3", "q13", "q18")
THROUGHPUT_TIMEOUT_S = float(os.environ.get("BENCH_THROUGHPUT_TIMEOUT_S",
                                            "420"))
# cluster-runtime worker ladder (CLUSTER metric): q6 + q3 at 1/2/4
# local worker subprocesses over the DCN shuffle plane
# (spark.rapids.cluster.mode=local[N]).  Always measured on the CPU
# backend: co-tenant worker processes cannot share one exclusively-held
# TPU, so a CPU ladder is the honest shape measurement.
CLUSTER_QUERIES = ("q6", "q3")
CLUSTER_LADDER = tuple(
    int(x) for x in os.environ.get("BENCH_CLUSTER_LADDER",
                                   "1,2,4").split(",") if x.strip())
CLUSTER_SF = float(os.environ.get("BENCH_CLUSTER_SF", "0.05"))
CLUSTER_TIMEOUT_S = float(os.environ.get("BENCH_CLUSTER_TIMEOUT_S", "420"))
# transactional CTAS write rung (WRITE metric): a q6-shaped CTAS
# (lineitem under q6's filter, hive-partitioned by l_returnflag)
# through the two-phase commit protocol (io/writer.py) — clean run for
# the throughput number, then an io.write.* fault storm and a cluster
# worker-death run, each of which must reproduce the clean run's
# read-back row hash exactly.  CPU backend, like the cluster ladder.
WRITE_SF = float(os.environ.get("BENCH_WRITE_SF", "0.1"))
WRITE_TIMEOUT_S = float(os.environ.get("BENCH_WRITE_TIMEOUT_S", "300"))
# mixed-tenant STORM rung (control-plane metric): web/etl/batch tenants
# share one bottlenecked session; a fixed admission grid is swept with
# the control plane OFF, then the closed loop runs with it ON.  value =
# min(slo/p99) over the served tenants in the closed-loop run (>1 means
# every served SLO met, with margin) — and the report carries the whole
# grid, so the claim "no fixed config serves what the closed loop
# serves" is inspectable.  CPU backend: admission/SLO dynamics are
# host-side, like the cluster ladder.
STORM_SF = float(os.environ.get("BENCH_STORM_SF", "0.01"))
STORM_DURATION_S = float(os.environ.get("BENCH_STORM_DURATION_S", "5"))
STORM_TIMEOUT_S = float(os.environ.get("BENCH_STORM_TIMEOUT_S", "420"))


def _mesh_env(n_devices: int) -> dict:
    """Child env forcing n virtual host devices (idempotent append)."""
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count=" not in flags:
        env["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={n_devices}").strip()
    return env


def _emit(value: float, sf: float, backend: str, error: str | None = None,
          extra: dict | None = None):
    name = f"tpcds_q6_sf{sf:g}_speedup_vs_cpu_oracle"
    if backend != "tpu":
        name += f"_{backend}"
    rec = {
        "metric": name,
        "value": round(float(value), 3),
        "unit": "x",
        "vs_baseline": round(float(value) / 4.0, 3),
    }
    if extra:
        rec.update(extra)
    if error:
        rec["error"] = str(error)[:500]
    print(json.dumps(rec))
    sys.stdout.flush()


_REPORT_PREFIX = "BENCH_REPORT:"


def _probe_backend(platform: str, timeout_s: float,
                   env: dict | None = None) -> tuple[bool, str]:
    """Cheaply check the backend can initialize at all.

    Runs ``jax.devices()`` plus one tiny device computation in a killable
    subprocess with a faulthandler watchdog.  A wedged axon tunnel hangs
    inside ``make_c_api_client`` — that stack signature (when present) is
    returned in the detail string so the emitted artifact records WHY the
    TPU ladder was skipped, not just that it was.
    """
    watchdog = max(5.0, timeout_s - 10.0)
    code = (
        "import faulthandler, os, sys\n"
        f"faulthandler.dump_traceback_later({watchdog:.0f}, exit=True)\n"
        "import jax\n"
    )
    if platform == "cpu":
        code += ("os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                 "jax.config.update('jax_platforms', 'cpu')\n")
    code += (
        "ds = jax.devices()\n"
        "import jax.numpy as jnp\n"
        "x = jnp.arange(8); x.block_until_ready()\n"
        "print('PROBE_OK', ds[0].platform, len(ds), flush=True)\n"
        "os._exit(0)\n"
    )
    kw = {"env": env} if env else {}
    rc, out, errout = _run_killable([sys.executable, "-c", code], timeout_s,
                                    **kw)
    out = (out or "") + (errout or "")
    if rc is None:
        # even in the kill path, scan the drained output: the watchdog
        # dump may already name the wedged frame
        if "make_c_api_client" in out:
            return False, ("tunnel wedged: jax.devices() hung in "
                           "make_c_api_client (killed by probe timeout)")
        return False, f"probe killed after {timeout_s:.0f}s (no traceback)"
    for line in out.splitlines():
        if line.startswith("PROBE_OK"):
            parts = line.split()
            got = parts[1] if len(parts) > 1 else "?"
            want_cpu = platform == "cpu"
            if want_cpu != (got == "cpu"):
                return False, f"probe initialized '{got}' not '{platform}'"
            return True, f"backend '{got}' x{parts[2] if len(parts) > 2 else '?'}"
    if "make_c_api_client" in out:
        return False, ("tunnel wedged: jax.devices() hung in "
                       "make_c_api_client (watchdog fired)")
    tail = out.strip().splitlines()[-1][:200] if out.strip() else "no output"
    return False, f"probe rc={rc}: {tail}"


def _run_killable(cmd: list[str], timeout_s: float,
                  **popen_kw) -> tuple[int | None, str, str]:
    """Spawn ``cmd`` in its own session and wait up to ``timeout_s``.

    On timeout the whole process GROUP is killed (wedged PJRT/tunnel
    helper children die with it instead of holding the TPU connection
    and the stdout pipe forever) and whatever output was produced is
    still drained and returned.  Returns (returncode|None-if-killed,
    stdout, stderr)."""
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True,
                         start_new_session=True, **popen_kw)
    try:
        out, errout = p.communicate(timeout=timeout_s)
        return p.returncode, out or "", errout or ""
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(p.pid), 9)
        except (ProcessLookupError, PermissionError):
            p.kill()
        try:
            out, errout = p.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            out, errout = "", ""
        return None, out or "", errout or ""


def _run_rung(sf: float, platform: str, timeout_s: float) -> dict:
    """One ladder rung in a killable subprocess; returns its JSON report
    or {"error": ...}."""
    cmd = [sys.executable, os.path.abspath(__file__),
           "--child", str(sf), platform]
    kw = {}
    if MESH_DEVICES > 1 and platform == "cpu":
        # the mesh needs the virtual devices to exist before jax inits
        kw["env"] = _mesh_env(MESH_DEVICES)
    rc, out, errout = _run_killable(
        cmd, timeout_s,
        cwd=os.path.dirname(os.path.abspath(__file__)) or None, **kw)
    if rc is None:
        return {"error": f"rung sf{sf:g}/{platform} killed after "
                         f"{timeout_s:.0f}s (backend hang)"}
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith(_REPORT_PREFIX):
            try:
                return json.loads(line[len(_REPORT_PREFIX):])
            except json.JSONDecodeError:
                break
    tail = (errout or "")[-300:].replace("\n", " | ")
    return {"error": f"rung sf{sf:g}/{platform} exited rc={rc} "
                     f"with no report; stderr tail: {tail}"}


def _scenario_pass(sf: float, session_conf, aqe: bool) -> list:
    """One q13+q18 scenario sweep, static or adaptive.  The adaptive
    pass records the per-query aqe_* counter movement from the runner's
    observability block so the artifact shows what the re-optimizer
    actually did (broadcast switches, coalesced/split partitions,
    dynamic filters), not just the wall time."""
    from spark_rapids_tpu.bench.runner import run_benchmark
    conf = dict(session_conf or {})
    if aqe:
        conf["spark.sql.adaptive.shuffledHashJoin.enabled"] = True
    out = []
    srs = run_benchmark(
        os.path.join(DATA_DIR, f"tpch_sf{sf:g}"), sf,
        ["q13", "q18"], iterations=1, verify=True, suite="tpch",
        session_conf=conf or None)
    for sr in srs:
        row = {
            "suite": "tpch", "query": sr.get("query"),
            "kind": ("string_heavy" if sr.get("query") == "q13"
                     else "high_skew"),
            "adaptive": aqe,
            "ok": bool(sr.get("ok")) and not sr.get("error"),
            "speedup": sr.get("speedup"),
            "device_s": sr.get("device_s"),
            "oracle_s": sr.get("oracle_s"),
            "rows": sr.get("rows"),
        }
        if aqe:
            counters = (sr.get("observability", {})
                        .get("registry", {}).get("counters", {}))
            row["aqe"] = {k: v for k, v in counters.items()
                          if k.startswith("aqe_")}
        # memory-governor movement for this query: reclaim/grant/shed
        # counters plus the per-query peak-bytes gauges (the registry
        # delta is captured while the query's ExecCtx is still open, so
        # its governor.q.<qid>.* gauges are present)
        reg = sr.get("observability", {}).get("registry", {})
        gov = {k: v for k, v in reg.get("counters", {}).items()
               if k.startswith("governor_")}
        gov.update({k: v for k, v in reg.get("gauges", {}).items()
                    if k.startswith("governor.q.")
                    and k.endswith("peak_bytes")})
        if gov:
            row["governor"] = gov
        out.append(row)
    return out


def _child(sf: float, platform: str) -> None:
    """Run one rung in-process and print its report as the last line."""
    import jax
    if platform == "cpu":
        # the axon sitecustomize re-pins jax at the tunneled TPU whatever
        # JAX_PLATFORMS says in the environment; config.update after
        # import is the authoritative override
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.runtime import enable_compilation_cache
    enable_compilation_cache()
    backend = jax.default_backend()
    # jax can silently fall back to CPU when accelerator init FAILS fast
    # (vs hanging), and the sitecustomize can re-pin a cpu request at the
    # TPU — neither mislabeling is acceptable in the emitted metric
    if (platform == "tpu") != (backend != "cpu"):
        print(_REPORT_PREFIX + json.dumps(
            {"ok": False,
             "error": f"requested {platform} but jax initialized "
                      f"'{backend}'"}), flush=True)
        os._exit(1)
    from spark_rapids_tpu.bench.runner import run_benchmark
    # pod-scale: when SPARK_RAPIDS_BENCH_MESH_DEVICES is set the rung's
    # plan runs sharded over the mesh — but only if that many devices
    # actually exist; a silent 1-device "mesh" run would mislabel the
    # metric, so the shortfall is recorded instead
    session_conf = None
    mesh_note = None
    if MESH_DEVICES > 1:
        have = len(jax.devices())
        if have >= MESH_DEVICES:
            session_conf = {"spark.rapids.tpu.mesh.deviceCount":
                            MESH_DEVICES}
        else:
            mesh_note = (f"requested mesh x{MESH_DEVICES} but only "
                         f"{have} devices; ran single-device")
    # 3 iterations at every SF: the median discards the one-time
    # executable-cache load that dominates iteration 0, at the cost of
    # ~2 extra warm runs — the per-rung subprocess budget (not an
    # iteration count) is what bounds a slow backend here
    reports = run_benchmark(os.path.join(DATA_DIR, f"sf{sf:g}"), sf, ["q6"],
                            iterations=3, verify=True,
                            session_conf=session_conf)
    r = reports[0]
    if session_conf is not None:
        r["mesh_devices"] = MESH_DEVICES
    if mesh_note:
        r["mesh_note"] = mesh_note
    if r.get("ok") and r.get("rows", 0) <= 0:
        r["ok"] = False
        r["error"] = "query produced 0 rows"
    # scenario-diversity rider (ROADMAP): one string-heavy and one
    # high-skew query alongside q6, so fusion/compile wins aren't
    # measured on arithmetic-only plans.  TPC-H q13 is LIKE-dominated
    # (o_comment scan) and q18 concentrates on heavy-order keys.
    # Small SFs only, and never fatal to the rung: the q6 ladder metric
    # stays the gate, the scenarios ride along in the artifact.
    if r.get("ok") and sf <= 1:
        scenarios = []
        try:
            scenarios += _scenario_pass(sf, session_conf, aqe=False)
            # AQE on/off A-B on the same rungs: q13's string-heavy plan
            # and q18's skewed orderkeys are exactly where the
            # re-optimizer should move the aqe_* counters, and rows must
            # stay identical to the static pass either way
            scenarios += _scenario_pass(sf, session_conf, aqe=True)
        except Exception as e:  # pragma: no cover - rider must not gate
            scenarios.append({"error": str(e)[:300]})
        r["scenarios"] = scenarios
    print(_REPORT_PREFIX + json.dumps(r))
    sys.stdout.flush()
    # a wedged PJRT teardown must not eat the already-printed report
    os._exit(0)


def _mchild(n_devices: int, platform: str) -> None:
    """One MULTICHIP rung: q6 + q3 + q13 + q18 (TPC-H) on an n-device
    mesh.

    Prints a BENCH_REPORT line with per-query wall times.  The parent
    forces ``--xla_force_host_platform_device_count`` in this child's
    env for the virtual-CPU ladder, so jax must not initialize before
    that takes effect (it already has: env is set pre-spawn)."""
    import jax
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.runtime import enable_compilation_cache
    enable_compilation_cache()
    have = len(jax.devices())
    if have < n_devices:
        print(_REPORT_PREFIX + json.dumps(
            {"ok": False, "error": f"need {n_devices} devices, have {have}"}),
            flush=True)
        os._exit(1)
    from spark_rapids_tpu.bench.runner import run_benchmark
    conf = ({"spark.rapids.tpu.mesh.deviceCount": n_devices}
            if n_devices > 1 else None)
    sf = MULTICHIP_SF
    reports = run_benchmark(
        os.path.join(DATA_DIR, f"tpch_sf{sf:g}"), sf,
        list(MULTICHIP_QUERIES), iterations=3, verify=True, suite="tpch",
        session_conf=conf)
    out = {"ok": True, "devices": n_devices, "queries": {}}
    for r in reports:
        q = r.get("query")
        qr = {"ok": bool(r.get("ok")) and not r.get("error"),
              "wall_s": r.get("device_s"), "rows": r.get("rows")}
        if r.get("error"):
            qr["error"] = str(r["error"])[:300]
        out["queries"][q] = qr
        out["ok"] = out["ok"] and qr["ok"]
    print(_REPORT_PREFIX + json.dumps(out))
    sys.stdout.flush()
    os._exit(0)


def _split_tpch_tables(data_dir: str, tables, parts: int) -> None:
    """Re-write each table as ``parts`` parquet files so its scan is
    multi-partition and the plans above it contain real shuffle
    exchanges for the cluster runtime to shard (a 1-file sf0.1 scan
    plans as a single complete aggregation with nothing to
    distribute)."""
    import pyarrow.parquet as pq
    for table in tables:
        d = os.path.join(data_dir, table)
        have = [f for f in os.listdir(d) if f.endswith(".parquet")]
        if len(have) >= parts:
            continue
        t = pq.read_table(os.path.join(d, "part-0.parquet"))
        step = -(-t.num_rows // parts)
        for i in range(parts):
            pq.write_table(t.slice(i * step, step),
                           os.path.join(d, f"part-{i}.parquet"))


def _cchild(n_workers: int, platform: str) -> None:
    """One CLUSTER rung: q6 + q3 (TPC-H) over a local[N] worker pool.

    Prints a BENCH_REPORT line with per-query wall times plus the
    cluster's registry movement and per-worker heartbeat deltas."""
    import jax
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.runtime import enable_compilation_cache
    enable_compilation_cache()
    from spark_rapids_tpu.bench.runner import run_benchmark
    from spark_rapids_tpu.bench.tpch_gen import generate_tpch
    sf = CLUSTER_SF
    data = os.path.join(DATA_DIR, f"tpch_cluster_sf{sf:g}")
    generate_tpch(data, sf=sf)
    _split_tpch_tables(data, ("lineitem", "orders", "customer"), 4)
    conf = {"spark.rapids.cluster.mode": f"local[{n_workers}]"}
    reports = run_benchmark(data, sf, list(CLUSTER_QUERIES), iterations=2,
                            verify=True, suite="tpch", generate=False,
                            session_conf=conf)
    out = {"ok": True, "workers": n_workers, "queries": {}}
    for r in reports:
        q = r.get("query")
        obs = r.get("observability") or {}
        reg = (obs.get("registry") or {}).get("counters") or {}
        qr = {"ok": bool(r.get("ok")) and not r.get("error"),
              "wall_s": r.get("device_s"), "rows": r.get("rows"),
              "cluster": {k: v for k, v in reg.items()
                          if k.startswith("cluster")},
              "worker_deltas": obs.get("cluster_workers")}
        if r.get("error"):
            qr["error"] = str(r["error"])[:300]
        out["queries"][q] = qr
        out["ok"] = out["ok"] and qr["ok"]
    print(_REPORT_PREFIX + json.dumps(out))
    sys.stdout.flush()
    os._exit(0)


def _wchild(platform: str) -> None:
    """One CTAS write rung: q6-shaped CTAS, clean + chaos, in one
    killable child.  Prints a BENCH_REPORT line with the clean write's
    wall/rows/bytes plus each chaos variant's hash verdict."""
    import datetime
    import hashlib
    import shutil
    import tempfile

    import jax
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.runtime import enable_compilation_cache
    enable_compilation_cache()
    from spark_rapids_tpu.bench.tpch_gen import generate_tpch
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.obs.registry import get_registry
    from spark_rapids_tpu.session import TpuSession
    sf = WRITE_SF
    data = os.path.join(DATA_DIR, f"tpch_write_sf{sf:g}")
    generate_tpch(data, sf=sf)
    _split_tpch_tables(data, ("lineitem",), 4)

    def ctas(conf, out):
        sess = TpuSession(conf)
        try:
            li = sess.read_parquet(
                os.path.join(data, "lineitem"),
                columns=["l_returnflag", "l_extendedprice", "l_discount",
                         "l_shipdate", "l_quantity"])
            q6ish = li.where(
                (col("l_shipdate") >= lit(datetime.date(1994, 1, 1)))
                & (col("l_shipdate") < lit(datetime.date(1995, 1, 1)))
                & (col("l_discount") >= lit(0.05))
                & (col("l_discount") <= lit(0.07))
                & (col("l_quantity") < lit(24.0)))
            t0 = time.perf_counter()
            stats = q6ish.write_parquet(out,
                                        partition_by=["l_returnflag"])
            return stats, time.perf_counter() - t0
        finally:
            sess.shutdown()

    def row_hash(out):
        import pyarrow.dataset as ds
        t = ds.dataset(out, format="parquet",
                       partitioning="hive").to_table()
        t = t.select(sorted(t.column_names))
        rows = sorted(zip(*(t.column(n).to_pylist()
                            for n in t.column_names)), key=str)
        h = hashlib.sha256()
        for r in rows:
            h.update(repr(r).encode())
        return h.hexdigest()

    base = tempfile.mkdtemp()
    clean_out = os.path.join(base, "clean")
    stats, wall = ctas({}, clean_out)
    want = row_hash(clean_out)
    out = {"ok": True, "sf": sf, "rows": stats.num_rows,
           "files": stats.num_files, "bytes": stats.num_bytes,
           "clean_wall_s": round(wall, 4),
           "rows_per_s": round(stats.num_rows / max(wall, 1e-9), 1),
           "read_back_hash": want[:16], "chaos": {}}
    storms = {
        "fault_storm": {"spark.rapids.test.faults":
                        "io.write.partial:crash,times=2;"
                        "io.write.commit.drop:drop,times=1;"
                        "io.write.rename.fail:fail,times=1"},
        "worker_death": {"spark.rapids.cluster.mode": "local[2]",
                         "spark.rapids.test.faults":
                         "cluster.worker.dead:dead,worker=w1,"
                         "seconds=0.02,times=1"},
    }
    for name, conf in storms.items():
        cdir = os.path.join(base, name)
        before = get_registry().snapshot()
        try:
            _, cwall = ctas(conf, cdir)
            delta = get_registry().delta(before)["counters"]
            injected = sum(v for k, v in delta.items()
                           if k.startswith("faults.injected."))
            exact = row_hash(cdir) == want
            out["chaos"][name] = {
                "ok": exact and injected > 0, "exact": exact,
                "faults_injected": injected, "wall_s": round(cwall, 4)}
        except Exception as e:  # pragma: no cover - reported, not raised
            out["chaos"][name] = {"ok": False, "error": str(e)[:300]}
        out["ok"] = out["ok"] and out["chaos"][name]["ok"]
    shutil.rmtree(base, ignore_errors=True)
    print(_REPORT_PREFIX + json.dumps(out))
    sys.stdout.flush()
    os._exit(0)


def _emit_write(rep: dict | None, error) -> None:
    rec = {
        "metric": f"tpch_ctas_write_sf{WRITE_SF:g}_cpu",
        "value": float((rep or {}).get("rows_per_s") or 0.0),
        "unit": "rows/s",
        "report": rep or {},
    }
    if error:
        rec["error"] = str(error)[:500]
    print(json.dumps(rec))
    sys.stdout.flush()


def _write_rung(deadline: float) -> None:
    """Fourth metric line: the transactional CTAS write rung, its own
    killable subprocess like every other ladder."""
    budget = min(WRITE_TIMEOUT_S, deadline - time.monotonic())
    if budget < 30:
        _emit_write(None, "no budget for write rung")
        return
    cmd = [sys.executable, os.path.abspath(__file__), "--wchild", "cpu"]
    rc, out, errout = _run_killable(
        cmd, budget,
        cwd=os.path.dirname(os.path.abspath(__file__)) or None)
    if rc is None:
        _emit_write(None, f"write rung killed after {budget:.0f}s")
        return
    rep = None
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith(_REPORT_PREFIX):
            try:
                rep = json.loads(line[len(_REPORT_PREFIX):])
            except json.JSONDecodeError:
                pass
            break
    if rep is None:
        tail = (errout or "")[-300:].replace("\n", " | ")
        _emit_write(None, f"write rung rc={rc} no report; {tail}")
        return
    _emit_write(rep, None if rep.get("ok") else "write rung not exact")


def _schild(platform: str) -> None:
    """One killable mixed-tenant storm run: the whole grid plus the
    closed loop live in one child so every rung shares one warm
    compile cache and the comparison is apples-to-apples."""
    import jax
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.runtime import enable_compilation_cache
    enable_compilation_cache()
    from spark_rapids_tpu.bench.storm import run_storm
    sf = STORM_SF
    rep = run_storm(os.path.join(DATA_DIR, f"tpch_sf{sf:g}"), sf,
                    duration_s=STORM_DURATION_S)
    print(_REPORT_PREFIX + json.dumps(rep))
    sys.stdout.flush()
    os._exit(0)


def _storm_rung(deadline: float) -> None:
    """Fifth metric line: the mixed-tenant storm — does the closed
    control loop serve SLOs that no fixed configuration can?"""
    rec = {
        "metric": f"tpch_storm_p99_slo_sf{STORM_SF:g}_cpu",
        "value": 0.0,
        "unit": "x",
    }
    budget = min(STORM_TIMEOUT_S, deadline - time.monotonic())
    if budget < 60:
        rec["error"] = "no budget for storm rung"
        print(json.dumps(rec))
        sys.stdout.flush()
        return
    cmd = [sys.executable, os.path.abspath(__file__), "--schild", "cpu"]
    rc, out, errout = _run_killable(
        cmd, budget,
        cwd=os.path.dirname(os.path.abspath(__file__)) or None)
    rep = None
    if rc is not None:
        for line in reversed(out.splitlines()):
            line = line.strip()
            if line.startswith(_REPORT_PREFIX):
                try:
                    rep = json.loads(line[len(_REPORT_PREFIX):])
                except json.JSONDecodeError:
                    pass
                break
    if rep is None:
        tail = (errout or "")[-300:].replace("\n", " | ")
        rec["error"] = (f"storm rung killed after {budget:.0f}s"
                        if rc is None else
                        f"storm rung rc={rc} no report; {tail}")
        print(json.dumps(rec))
        sys.stdout.flush()
        return
    rec["value"] = float(rep.get("closed_slo_margin") or 0.0)
    rec["ok"] = bool(rep.get("ok"))
    rec["report"] = rep
    if rep.get("error"):
        rec["error"] = str(rep["error"])[:500]
    print(json.dumps(rec))
    sys.stdout.flush()


def _tchild(platform: str) -> None:
    """One killable multi-stream throughput run (the whole ladder lives
    in one child: rungs share the warm session-level caches, which is
    the point of the measurement)."""
    import jax
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.runtime import enable_compilation_cache
    enable_compilation_cache()
    from spark_rapids_tpu.bench.throughput import run_throughput
    sf = THROUGHPUT_SF
    rep = run_throughput(os.path.join(DATA_DIR, f"tpch_sf{sf:g}"), sf,
                         streams=THROUGHPUT_STREAMS,
                         queries=THROUGHPUT_QUERIES, suite="tpch")
    print(_REPORT_PREFIX + json.dumps(rep))
    sys.stdout.flush()
    os._exit(0)


def _throughput(deadline: float, tpu_probe_ok: bool) -> None:
    """Third metric line: the multi-stream throughput ladder.

    value = warm queries-per-hour at the LARGEST verified stream count;
    the rung list carries the whole curve plus cache-hit and fairness
    counter movement, and ``scaling_4v1`` pins the acceptance shape
    (4-stream warm throughput vs 1-stream)."""
    platform = "tpu" if tpu_probe_ok else "cpu"
    budget = min(THROUGHPUT_TIMEOUT_S, deadline - time.monotonic())
    rec = {
        "metric": f"tpch_multistream_qph_sf{THROUGHPUT_SF:g}_{platform}",
        "value": 0.0,
        "unit": "queries/hour",
        "streams": list(THROUGHPUT_STREAMS),
        "queries": list(THROUGHPUT_QUERIES),
    }
    if budget < 45:
        rec["error"] = "no budget for throughput ladder"
        print(json.dumps(rec))
        sys.stdout.flush()
        return
    cmd = [sys.executable, os.path.abspath(__file__), "--tchild", platform]
    rc, out, errout = _run_killable(
        cmd, budget,
        cwd=os.path.dirname(os.path.abspath(__file__)) or None)
    rep = None
    if rc is not None:
        for line in reversed(out.splitlines()):
            line = line.strip()
            if line.startswith(_REPORT_PREFIX):
                try:
                    rep = json.loads(line[len(_REPORT_PREFIX):])
                except json.JSONDecodeError:
                    pass
                break
    if rep is None:
        tail = (errout or "")[-300:].replace("\n", " | ")
        rec["error"] = (f"throughput run killed after {budget:.0f}s"
                        if rc is None else
                        f"throughput run rc={rc} no report; {tail}")
        print(json.dumps(rec))
        sys.stdout.flush()
        return
    rungs = rep.get("streams", [])
    qph = {r["streams"]: r for r in rungs
           if r.get("qph") and not r.get("errors")
           and not r.get("mismatches")}
    if qph:
        top = max(qph)
        rec["value"] = qph[top]["qph"]
        rec["streams_at_value"] = top
        if 1 in qph and 4 in qph and qph[1]["qph"] > 0:
            rec["scaling_4v1"] = round(qph[4]["qph"] / qph[1]["qph"], 3)
    rec["ok"] = bool(rep.get("ok"))
    rec["qph_cold_1stream"] = rep.get("qph_cold_1stream")
    rec["ladder"] = rungs
    if rep.get("error"):
        rec["error"] = str(rep["error"])[:500]
    print(json.dumps(rec))
    sys.stdout.flush()


def _emit_multichip(rungs: list, backend: str, error: str | None) -> None:
    """Second metric line: the MULTICHIP device-count scaling ladder.

    value = q3 scaling speedup t(1)/t(n) at the largest completed rung;
    every rung carries its wall times and efficiency t1/(n*tn) so the
    artifact shows the whole curve, not one point."""
    base = {}     # query -> t(1)
    for r in rungs:
        if r.get("devices") == 1 and r.get("ok"):
            for q, qr in r.get("queries", {}).items():
                if qr.get("ok") and qr.get("wall_s"):
                    base[q] = qr["wall_s"]
    value = 0.0
    top = 0
    for r in rungs:
        n = r.get("devices", 0)
        for q, qr in r.get("queries", {}).items():
            t = qr.get("wall_s")
            if qr.get("ok") and t and q in base:
                qr["speedup_vs_1dev"] = round(base[q] / t, 3)
                qr["efficiency"] = round(base[q] / (n * t), 3)
        q3 = r.get("queries", {}).get("q3", {})
        if r.get("ok") and n > top and "speedup_vs_1dev" in q3:
            top, value = n, q3["speedup_vs_1dev"]
    rec = {
        "metric": f"tpch_multichip_scaling_sf{MULTICHIP_SF:g}_{backend}",
        "value": round(float(value), 3),
        "unit": "x",
        "devices": top,
        "queries": list(MULTICHIP_QUERIES),
        "ladder": rungs,
    }
    if error:
        rec["error"] = str(error)[:500]
    print(json.dumps(rec))
    sys.stdout.flush()


def _multichip(deadline: float, tpu_probe_detail: str) -> None:
    """Climb the device-count ladder and emit the MULTICHIP metric line.

    Real multi-device TPU hardware is used when the probe saw >=2
    devices; otherwise the ladder runs on virtual CPU devices (honestly
    labeled ``cpu_virtual``) — scaling SHAPE is still meaningful there
    because the per-device programs and collectives are identical."""
    m = None
    for tok in tpu_probe_detail.split():
        if tok.startswith("x") and tok[1:].isdigit():
            m = int(tok[1:])
    max_n = max(MULTICHIP_LADDER)
    if m is not None and m >= 2:
        platform, backend = "tpu", "tpu"
        env = None
    else:
        platform, backend = "cpu", "cpu_virtual"
        env = _mesh_env(max_n)
    rungs: list[dict] = []
    err = None
    for n in MULTICHIP_LADDER:
        budget = min(MULTICHIP_TIMEOUT_S, deadline - time.monotonic())
        if budget < 45:
            err = (err or "") + f" (no budget for x{n})"
            break
        if platform == "tpu" and n > (m or 1):
            rungs.append({"devices": n, "ok": False,
                          "error": f"only {m} tpu devices"})
            continue
        cmd = [sys.executable, os.path.abspath(__file__),
               "--mchild", str(n), platform]
        kw = {"env": env} if env else {}
        rc, out, errout = _run_killable(
            cmd, budget,
            cwd=os.path.dirname(os.path.abspath(__file__)) or None, **kw)
        r = {"error": f"rung x{n} killed after {budget:.0f}s"} \
            if rc is None else None
        if r is None:
            for line in reversed(out.splitlines()):
                line = line.strip()
                if line.startswith(_REPORT_PREFIX):
                    try:
                        r = json.loads(line[len(_REPORT_PREFIX):])
                    except json.JSONDecodeError:
                        pass
                    break
            if r is None:
                tail = (errout or "")[-300:].replace("\n", " | ")
                r = {"error": f"rung x{n} rc={rc} no report; {tail}"}
        r.setdefault("devices", n)
        r.setdefault("ok", False)
        rungs.append(r)
        if not r["ok"]:
            err = r.get("error") or f"x{n} failed"
    _emit_multichip(rungs, backend, err)


def _emit_cluster(rungs: list, backend: str, error) -> None:
    base: dict = {}
    for r in rungs:
        if r.get("workers") == 1 and r.get("ok"):
            for q, qr in r.get("queries", {}).items():
                if qr.get("ok") and qr.get("wall_s"):
                    base[q] = qr["wall_s"]
    value = 0.0
    top = 0
    for r in rungs:
        n = r.get("workers", 0)
        for q, qr in r.get("queries", {}).items():
            t = qr.get("wall_s")
            if qr.get("ok") and t and q in base:
                qr["speedup_vs_1worker"] = round(base[q] / t, 3)
                qr["efficiency"] = round(base[q] / (n * t), 3)
        q3 = r.get("queries", {}).get("q3", {})
        if r.get("ok") and n > top and "speedup_vs_1worker" in q3:
            top, value = n, q3["speedup_vs_1worker"]
    rec = {
        "metric": f"tpch_cluster_scaling_sf{CLUSTER_SF:g}_{backend}",
        "value": round(float(value), 3),
        "unit": "x",
        "workers": top,
        "queries": list(CLUSTER_QUERIES),
        "ladder": rungs,
    }
    if error:
        rec["error"] = str(error)[:500]
    print(json.dumps(rec))
    sys.stdout.flush()


def _cluster_scaling(deadline: float) -> None:
    """Climb the worker-count ladder (local[1] -> local[2] -> local[4])
    and emit the CLUSTER metric line.  Each rung is its own killable
    subprocess — a wedged worker pool is killed, not waited on — and
    every query is oracle-verified, so a scaling number can never come
    from wrong rows."""
    rungs: list[dict] = []
    err = None
    for n in CLUSTER_LADDER:
        budget = min(CLUSTER_TIMEOUT_S, deadline - time.monotonic())
        if budget < 45:
            err = (err or "") + f" (no budget for {n} workers)"
            break
        cmd = [sys.executable, os.path.abspath(__file__),
               "--cchild", str(n), "cpu"]
        rc, out, errout = _run_killable(
            cmd, budget,
            cwd=os.path.dirname(os.path.abspath(__file__)) or None)
        r = {"error": f"rung {n}w killed after {budget:.0f}s"} \
            if rc is None else None
        if r is None:
            for line in reversed(out.splitlines()):
                line = line.strip()
                if line.startswith(_REPORT_PREFIX):
                    try:
                        r = json.loads(line[len(_REPORT_PREFIX):])
                    except json.JSONDecodeError:
                        pass
                    break
            if r is None:
                tail = (errout or "")[-300:].replace("\n", " | ")
                r = {"error": f"rung {n}w rc={rc} no report; {tail}"}
        r.setdefault("workers", n)
        r.setdefault("ok", False)
        rungs.append(r)
        if not r["ok"]:
            err = r.get("error") or f"{n} workers failed"
    _emit_cluster(rungs, "cpu", err)


def _ladder(platform: str, deadline: float, reserve: float, rungs: list):
    """Climb the ladder on one backend; returns ((sf, report) | None,
    err).  Every rung attempt (pass or fail) is appended to ``rungs`` so
    the emitted artifact shows the partial ladder, not just the summit."""
    best = None
    err = None
    for sf in LADDER:
        budget = deadline - time.monotonic() - (reserve if best is None
                                                else 0.0)
        if budget < 45:
            err = (err or "") + f" (no budget for sf{sf:g})"
            break
        r = _run_rung(sf, platform, budget)
        rung = {"sf": sf, "backend": platform,
                "ok": bool(r.get("ok")) and not r.get("error")}
        for k in ("speedup", "device_s", "oracle_s", "rows", "scenarios"):
            if k in r:
                rung[k] = r[k]
        if r.get("error"):
            rung["error"] = str(r["error"])[:300]
        rungs.append(rung)
        if rung["ok"]:
            best = (sf, r)
        else:
            err = r.get("error") or f"sf{sf:g}: device != oracle"
            break
    return best, err


def _prewarm(sf: float) -> None:
    """Resumable compile-cache warmer: run the engine once on the TPU at
    a small SF purely to populate the persistent XLA executable cache
    (~/.cache/spark_rapids_tpu/xla), so a later bench run measures
    execution instead of compilation.  Safe to re-run; each invocation
    adds whatever entries the previous one didn't reach before being
    killed.  Exits 0 if the rung completed, 1 otherwise."""
    ok, detail = _probe_backend("tpu", PROBE_TIMEOUT_S)
    print(f"prewarm: tpu probe: {detail}", file=sys.stderr)
    if not ok:
        sys.exit(1)
    budget = TOTAL_TIMEOUT_S
    r = _run_rung(sf, "tpu", budget)
    print(f"prewarm: rung sf{sf:g} -> "
          f"{'ok' if r.get('ok') else r.get('error')}", file=sys.stderr)
    sys.exit(0 if r.get("ok") else 1)


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        _child(float(sys.argv[2]), sys.argv[3])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--mchild":
        _mchild(int(sys.argv[2]), sys.argv[3])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--cchild":
        _cchild(int(sys.argv[2]), sys.argv[3])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--tchild":
        _tchild(sys.argv[2])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--wchild":
        _wchild(sys.argv[2])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--schild":
        _schild(sys.argv[2])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--prewarm":
        _prewarm(float(sys.argv[2]) if len(sys.argv) > 2 else 0.1)
        return
    deadline = time.monotonic() + TOTAL_TIMEOUT_S
    # cap the reserve so a small total budget still attempts the TPU
    # ladder instead of silently skipping straight to the fallback
    reserve = min(FALLBACK_RESERVE_S, TOTAL_TIMEOUT_S / 3.0)
    rungs: list[dict] = []
    probe_ok, probe_detail = _probe_backend("tpu", PROBE_TIMEOUT_S)
    if MESH_DEVICES > 1 and not probe_ok:
        # the mesh ladder will run on virtual CPU devices: record the
        # device width it will ACTUALLY use (xN), not the dead tunnel's
        mok, mdetail = _probe_backend("cpu", PROBE_TIMEOUT_S,
                                      env=_mesh_env(MESH_DEVICES))
        probe_detail += f" ; mesh cpu probe: {mdetail}"
    if probe_ok:
        best, err = _ladder("tpu", deadline, reserve, rungs)
    else:
        # don't burn a full rung timeout on a backend that can't even
        # enumerate devices — skip straight to the honest fallback
        best, err = None, f"tpu probe failed: {probe_detail}"
    backend = "tpu"
    if best is None:
        tpu_err = err
        best, err = _ladder("cpu", deadline, 0.0, rungs)
        backend = "cpu_fallback"
        err = f"tpu ladder failed: {tpu_err}" + (f" ; {err}" if err else "")
    extra = {"ladder": rungs, "tpu_probe": probe_detail}
    if MESH_DEVICES > 1:
        extra["mesh_devices"] = MESH_DEVICES
    rc = 0
    if best is not None:
        sf, r = best
        extra.update({"device_s": r.get("device_s"),
                      "oracle_s": r.get("oracle_s"),
                      "rows": r.get("rows")})
        if r.get("scenarios"):
            extra["scenarios"] = r["scenarios"]
        _emit(r.get("speedup", 0.0), sf, backend, error=err, extra=extra)
    else:
        _emit(0.0, LADDER[0], backend, error=err or "no rung completed",
              extra=extra)
        rc = 1
    # second metric line: the pod-scale device-count ladder (q6 + q3 +
    # q13 + q18 at 1/2/4/8 devices).  Runs after the primary metric so a
    # wedged mesh rung can never eat the gate number.
    mc_deadline = time.monotonic() + MULTICHIP_TIMEOUT_S
    try:
        _multichip(mc_deadline, probe_detail)
    except Exception as e:  # pragma: no cover - rider must not gate
        _emit_multichip([], "none", f"multichip ladder crashed: {e}")
    # cluster-runtime worker ladder (q6 + q3 at local[1]/[2]/[4]):
    # runs after the primary metric so a wedged worker pool can never
    # eat the gate number
    c_deadline = time.monotonic() + CLUSTER_TIMEOUT_S
    try:
        _cluster_scaling(c_deadline)
    except Exception as e:  # pragma: no cover - rider must not gate
        _emit_cluster([], "none", f"cluster ladder crashed: {e}")
    # third metric line: the multi-stream serving-tier throughput ladder
    # (queries-per-hour at 1/2/4/8 concurrent tenant streams, warm)
    t_deadline = time.monotonic() + THROUGHPUT_TIMEOUT_S
    try:
        _throughput(t_deadline, probe_ok)
    except Exception as e:  # pragma: no cover - rider must not gate
        print(json.dumps({
            "metric": f"tpch_multistream_qph_sf{THROUGHPUT_SF:g}_none",
            "value": 0.0, "unit": "queries/hour",
            "error": f"throughput ladder crashed: {e}"}))
        sys.stdout.flush()
    # fourth metric line: the transactional CTAS write rung (clean
    # throughput + fault-storm/worker-death exactness)
    w_deadline = time.monotonic() + WRITE_TIMEOUT_S
    try:
        _write_rung(w_deadline)
    except Exception as e:  # pragma: no cover - rider must not gate
        _emit_write(None, f"write rung crashed: {e}")
    # fifth metric line: the mixed-tenant storm — the closed control
    # loop vs a fixed admission grid under the same self-calibrated SLOs
    s_deadline = time.monotonic() + STORM_TIMEOUT_S
    try:
        _storm_rung(s_deadline)
    except Exception as e:  # pragma: no cover - rider must not gate
        print(json.dumps({
            "metric": f"tpch_storm_p99_slo_sf{STORM_SF:g}_cpu",
            "value": 0.0, "unit": "x",
            "error": f"storm rung crashed: {e}"}))
        sys.stdout.flush()
    sys.exit(rc)


if __name__ == "__main__":
    main()

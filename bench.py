"""Benchmark: TPC-DS-q6-shaped columnar step, device vs CPU oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The tracked north star (BASELINE.json) is >=4x speedup over CPU Spark on
TPC-DS; this bench measures the framework's hot path (scan-resident
filter -> group-by aggregate, SURVEY.md §3.3) on the device vs the
single-threaded CPU oracle engine on identical data, so
vs_baseline = speedup / 4.0.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main() -> None:
    import jax
    from __graft_entry__ import SCHEMA, _SPECS, _make_host_batch, \
        _q6_condition, query_step
    from spark_rapids_tpu.expr.core import bind, eval_host
    from spark_rapids_tpu.ops.host_kernels import host_filter, host_group_by

    n = 1 << 20
    cap = 1 << 20
    # host data first, uploaded once; never device_get the device inputs —
    # under the axon tunnel a fetched array degrades later executions to a
    # re-upload per call.
    hb = _make_host_batch(n, seed=3)
    batch = hb.to_device(capacity=cap)

    # --- device path (jitted, steady-state) ---------------------------
    step = jax.jit(query_step)
    out = step(batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(out))  # compile+warm
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = step(batch)
        jax.block_until_ready(jax.tree_util.tree_leaves(out))
        times.append(time.perf_counter() - t0)
    dev_t = float(np.median(times))

    # --- CPU oracle ---------------------------------------------------
    cond = bind(_q6_condition(), SCHEMA)

    def host_step(b):
        c = eval_host(cond, b)
        kept = host_filter(b, c.data.astype(bool) & c.validity)
        return host_group_by(kept, [0], list(_SPECS))

    h0 = time.perf_counter()
    hout = host_step(hb)
    host_t = time.perf_counter() - h0

    # sanity: same group count
    assert hout.num_rows == out.host_num_rows(), \
        (hout.num_rows, out.host_num_rows())

    speedup = host_t / dev_t
    print(json.dumps({
        "metric": "q6like_filter_groupby_speedup_vs_cpu_oracle_1M_rows",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup / 4.0, 3),
    }))


if __name__ == "__main__":
    main()

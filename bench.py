"""Benchmark: TPC-DS q6 (BASELINE configs[0]) device vs CPU oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Runs a scale-factor ladder (SF0.01 smoke -> SF1 -> SF10) of TPC-DS q6
through the real engine (parquet scan -> joins -> filter -> group-by ->
having -> sort -> limit, spark_rapids_tpu.bench.runner), verifying each
rung against the host oracle.  The emitted line is the LARGEST rung that
completed, labeled with its scale factor — a smoke number is never
reported under a bigger-SF metric name.

Robustness (round-1 failure mode: tunnel hang): ALL device work runs on
a daemon worker thread under init/total deadlines, so a JSON line is
always printed (the reference treats init failure as fail-fast,
Plugin.scala:146-153).

vs_baseline = speedup / 4.0 against BASELINE.json's >=4x-vs-CPU-Spark
target.  The oracle is this repo's single-threaded numpy engine, NOT
CPU Spark — an interim proxy, stated in the metric name.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback

INIT_TIMEOUT_S = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "180"))
TOTAL_TIMEOUT_S = float(os.environ.get("BENCH_TOTAL_TIMEOUT_S", "540"))
MAX_SF = float(os.environ.get("BENCH_SF", "10"))
DATA_DIR = os.environ.get("BENCH_DATA_DIR",
                          os.path.join(os.path.dirname(
                              os.path.abspath(__file__)), ".bench_data"))
# smoke rung is SF0.1, the smallest scale where q6 produces result rows —
# a 0-row "device == oracle" comparison verifies nothing (round-2 verdict)
LADDER = [sf for sf in (0.1, 1.0, 10.0) if sf <= MAX_SF] or [0.1]


def _emit(value: float, sf: float, error: str | None = None,
          extra: dict | None = None):
    rec = {
        "metric": f"tpcds_q6_sf{sf:g}_speedup_vs_cpu_oracle",
        "value": round(float(value), 3),
        "unit": "x",
        "vs_baseline": round(float(value) / 4.0, 3),
    }
    if extra:
        rec.update(extra)
    if error:
        rec["error"] = str(error)[:500]
    print(json.dumps(rec))
    sys.stdout.flush()


def main() -> None:
    state: dict = {}

    def _work():
        try:
            from spark_rapids_tpu.runtime import enable_compilation_cache
            enable_compilation_cache()
            import jax
            jax.devices()
            state["init"] = True
            from spark_rapids_tpu.bench.runner import run_benchmark
            for sf in LADDER:
                iters = 3 if sf <= 1 else 1
                reports = run_benchmark(
                    os.path.join(DATA_DIR, f"sf{sf:g}"), sf, ["q6"],
                    iterations=iters, verify=True)
                r = reports[0]
                if "error" in r:
                    state["error"] = f"sf{sf:g}: {r['error']}"
                    break
                if not r.get("ok", False):
                    state["error"] = f"sf{sf:g}: device != oracle"
                    break
                if r.get("rows", 0) <= 0:
                    state["error"] = f"sf{sf:g}: query produced 0 rows"
                    break
                state["best"] = (sf, r)
        except BaseException as e:  # noqa: BLE001 - reported via JSON line
            state["error"] = \
                f"{type(e).__name__}: {e} | {traceback.format_exc(limit=3)}"

    t = threading.Thread(target=_work, daemon=True)
    t.start()
    t.join(INIT_TIMEOUT_S)
    if t.is_alive() and "init" not in state:
        _emit(0.0, LADDER[-1],
              error=f"jax backend init did not return in {INIT_TIMEOUT_S}s")
        os._exit(1)
    t.join(max(0.0, TOTAL_TIMEOUT_S - INIT_TIMEOUT_S))
    err = state.get("error")
    if t.is_alive():
        err = (err or "") + f" deadline {TOTAL_TIMEOUT_S}s exceeded"
    if "best" in state:
        sf, r = state["best"]
        _emit(r.get("speedup", 0.0), sf, error=err,
              extra={"device_s": r.get("device_s"),
                     "oracle_s": r.get("oracle_s"),
                     "rows": r.get("rows")})
        rc = 0
    else:
        _emit(0.0, LADDER[0], error=err or "no rung completed")
        rc = 1
    # worker thread may still hold native state; exit hard so a hung
    # atexit teardown can't eat the already-printed JSON line.
    os._exit(rc)


if __name__ == "__main__":
    main()

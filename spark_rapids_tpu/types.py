"""SQL data types and the Spark<->Arrow<->jax dtype mapping.

Mirrors the type surface the reference supports on GPU (reference
sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java:172-187:
bool/byte/short/int/long/float/double/date/timestamp/string).  Decimal and
nested types are not supported by the reference v0.3 plugin and are likewise
unsupported here (they tag as will-not-work and fall back to CPU).
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "DataType", "BooleanType", "ByteType", "ShortType", "IntegerType",
    "LongType", "FloatType", "DoubleType", "StringType", "DateType",
    "TimestampType", "NullType", "all_types", "from_arrow", "to_arrow",
]


class DataType:
    """Base class for SQL data types. Instances are singletons."""

    #: numpy dtype of the physical device representation (None for STRING).
    np_dtype: np.dtype | None = None
    #: short name used in schemas / explain output
    name: str = "datatype"
    #: True for int8/16/32/64
    integral: bool = False
    #: True for float32/64
    fractional: bool = False

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @property
    def numeric(self) -> bool:
        return self.integral or self.fractional

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return self.name

    def __eq__(self, other) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self))


class BooleanType(DataType):
    np_dtype = np.dtype(np.bool_)
    name = "boolean"


class ByteType(DataType):
    np_dtype = np.dtype(np.int8)
    name = "byte"
    integral = True


class ShortType(DataType):
    np_dtype = np.dtype(np.int16)
    name = "short"
    integral = True


class IntegerType(DataType):
    np_dtype = np.dtype(np.int32)
    name = "int"
    integral = True


class LongType(DataType):
    np_dtype = np.dtype(np.int64)
    name = "long"
    integral = True


class FloatType(DataType):
    np_dtype = np.dtype(np.float32)
    name = "float"
    fractional = True


class DoubleType(DataType):
    np_dtype = np.dtype(np.float64)
    name = "double"
    fractional = True


class StringType(DataType):
    # device repr: padded uint8 byte matrix + int32 lengths (see columnar/column.py)
    np_dtype = None
    name = "string"


class DateType(DataType):
    """Days since unix epoch, int32 (Arrow date32)."""
    np_dtype = np.dtype(np.int32)
    name = "date"


class TimestampType(DataType):
    """Microseconds since unix epoch, int64 (Arrow timestamp[us], like Spark)."""
    np_dtype = np.dtype(np.int64)
    name = "timestamp"


class NullType(DataType):
    np_dtype = np.dtype(np.bool_)
    name = "null"


class MapType(DataType):
    """Map columns (reference GetMapValue, complexTypeExtractors).

    HOST-ONLY: maps have no device representation here (two aligned
    var-width buffers per row do not fit the single-matrix column
    layout), so the planner tags every operator whose schema carries a
    map as host — the reference's own degradation model for
    unsupported types (RapidsMeta.willNotWorkOnGpu).  Host rows hold
    python dicts."""

    name = "map"
    np_dtype = None

    def __new__(cls, key_type: DataType, value_type: DataType):
        return object.__new__(cls)

    def __init__(self, key_type: DataType, value_type: DataType):
        self.key_type = key_type
        self.value_type = value_type

    def __repr__(self) -> str:
        return f"map<{self.key_type!r},{self.value_type!r}>"

    def __eq__(self, other) -> bool:
        return (type(self) is type(other)
                and self.key_type == other.key_type
                and self.value_type == other.value_type)

    def __hash__(self) -> int:
        return hash((MapType, self.key_type, self.value_type))


class ArrayType(DataType):
    """Array of fixed-width elements (reference: cuDF LIST columns used
    by complexTypeExtractors / GetArrayItem).  Device layout mirrors
    strings: padded ``[capacity, max_len]`` element matrix + int32
    lengths — static shapes for XLA, power-of-two-bucketed widths.
    Element nulls are not modeled (Spark arrays may hold nulls; such
    data stays on the host scan path)."""

    name = "array"

    def __new__(cls, element_type: DataType):
        # parameterized: NOT a singleton like the scalar types
        self = object.__new__(cls)
        return self

    def __init__(self, element_type: DataType):
        assert element_type.numeric or isinstance(
            element_type, (BooleanType, DateType, TimestampType)), \
            f"device arrays need fixed-width elements, got {element_type}"
        self.element_type = element_type
        self.np_dtype = element_type.np_dtype

    def __repr__(self) -> str:
        return f"array<{self.element_type!r}>"

    def __eq__(self, other) -> bool:
        return (type(self) is type(other)
                and self.element_type == other.element_type)

    def __hash__(self) -> int:
        return hash((ArrayType, self.element_type))


def all_types() -> list[DataType]:
    return [BooleanType(), ByteType(), ShortType(), IntegerType(), LongType(),
            FloatType(), DoubleType(), StringType(), DateType(), TimestampType()]


_INTEGRAL_RANK = {ByteType(): 0, ShortType(): 1, IntegerType(): 2, LongType(): 3}
_FRACTIONAL_RANK = {FloatType(): 4, DoubleType(): 5}


def numeric_promote(a: DataType, b: DataType) -> DataType:
    """Spark-style numeric type promotion for binary arithmetic."""
    order = {**_INTEGRAL_RANK, **_FRACTIONAL_RANK}
    if a not in order or b not in order:
        raise TypeError(f"cannot promote {a} and {b}")
    return a if order[a] >= order[b] else b


# ---------------------------------------------------------------------------
# Arrow interop
# ---------------------------------------------------------------------------

def from_numpy_dtype(dtype) -> DataType:
    """numpy dtype -> engine type (ML-interop import direction;
    reference InternalColumnarRddConverter's type mapping)."""
    m = {
        np.dtype(np.bool_): BooleanType(), np.dtype(np.int8): ByteType(),
        np.dtype(np.int16): ShortType(), np.dtype(np.int32): IntegerType(),
        np.dtype(np.int64): LongType(), np.dtype(np.float32): FloatType(),
        np.dtype(np.float64): DoubleType(),
    }
    dt = m.get(np.dtype(dtype))
    if dt is None:
        if np.dtype(dtype).kind in ("U", "O", "S"):
            return StringType()
        raise TypeError(f"no engine type for numpy dtype {dtype}")
    return dt


def arrow_map_to_numpy(arr) -> "np.ndarray":
    """Arrow MapArray -> object ndarray of python dicts (shared by
    every host ingest path so the decode cannot diverge, like
    arrow_fixed_to_numpy for fixed-width)."""
    out = np.empty(len(arr), dtype=object)
    for j, x in enumerate(arr.to_pylist()):
        out[j] = None if x is None else dict(x)
    return out


def to_arrow(dt: DataType):
    import pyarrow as pa
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow(dt.element_type))
    if isinstance(dt, MapType):
        return pa.map_(to_arrow(dt.key_type), to_arrow(dt.value_type))
    m = {
        BooleanType(): pa.bool_(), ByteType(): pa.int8(), ShortType(): pa.int16(),
        IntegerType(): pa.int32(), LongType(): pa.int64(), FloatType(): pa.float32(),
        DoubleType(): pa.float64(), StringType(): pa.string(),
        DateType(): pa.date32(), TimestampType(): pa.timestamp("us"),
    }
    return m[dt]


def from_arrow(at) -> DataType:
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return BooleanType()
    if pa.types.is_int8(at):
        return ByteType()
    if pa.types.is_int16(at):
        return ShortType()
    if pa.types.is_int32(at):
        return IntegerType()
    if pa.types.is_int64(at):
        return LongType()
    if pa.types.is_float32(at):
        return FloatType()
    if pa.types.is_float64(at):
        return DoubleType()
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return StringType()
    if pa.types.is_date32(at):
        return DateType()
    if pa.types.is_timestamp(at):
        return TimestampType()
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow(at.value_type))
    if pa.types.is_map(at):
        return MapType(from_arrow(at.key_type), from_arrow(at.item_type))
    raise TypeError(f"unsupported arrow type {at}")


def arrow_fixed_to_numpy(arr, dt: DataType) -> "np.ndarray":
    """Extract a fixed-width Arrow array as numpy in the framework's
    physical encoding (date=int32 days, timestamp=int64 micros, nulls
    zero-filled).  Shared by the host oracle batch and the device batch so
    the two paths cannot diverge.

    Reads the Arrow buffers with raw numpy math instead of
    pyarrow.compute kernels: the decode path runs on concurrent drain
    workers and pa.compute interleaved with jax CPU execution segfaulted
    intermittently (fill_null/cast); buffer reads are plain memory."""
    import pyarrow as pa
    if isinstance(dt, TimestampType):
        expect = pa.timestamp("us")
        base = np.int64
    elif isinstance(dt, DateType):
        expect = pa.date32()
        base = np.int32
    elif isinstance(dt, BooleanType):
        expect = pa.bool_()
        base = None
    else:
        expect = to_arrow(dt)
        base = np.dtype(dt.np_dtype)
    if arr.type != expect:
        arr = arr.cast(expect)  # rare physical-type adjust (scan shims)
    n = len(arr)
    off = arr.offset
    bufs = arr.buffers()
    if base is None:  # boolean: bit-packed values
        nbytes = (off + n + 7) // 8
        bits = np.frombuffer(bufs[1], np.uint8, count=nbytes)
        out = np.unpackbits(bits, bitorder="little")[off:off + n] \
            .astype(np.bool_)
    else:
        itemsize = np.dtype(base).itemsize
        out = np.frombuffer(bufs[1], base, count=n,
                            offset=off * itemsize).copy()
    if arr.null_count:
        valid = arrow_validity_numpy(arr)
        out[~valid] = 0
    return out if base is None else out.astype(dt.np_dtype, copy=False)


def arrow_validity_numpy(arr) -> "np.ndarray":
    """bool[n] validity from the Arrow bitmap (no pa.compute)."""
    n = len(arr)
    if arr.null_count == 0 or arr.buffers()[0] is None:
        return np.ones(n, dtype=np.bool_)
    off = arr.offset
    nbytes = (off + n + 7) // 8
    bits = np.frombuffer(arr.buffers()[0], np.uint8, count=nbytes)
    return np.unpackbits(bits, bitorder="little")[off:off + n] \
        .astype(np.bool_)


class StructField:
    __slots__ = ("name", "data_type", "nullable")

    def __init__(self, name: str, data_type: DataType, nullable: bool = True):
        self.name = name
        self.data_type = data_type
        self.nullable = nullable

    def __repr__(self):
        return f"{self.name}:{self.data_type.name}{'?' if self.nullable else ''}"

    def __eq__(self, other):
        return (isinstance(other, StructField) and self.name == other.name
                and self.data_type == other.data_type and self.nullable == other.nullable)

    def __hash__(self):
        return hash((self.name, self.data_type, self.nullable))


class Schema:
    """An ordered list of named, typed, nullable fields."""

    def __init__(self, fields: list[StructField]):
        self.fields = list(fields)

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> StructField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __len__(self):
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __repr__(self):
        return "Schema(" + ", ".join(repr(f) for f in self.fields) + ")"

    def __eq__(self, other):
        return isinstance(other, Schema) and self.fields == other.fields

    def __hash__(self):
        return hash(tuple(self.fields))

    def to_arrow(self):
        import pyarrow as pa
        return pa.schema([pa.field(f.name, to_arrow(f.data_type), f.nullable)
                          for f in self.fields])

    @staticmethod
    def from_arrow(aschema) -> "Schema":
        return Schema([StructField(f.name, from_arrow(f.type), f.nullable)
                       for f in aschema])

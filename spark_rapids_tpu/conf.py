"""Typed, self-documenting configuration registry.

TPU-native analog of the reference's RapidsConf (reference
sql-plugin/src/main/scala/com/nvidia/spark/rapids/RapidsConf.scala:30-1059):
a builder-based registry of `spark.rapids.*` entries with docs, defaults,
value checking and doc generation (`RapidsConf.help`, RapidsConf.scala:785),
plus per-operator auto-generated enable keys
(`spark.rapids.sql.expression.<Name>` etc., GpuOverrides.scala:132-137).
"""
from __future__ import annotations

import re
from typing import Any, Callable

__all__ = ["ConfEntry", "TpuConf", "register", "registered_entries", "help_text"]

_BYTE_SUFFIXES = {
    "b": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40,
}


def parse_bytes(v) -> int:
    """Parse '512m', '2g', plain ints. Mirrors Spark byte-unit parsing used by
    RapidsConf (reference RapidsConf.scala bytesConf entries, e.g. :364)."""
    if isinstance(v, (int, float)):
        return int(v)
    m = re.fullmatch(r"\s*(\d+)\s*([bkmgt]?)b?\s*", str(v).lower())
    if not m:
        raise ValueError(f"cannot parse byte size: {v!r}")
    return int(m.group(1)) * _BYTE_SUFFIXES.get(m.group(2) or "b", 1)


class ConfEntry:
    def __init__(self, key: str, default: Any, doc: str, *,
                 conv: Callable[[Any], Any] | None = None,
                 check: Callable[[Any], bool] | None = None,
                 check_doc: str = "", internal: bool = False):
        self.key = key
        self.default = default
        self.doc = doc
        self.conv = conv
        self.check = check
        self.check_doc = check_doc
        self.internal = internal

    def get(self, settings: dict) -> Any:
        if self.key in settings:
            v = settings[self.key]
            if self.conv is not None:
                v = self.conv(v)
            if self.check is not None and not self.check(v):
                raise ValueError(f"{self.key}={v!r}: {self.check_doc}")
            return v
        return self.default


_REGISTRY: dict[str, ConfEntry] = {}


def register(entry: ConfEntry) -> ConfEntry:
    _REGISTRY[entry.key] = entry
    return entry


def registered_entries() -> dict[str, ConfEntry]:
    return dict(_REGISTRY)


def _bool(v):
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("true", "1", "yes")


def conf(key, default, doc, **kw):
    return register(ConfEntry(key, default, doc, **kw))


def bool_conf(key, default, doc, **kw):
    return register(ConfEntry(key, default, doc, conv=_bool, **kw))


def int_conf(key, default, doc, **kw):
    return register(ConfEntry(key, default, doc, conv=int, **kw))


def float_conf(key, default, doc, **kw):
    return register(ConfEntry(key, default, doc, conv=float, **kw))


def bytes_conf(key, default, doc, **kw):
    return register(ConfEntry(key, parse_bytes(default), doc, conv=parse_bytes, **kw))


# ---------------------------------------------------------------------------
# Core entries — names mirror the reference where the concept matches.
# ---------------------------------------------------------------------------

SQL_ENABLED = bool_conf(
    "spark.rapids.sql.enabled", True,
    "Enable or disable TPU acceleration of SQL operators entirely. "
    "(ref RapidsConf.scala ENABLE_SQL)")

EXPLAIN = conf(
    "spark.rapids.sql.explain", "NONE",
    "Explain why parts of a query were or were not placed on the TPU: "
    "NONE, ALL, or NOT_ON_TPU. (ref RapidsConf.scala:744)",
    check=lambda v: v in ("NONE", "ALL", "NOT_ON_TPU"),
    check_doc="must be NONE|ALL|NOT_ON_TPU")

BATCH_SIZE_BYTES = bytes_conf(
    "spark.rapids.sql.batchSizeBytes", 1 << 30,
    "Target byte size for coalesced TPU batches; the CoalesceGoal target. "
    "(ref RapidsConf.scala:364)")

BATCH_CAPACITY_ROWS = int_conf(
    "spark.rapids.sql.batchRowCapacity", 1 << 20,
    "Default logical row capacity bucket for device batches. Batches are "
    "padded up to power-of-two capacities for static-shape XLA compilation "
    "(TPU-specific; no reference analog — cuDF supports dynamic shapes).")

INCOMPATIBLE_OPS = bool_conf(
    "spark.rapids.sql.incompatibleOps.enabled", False,
    "Enable operators flagged as not bit-for-bit compatible with the CPU "
    "engine. (ref RapidsConf.scala INCOMPATIBLE_OPS)")

HAS_NANS = bool_conf(
    "spark.rapids.sql.hasNans", True,
    "Assume floating point data may contain NaNs; disables some ops whose "
    "NaN semantics differ. (ref RapidsConf.scala HAS_NANS)")

ALLOW_FLOAT_AGG = bool_conf(
    "spark.rapids.sql.variableFloatAgg.enabled", True,
    "Allow float aggregations whose result may differ in last-bit rounding "
    "due to reduction order. (ref RapidsConf.scala ENABLE_FLOAT_AGG)")

EXACT_DOUBLE_AGG = bool_conf(
    "spark.rapids.sql.exactDoubleAggregation", False,
    "Force aggregations over DOUBLE columns to the host engine: TPU f64 "
    "is a float32-pair emulation (~48 mantissa bits, f32 exponent range "
    "— docs/compatibility.md) and sums/averages can deviate from exact "
    "f64; artifacts/f64_pair_error.json quantifies the measured error "
    "per op. float32 aggregations are exact on TPU and stay on device. "
    "(ref RapidsConf.scala incompat machinery :461-492)")

REPLACE_SORT_MERGE_JOIN = bool_conf(
    "spark.rapids.sql.replaceSortMergeJoin.enabled", True,
    "Replace sort-merge joins with hash joins on TPU. "
    "(ref RapidsConf.scala:450)")

TEST_ENABLED = bool_conf(
    "spark.rapids.sql.test.enabled", False,
    "Test mode: assert the whole plan runs on the TPU. "
    "(ref RapidsConf.scala TEST_CONF)", internal=True)

TEST_ALLOWED_NONTPU = conf(
    "spark.rapids.sql.test.allowedNonTpu", "",
    "Comma separated exec names allowed on CPU in test mode.", internal=True)

SCAN_REUSE = bool_conf(
    "spark.rapids.sql.scanReuse", True,
    "Share one materialization among identical scans (same files, "
    "columns, pushdown) within a plan, parked spillable in the buffer "
    "catalog — leaf-level ReuseExchange (Spark's rule the reference "
    "inherits); q28-style multi-branch plans otherwise re-read and "
    "re-transfer the same table per branch.")

MAX_READER_BATCH_SIZE_BYTES = bytes_conf(
    "spark.rapids.sql.reader.batchSizeBytes", 1 << 30,
    "Soft cap on bytes per scan batch, converted to a row cap through a "
    "static schema width estimate (io/scan.py). Combines with "
    "spark.rapids.sql.reader.batchRows. (ref RapidsConf.scala:378)")

HBM_ALLOC_FRACTION = float_conf(
    "spark.rapids.memory.tpu.allocFraction", 0.75,
    "Fraction of device HBM the buffer store may occupy before spilling. "
    "(ref RapidsConf.scala gpu.allocFraction, docs/configs.md:33)")

PINNED_POOL_SIZE = bytes_conf(
    "spark.rapids.memory.pinnedPool.size", 0,
    "Size of the native pinned host staging pool (0 disables). "
    "(ref GpuDeviceManager.scala:264-270)")

SHUFFLE_COMPRESSION_CODEC = conf(
    "spark.rapids.shuffle.compression.codec", "none",
    "Codec for shuffle partition buffers: none, lz4 (native C++ block "
    "codec, native/lz4.cpp) or zstd. (ref RapidsConf.scala:729, "
    "NvcompLZ4CompressionCodec.scala:25)",
    check=lambda v: v in ("none", "lz4", "zstd"),
    check_doc="must be none|lz4|zstd")

SHUFFLE_TRANSPORT_CLASS = conf(
    "spark.rapids.shuffle.transport.class",
    "spark_rapids_tpu.shuffle.local.LocalShuffleTransport",
    "Fully qualified class of the shuffle transport implementation, loaded "
    "by reflection. (ref RapidsConf.scala:652, RapidsShuffleTransport.scala:638)")

SHUFFLE_MAX_METADATA_SIZE = bytes_conf(
    "spark.rapids.shuffle.maxMetadataSize", 1 << 20,
    "Max size for shuffle metadata messages. (ref RapidsConf.scala shuffle)")

SHUFFLE_PARTITIONS = int_conf(
    "spark.sql.shuffle.partitions", 8,
    "Number of shuffle partitions for exchanges (Spark's own knob; honored "
    "here for parity).")

MESH_DEVICE_COUNT = int_conf(
    "spark.rapids.tpu.mesh.deviceCount", 0,
    "Devices in the 1-D mesh used for collective shuffle/aggregation. "
    "When > 1, grouped aggregations and hash repartitions lower to "
    "shard_map all-to-all programs over the mesh (the ICI data plane, "
    "SURVEY.md §5.8) instead of the in-process exchange. 0 disables. "
    "(ref: the UCX transport enable, RapidsConf.scala:652)")

MESH_REGIONS_ENABLED = bool_conf(
    "spark.rapids.tpu.mesh.regions.enabled", True,
    "Form mesh REGIONS: a contiguous elementwise pipeline "
    "(filter/project/fused stage) feeding a mesh collective operator "
    "(aggregate, exchange, sort) runs INSIDE the per-device shard_map "
    "program — batches are sharded once at the region leaves and stay "
    "device-resident through the whole pipeline, with host/device-0 "
    "transitions only at region boundaries. Disable to run each mesh "
    "operator as an isolated island (the pre-region plan shape).")

MESH_SEND_CAPACITY = int_conf(
    "spark.rapids.tpu.mesh.exchange.sendCapacityRows", 0,
    "Per-target row capacity C of the [P, C] all-to-all send buffers in "
    "mesh exchanges. 0 (default) sizes C to the full shard capacity — "
    "the static worst case where every row targets one device, which "
    "can never overflow but costs P x shard bytes of send-buffer HBM "
    "per device. A smaller C bounds that memory; if a skewed key "
    "distribution overflows it, the exchange detects the overflow "
    "in-program (no silent truncation), counts mesh_send_overflows, "
    "and degrades into a retry at worst-case capacity — the mesh "
    "analog of the OOM split-and-retry ladder (memory/retry.py).")

MESH_JOIN_BUILD_THRESHOLD = bytes_conf(
    "spark.rapids.tpu.mesh.join.buildThresholdBytes", 128 << 20,
    "Mesh joins replicate the build side to every device while it fits "
    "under this many bytes (broadcast-style, GpuBroadcastHashJoinExec); "
    "above it BOTH sides hash-exchange on the join keys over the mesh "
    "and each device joins its co-partitioned shards locally "
    "(GpuShuffledHashJoinExec.scala:162). 0 forces the partitioned "
    "path.")

MESH_WINDOW_ENABLED = bool_conf(
    "spark.rapids.tpu.mesh.window.enabled", True,
    "Lower window functions to MeshWindowExec when a mesh is active. "
    "Partitioned windows hash-exchange rows on the PARTITION BY keys "
    "in-program (whole groups land on one device) and run the columnar "
    "window kernel per device; unpartitioned windows all-gather the "
    "input and evaluate the global frame on every device, each keeping "
    "its contiguous slice of the ordered output (the MeshSortExec "
    "global-order machinery). Disable to gather window inputs to a "
    "single device (the pre-mesh WindowExec path).")

MESH_REGION_CHAINING = bool_conf(
    "spark.rapids.tpu.mesh.regions.chain.enabled", True,
    "Chain consecutive mesh regions: when a region's exchange terminal "
    "feeds another region's leaf, the producing region's output shards "
    "stay committed one-per-device (parallel/mesh.split_shards) and the "
    "downstream region shards them in place — no gather to device 0, no "
    "host hop, no re-partitioning round trip between regions. Disable "
    "to route chained regions through the per-partition island path.")

UDF_COMPILER_ENABLED = bool_conf(
    "spark.rapids.sql.udfCompiler.enabled", False,
    "Compile Python UDF bytecode to native expressions when possible. "
    "(ref udf-compiler Plugin.scala:29-35)")

FALLBACK_ON_DEVICE_ERROR = bool_conf(
    "spark.rapids.sql.fallbackOnDeviceError", False,
    "Re-run a query on the host engine when device execution raises at "
    "runtime (loud warning). Off by default: the reference only falls "
    "back at plan time, and silent runtime masking would defeat "
    "differential testing.")

SPILL_ENABLED = bool_conf(
    "spark.rapids.memory.spill.enabled", True,
    "Enable HBM->host->disk spill of catalog-registered buffers. "
    "(ref RapidsBufferCatalog.scala:128-142)")

METRICS_ENABLED = bool_conf(
    "spark.rapids.sql.metrics.enabled", True,
    "Collect per-operator metrics (rows/batches/time). (ref GpuExec.scala:47-55)")

TEST_FAULTS = conf(
    "spark.rapids.test.faults", "",
    "Deterministic fault-injection plan: 'point:action,k=v;...' rules "
    "interpreted by spark_rapids_tpu/faults.py and threaded through the "
    "TCP shuffle server/client, the local shuffle store, and the spill "
    "path. Empty (the default) builds no registry at all, so every "
    "injection site is a single None check. Test-only: never set in "
    "production. (reference: RapidsShuffleTestHelper exercises failure "
    "paths with mocked transports; here the REAL transport runs under "
    "seeded faults)")

TEST_FAULTS_SEED = int_conf(
    "spark.rapids.test.faults.seed", 0,
    "Seed for the fault plan's per-rule PRNGs (probabilistic triggers, "
    "corrupted-byte selection), so a chaos run replays identically.")


class TpuConf:
    """An immutable snapshot of settings, queried through typed entries.

    Reference: `class RapidsConf` (RapidsConf.scala:894+). Per-operator enable
    keys look like `spark.rapids.sql.expression.Add` and are checked via
    :meth:`is_op_enabled` (ref GpuOverrides.scala confKey :132-137).
    """

    def __init__(self, settings: dict | None = None):
        self.settings = dict(settings or {})

    def get(self, entry: ConfEntry):
        return entry.get(self.settings)

    # convenience properties mirroring RapidsConf accessors
    @property
    def sql_enabled(self) -> bool: return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str: return self.get(EXPLAIN)

    @property
    def batch_size_bytes(self) -> int: return self.get(BATCH_SIZE_BYTES)

    @property
    def batch_capacity_rows(self) -> int: return self.get(BATCH_CAPACITY_ROWS)

    @property
    def incompatible_ops(self) -> bool: return self.get(INCOMPATIBLE_OPS)

    @property
    def has_nans(self) -> bool: return self.get(HAS_NANS)

    @property
    def test_enabled(self) -> bool: return self.get(TEST_ENABLED)

    @property
    def shuffle_partitions(self) -> int: return self.get(SHUFFLE_PARTITIONS)

    @property
    def is_udf_compiler_enabled(self) -> bool: return self.get(UDF_COMPILER_ENABLED)

    @property
    def mesh_device_count(self) -> int: return self.get(MESH_DEVICE_COUNT)

    def is_op_enabled(self, op_conf_key: str, default: bool = True) -> bool:
        v = self.settings.get(op_conf_key)
        if v is None:
            return default
        return _bool(v)

    def set(self, key: str, value) -> "TpuConf":
        s = dict(self.settings)
        s[key] = value
        return TpuConf(s)


def help_text(include_internal: bool = False) -> str:
    """Generate markdown docs for all registered entries.

    Reference: `RapidsConf.help` generates docs/configs.md (RapidsConf.scala:785).
    """
    lines = ["# spark_rapids_tpu configuration", "",
             "| Key | Default | Description |", "|---|---|---|"]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal and not include_internal:
            continue
        doc = e.doc.replace("\n", " ")
        lines.append(f"| {e.key} | {e.default} | {doc} |")
    return "\n".join(lines) + "\n"


def generate_docs() -> str:
    """Render every registered conf entry as markdown (the analog of
    RapidsConf.help generating docs/configs.md, RapidsConf.scala:785).

    Modules register entries at import near their consumers, so the
    generator imports EVERY package module first — a hand-kept list
    here silently drops new modules' keys from the docs."""
    import importlib
    import pkgutil
    import spark_rapids_tpu
    for m in pkgutil.walk_packages(spark_rapids_tpu.__path__,
                                   "spark_rapids_tpu."):
        if "._native" in m.name or m.name.endswith("_native"):
            continue
        try:
            importlib.import_module(m.name)
        # enginelint: disable=RL001 (docs walker: one failing import skips one module and warns loudly below; no query context)
        except Exception as e:  # noqa: BLE001 - any import-time failure
            # (not just ImportError: device/backend init in a module
            # must not abort the whole generator) skips ONE module; a
            # skipped module silently drops its keys from the docs —
            # make that loud instead of invisible
            import warnings
            warnings.warn(f"generate_docs: could not import {m.name} "
                          f"({e}); its conf keys are missing from the "
                          "generated docs", RuntimeWarning)
    lines = [
        "# Configuration",
        "",
        "Generated by `spark_rapids_tpu.conf.generate_docs()` "
        "(`python scripts/gen_config_docs.py`). Do not edit by hand.",
        "",
        "Reference analog: docs/configs.md generated by RapidsConf.help.",
        "",
        "| Name | Default | Description |",
        "|---|---|---|",
    ]
    for key in sorted(registered_entries()):
        e = registered_entries()[key]
        if e.internal:
            continue
        doc = " ".join(str(e.doc).split())
        default = e.default
        if isinstance(default, str) and not default:
            default = "(unset)"
        lines.append(f"| `{key}` | `{default}` | {doc} |")
    lines.append("")
    lines.append("Per-operation enable keys "
                 "(`spark.rapids.sql.{exec,expression}.<Name>`) default to "
                 "true and are generated from the registries "
                 "(reference ReplacementRule.confKey, "
                 "GpuOverrides.scala:132-137).")
    return "\n".join(lines) + "\n"

"""TpuSession + DataFrame: the user-facing query API.

The reference has no API of its own — it transparently accelerates
Spark SQL (`spark.plugins=com.nvidia.spark.SQLPlugin`,
SQLPlugin.scala:26-31).  Standalone, this engine exposes a PySpark-like
DataFrame API whose plans run through the same rewrite pipeline: build
logical plan -> lower to dual-backend execs -> TpuOverrides tagging
(per-op conf keys, fallback reasons, explain) -> transitions -> execute
on the TPU with the CPU engine as automatic fallback per node.
"""
from __future__ import annotations

from typing import Iterable, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode, collect_device, \
    collect_host
from spark_rapids_tpu.expr.core import (Alias, Expression, Literal, col,
                                        lit, output_name)
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.overrides import PlannedNode, TpuOverrides, lower

__all__ = ["TpuSession", "DataFrame"]


class TpuSession:
    """Session: conf + data sources (reference: SparkSession + the
    plugin's RapidsConf snapshot, Plugin.scala:116).

    The session is also the query lifecycle control plane
    (exec/lifecycle.py): every ``collect`` runs through FIFO admission
    (``spark.rapids.sql.admission.*``), is registered under its
    query_id while in flight so :meth:`cancel` / :meth:`cancel_all`
    reach it, and carries a deadline from
    ``spark.rapids.sql.queryTimeout`` or ``collect(timeout=...)``.
    :meth:`shutdown` stops admission and drains (or cancels) what is
    left — the analog of SparkContext.stop over the plugin's
    task-kill machinery."""

    def __init__(self, conf: dict | TpuConf | None = None):
        self.conf = conf if isinstance(conf, TpuConf) else TpuConf(conf or {})
        from spark_rapids_tpu.runtime import ensure_runtime
        ensure_runtime(self.conf)
        import threading
        self._lc_cond = threading.Condition()
        self._live: dict = {}        # query_id -> QueryLifecycle
        self._admission = None       # built lazily from the live conf
        self._cluster_handle = None  # ClusterDriver, lazily spawned
        self._http = None            # ObsHttpServer when the conf is on
        self._control = None         # ControlLoop when the conf is on
        # raw-settings gated: with the port conf absent/0 (the default)
        # obs.http is never imported (premerge asserts sys.modules)
        port = self.conf.settings.get("spark.rapids.obs.http.port")
        if port and int(port) > 0:
            from spark_rapids_tpu.obs.http import ObsHttpServer
            self._http = ObsHttpServer(self, int(port))
        # raw-settings gated like http/history/cluster: with
        # control.enabled unset (the default) the control package is
        # never imported — plans, confs, and counters stay
        # byte-identical to the static engine (premerge asserts it)
        if str(self.conf.settings.get(
                "spark.rapids.control.enabled", "")).lower() \
                in ("true", "1", "yes"):
            from spark_rapids_tpu.control import ControlLoop
            self._control = ControlLoop(self)
            self._control.start()

    # -- query lifecycle (exec/lifecycle.py) ---------------------------
    def _admission_controller(self):
        with self._lc_cond:
            if self._admission is None:
                from spark_rapids_tpu.exec.lifecycle import \
                    AdmissionController
                self._admission = AdmissionController.from_conf(self.conf)
                from spark_rapids_tpu.memory.governor import (
                    GOVERNOR_ENABLED, get_governor)
                if GOVERNOR_ENABLED.get(self.conf.settings):
                    # memory-pressure shedding: sustained device
                    # occupancy above the shed watermark rejects NEW
                    # queries at admission (memory/governor.py) —
                    # inert with the governor conf off
                    self._admission.pressure_hook = \
                        get_governor().admission_pressure
                # serving-tier fault points (admission.tenant.storm,
                # cache.result.corrupt) — inert unless
                # spark.rapids.test.faults names a plan
                from spark_rapids_tpu.faults import FaultRegistry
                self._admission.faults = FaultRegistry.from_conf(self.conf)
            return self._admission

    def _cluster(self):
        """Lazily spawn the ``local[N]`` worker pool (cluster/driver.py)
        on the first device query.  Raw-settings gated: with
        ``cluster.mode=off`` (the default) the cluster package is never
        imported and this returns None without side effects."""
        if self.conf.settings.get("spark.rapids.cluster.mode",
                                  "off") == "off":
            return None
        with self._lc_cond:
            if self._cluster_handle is None:
                from spark_rapids_tpu.cluster.driver import ClusterDriver
                self._cluster_handle = ClusterDriver(self.conf)
            return self._cluster_handle

    def attach_cluster(self, driver) -> "TpuSession":
        """Adopt an already-built ClusterDriver — the crash-recovery
        entry point: ``ClusterDriver.recover(conf, journal_dir)``
        rebuilds the pool from the write-ahead journal, then the new
        session attaches it instead of spawning fresh workers, so
        resumed queries can claim the recovered map outputs.  The
        session owns the driver from here (session.shutdown tears it
        down)."""
        with self._lc_cond:
            if self._cluster_handle is not None \
                    and self._cluster_handle is not driver:
                raise RuntimeError(
                    "session already has a cluster attached")
            self._cluster_handle = driver
        return self

    def active_queries(self) -> list[str]:
        """query_ids currently admitted and running."""
        with self._lc_cond:
            return sorted(self._live)

    def cancel(self, query_id: str) -> bool:
        """Request cooperative cancellation of one in-flight query.
        Returns True when the request transitioned it to CANCELLED
        (False: unknown id or already terminal).  The run itself
        unwinds at its next cancellation point, raising
        QueryCancelled from ``collect``."""
        with self._lc_cond:
            lc = self._live.get(query_id)
        return lc.cancel("session.cancel") if lc is not None else False

    def cancel_all(self) -> int:
        """Cancel every in-flight query; returns how many transitioned."""
        with self._lc_cond:
            lcs = list(self._live.values())
        return sum(1 for lc in lcs if lc.cancel("session.cancel_all"))

    def shutdown(self, drain: bool = True,
                 timeout: float | None = None) -> None:
        """Graceful session shutdown: stop admission (new queries get
        QueryRejected), then ``drain=True`` waits for in-flight queries
        to finish — cancelling whatever is still running once
        ``timeout`` (seconds, None = wait forever) expires — while
        ``drain=False`` cancels them immediately.  Each query's unwind
        closes its own ExecCtx: shuffle TCP servers stop, catalogs
        close (spill files unlinked), the DeviceSemaphore is released
        in full."""
        # control loop first: a controller actuating knobs while the
        # session tears them down would race, and stop() restores every
        # adapted knob to its static conf value (no thread survives
        # shutdown — premerge asserts it)
        control, self._control = self._control, None
        if control is not None:
            control.stop()
        self._admission_controller().begin_shutdown()
        if not drain:
            self.cancel_all()
            timeout = None
        if not self._wait_idle(timeout):
            # drain window expired: cancel the stragglers, then give
            # their cooperative checkpoints a bounded grace to unwind
            self.cancel_all()
            self._wait_idle(10.0)
        with self._lc_cond:
            cluster, self._cluster_handle = self._cluster_handle, None
        if cluster is not None:
            cluster.shutdown()
        http, self._http = self._http, None
        if http is not None:
            # torn down LAST so /healthz reports "draining" throughout
            http.close()

    def _wait_idle(self, timeout: float | None) -> bool:
        import time as _time
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        with self._lc_cond:
            while self._live:
                rem = None if deadline is None \
                    else deadline - _time.monotonic()
                if rem is not None and rem <= 0:
                    return False
                self._lc_cond.wait(rem if rem is not None else 1.0)
        return True

    def _routed_conf(self, logical) -> TpuConf:
        """The conf this plan should run under: the session conf, plus
        the control plane's history-learned routing overrides (mesh
        shape, express lane) when the controller is on and has enough
        samples for this plan's fingerprint.  With control disabled
        this IS ``self.conf`` — same object, zero divergence."""
        control = self._control
        if control is None or logical is None:
            return self.conf
        overrides = control.route_for(logical)
        if not overrides:
            return self.conf
        conf = self.conf
        for k, v in overrides.items():
            conf = conf.set(k, v)
        return conf

    def _run_query(self, node, backend: str,
                   timeout: float | None = None, logical=None,
                   tenant: str | None = None,
                   conf: "TpuConf | None" = None) -> list[tuple]:
        """Result-cache lookup -> admission -> lifecycle registration
        -> execution -> cleanup for one collect.  The lifecycle is
        registered in ``_live`` BEFORE admission so a cancel reaches a
        query still waiting in the queue (releasing its queue slot;
        counted once as cancelled, never rejected).  The ExecCtx cache
        is pre-seeded with the lifecycle handle (and its query_id) so
        every cancellation point down the stack observes the session's
        cancel/deadline.  A result-cache hit (exec/result_cache.py)
        serves rows without admission or an ExecCtx — zero executor
        dispatches — and a concurrent identical query coalesces onto
        the one in-flight run."""
        import uuid
        from spark_rapids_tpu.exec.lifecycle import (QueryLifecycle,
                                                     QueryLifecycleError)
        if conf is None:
            conf = self.conf
        admission = self._admission_controller()
        query_id = uuid.uuid4().hex[:16]
        lc = QueryLifecycle.from_conf(query_id, conf,
                                      timeout=timeout, tenant=tenant)
        # the control plane's per-tenant SLOs are end-to-end (queue
        # wait + wall): only control-enabled sessions emit the extra
        # e2e histogram, so a static engine's counter set is untouched
        lc.observe_e2e = self._control is not None
        with self._lc_cond:
            self._live[query_id] = lc
        admitted = False

        def run() -> list[tuple]:
            nonlocal admitted
            admission.admit(query_id, tenant=lc.tenant, lifecycle=lc)
            admitted = True
            lc.start()
            try:
                out = self._execute_collect(node, backend, query_id, lc,
                                            conf)
            except QueryLifecycleError:
                raise
            except BaseException:
                if not lc.fail():
                    # already terminal: the cancel/deadline unwound
                    # concurrent workers in arbitrary order and a
                    # secondary error won the race to surface — raise
                    # the lifecycle error (the real cause), chaining
                    # the loser as context
                    lc.check()
                raise
            lc.finish()
            return out

        # raw-settings gated: with history.dir unset (the default)
        # obs.history is never imported (premerge asserts sys.modules)
        hist_dir = self.conf.settings.get("spark.rapids.obs.history.dir")
        hist_before = None
        submitted = None
        if hist_dir:
            import time as _time
            from spark_rapids_tpu.obs.registry import get_registry
            hist_before = get_registry().snapshot()
            submitted = _time.time()
        # raw-settings gated like trace/history: with profile.enabled
        # unset (the default) obs.profile/obs.metering are never
        # imported (premerge asserts sys.modules)
        prof_on = str(conf.settings.get(
            "spark.rapids.obs.profile.enabled", "")).lower() \
            in ("true", "1", "yes")
        if prof_on and hist_before is None:
            from spark_rapids_tpu.obs.registry import get_registry
            hist_before = get_registry().snapshot()
        if prof_on:
            # the meter's registry baseline must predate THIS query's
            # counter movement (queries_executed incs at executor entry,
            # before the first profiler would lazily build the meter) or
            # conservation undercounts the first profiled run
            from spark_rapids_tpu.obs.metering import get_meter
            get_meter()
        if (hist_dir or prof_on) and logical is not None:
            # stash the plan fingerprint on the lifecycle NOW so the
            # live /queries view can map this run to its history
            # medians (percent-complete / ETA) while it executes
            # enginelint: disable=RL001 (fingerprinting is best-effort observability; an unfingerprintable plan still runs)
            try:
                from spark_rapids_tpu.exec.compile_cache import fingerprint
                from spark_rapids_tpu.exec.result_cache import _plan_part
                try:
                    lc.plan_fingerprint = fingerprint(_plan_part(logical))
                # enginelint: disable=RL001 (repr fallback mirrors _record_history's fingerprint path)
                except Exception:
                    lc.plan_fingerprint = fingerprint(repr(logical))
            # enginelint: disable=RL001 (fingerprinting is routing metadata; a plan that defeats it still runs)
            except Exception:
                pass
        err: BaseException | None = None
        try:
            rcache = None
            key = None
            if logical is not None and not admission.shutting_down:
                from spark_rapids_tpu.exec.result_cache import maybe_cache
                rcache = maybe_cache(conf)
                if rcache is not None:
                    # backend is part of the key: the host oracle must
                    # never be served a device run's rows (differential
                    # testing would silently compare a cache to itself).
                    # The ROUTED conf is part of the key too — an
                    # express-routed run and a full-mesh run of the
                    # same logical plan are different computations.
                    key = rcache.result_key(logical, backend, conf)
            if key is None:
                out = run()
            else:
                out = rcache.get_or_compute(
                    key, run, lifecycle=lc, faults=admission.faults)
                lc.finish()
            return out
        except BaseException as e:
            err = e
            raise
        finally:
            metered = None
            if prof_on:
                metered = self._meter_query(lc, hist_before, conf)
            if hist_dir:
                self._record_history(lc, node, logical, err,
                                     hist_before, submitted, conf,
                                     metered=metered)
            with self._lc_cond:
                self._live.pop(query_id, None)
                self._lc_cond.notify_all()
            if admitted:
                admission.release(tenant=lc.tenant)

    def _meter_query(self, lc, before: "dict | None",
                     conf: "TpuConf | None") -> "dict | None":
        """Charge one finished run to its tenant + fingerprint
        (obs/metering.py): device/HBM usage from the query's own
        profiler, byte metrics from its registry delta.  Returns the
        usage dict for the history entry, or None when the run never
        built a profiler (cache hit, pre-admission failure).  Metering
        must never fail the query."""
        # enginelint: disable=RL001 (metering is best-effort accounting; the query's own outcome already propagated)
        try:
            import time as _time
            ctx = getattr(lc, "ctx", None)
            prof = None if ctx is None else ctx.cache.get("profiler")
            if prof is None:
                return None
            from spark_rapids_tpu.obs.metering import get_meter
            from spark_rapids_tpu.obs.profile import get_store
            from spark_rapids_tpu.obs.registry import get_registry
            usage = prof.usage()
            counters = {} if before is None else \
                get_registry().delta(before).get("counters", {})
            usage["shuffle_bytes"] = float(
                counters.get("shuffle.fetch.bytes", 0.0))
            usage["scan_bytes"] = float(counters.get("scan.bytes", 0.0))
            usage["compile_seconds"] = float(
                counters.get("compile_wall_s", 0.0))
            fp = getattr(lc, "plan_fingerprint", None)
            get_meter().charge(lc.tenant or "default", fp, usage)
            if fp:
                started = lc._started_at
                wall = None if started is None \
                    else _time.monotonic() - started
                get_store().note(fp, prof.operators(), wall_s=wall)
            return usage
        # enginelint: disable=RL001 (metering must never fail a finished query; unmetered beats broken)
        except Exception:
            return None

    def _record_history(self, lc, node, logical, err,
                        before: dict, submitted: float,
                        conf: "TpuConf | None" = None,
                        metered: "dict | None" = None) -> None:
        """Append this query's terminal record to the history log
        (obs/history.py).  Forensics must never fail the query: any
        error here is swallowed after best-effort assembly."""
        # enginelint: disable=RL001 (history is best-effort forensics)
        try:
            import time as _time
            from spark_rapids_tpu.exec.lifecycle import (TERMINAL_STATES,
                                                         QueryRejected)
            from spark_rapids_tpu.obs.history import history_log
            from spark_rapids_tpu.obs.registry import get_registry
            log = history_log(self.conf)
            if log is None:
                return
            state = lc.state
            if state not in TERMINAL_STATES:
                state = "REJECTED" if isinstance(err, QueryRejected) \
                    else ("FAILED" if err is not None else state)
            started = lc._started_at
            if conf is None:
                conf = self.conf
            delta = get_registry().delta(before)
            counters = delta.get("counters", {})
            entry: dict = {
                "kind": "history", "version": 1,
                "query_id": lc.query_id,
                "tenant": lc.tenant,
                "state": state,
                "submitted_unix_s": submitted,
                "wall_s": (None if started is None
                           else round(_time.monotonic() - started, 6)),
                "registry_delta": {
                    "counters": counters,
                    "histograms": delta.get("histograms", {}),
                },
                "executed": bool(getattr(lc, "executed", False)),
                "served_from_cache": (err is None
                                      and not getattr(lc, "executed",
                                                      False)),
                "decisions": {k: v for k, v in counters.items()
                              if k.startswith(("aqe", "result_cache",
                                               "fragment_cache",
                                               "compile_count"))},
                # the mesh shape this run executed under (the ROUTED
                # conf when control routing rewrote it) — what the
                # HistoryIndex learns per-shape walls from
                "mesh_devices": max(1, int(conf.settings.get(
                    "spark.rapids.tpu.mesh.deviceCount", 0) or 0)),
                "control_route": conf is not self.conf,
            }
            if getattr(lc, "plan_fingerprint", None):
                entry["plan_fingerprint"] = lc.plan_fingerprint
            elif logical is not None:
                from spark_rapids_tpu.exec.compile_cache import fingerprint
                from spark_rapids_tpu.exec.result_cache import _plan_part
                try:
                    entry["plan_fingerprint"] = \
                        fingerprint(_plan_part(logical))
                # enginelint: disable=RL001 (fingerprint fallback only; the query's own error already propagated)
                except Exception:
                    # in-memory scans have no stable scan_fingerprint;
                    # the structural repr is identity enough for diffing
                    entry["plan_fingerprint"] = fingerprint(repr(logical))
            if metered is not None:
                entry["metering"] = {
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in metered.items()}
            ctx = getattr(lc, "ctx", None)
            if ctx is not None:
                # rows actually emitted, summed across operators — the
                # denominator the live /queries progress view compares
                # its in-flight sum against (HistoryIndex median_rows)
                try:
                    entry["rows_processed"] = int(sum(
                        m.values.get("numOutputRows", 0.0)
                        for m in list(ctx.metrics.values())))
                # enginelint: disable=RL001 (metrics race is benign; the entry ships without a row count)
                except Exception:
                    pass
                prof = ctx.cache.get("profiler")
                if prof is not None:
                    entry["profile"] = prof.history_blob()
                try:
                    from spark_rapids_tpu.plan.overrides import \
                        explain_analyze
                    entry["plan_analyzed"] = explain_analyze(node, ctx)
                # enginelint: disable=RL001 (plan render is best-effort; the entry ships without it)
                except Exception:
                    pass  # a plan that failed mid-build may not render
            if err is not None:
                entry["error"] = {
                    "type": type(err).__name__,
                    "message": str(err)[:4096],
                    "terminal": bool(getattr(err, "terminal", False)),
                }
            log.append(entry)
            control = self._control
            if control is not None:
                # in-process fast path: index the entry now instead of
                # waiting for the file-watch refresh at tick cadence
                control.note_history_entry(entry)
        # enginelint: disable=RL001 (history recording must never mask the query's own outcome; the real error already propagated to the caller)
        except Exception:
            pass

    def _execute_collect(self, node, backend: str, query_id: str, lc,
                         conf: "TpuConf | None" = None):
        # the executor-entry chokepoint: a result-cache hit never gets
        # here, so a zero delta on this counter across a repeated query
        # PROVES the executor was untouched (CI serving gate)
        from spark_rapids_tpu.obs.registry import get_registry
        get_registry().inc("queries_executed")
        lc.executed = True  # vs a result-cache hit, which never gets here
        if conf is None:
            conf = self.conf

        def make_ctx(be: str) -> ExecCtx:
            ctx = ExecCtx(backend=be, conf=conf)
            lc.ctx = ctx  # history records explain_analyze post-run
            ctx.cache["query_id"] = query_id
            ctx.cache["lifecycle"] = lc
            if be == "device":
                # the host backend is the differential ORACLE: it must
                # never see the cluster, or cluster bugs would cancel
                # out of the comparison
                cluster = self._cluster()
                if cluster is not None:
                    ctx.cache["cluster"] = cluster
            return ctx

        if backend != "device":
            return collect_host(node, conf, ctx=make_ctx("host"))
        from spark_rapids_tpu.conf import FALLBACK_ON_DEVICE_ERROR
        if not conf.get(FALLBACK_ON_DEVICE_ERROR):
            return collect_device(node, conf, ctx=make_ctx("device"))
        try:
            return collect_device(node, conf, ctx=make_ctx("device"))
        except Exception as e:  # noqa: BLE001 - opt-in resilience path
            # a cancelled/deadline-exceeded (or otherwise terminal)
            # query must NOT be resurrected on the host engine
            if getattr(e, "terminal", False):
                raise
            # opt-in runtime resilience beyond the reference (which only
            # falls back at PLAN time): rerun the whole query on the
            # host oracle with a loud warning. Off by default — masking
            # device bugs silently would defeat differential testing.
            import warnings
            warnings.warn(
                f"device execution failed ({type(e).__name__}: {e}); "
                "re-running on the host engine per "
                "spark.rapids.sql.fallbackOnDeviceError", RuntimeWarning)
            return collect_host(node, conf, ctx=make_ctx("host"))

    # -- sources -------------------------------------------------------
    def read_parquet(self, path, columns=None, **kw) -> "DataFrame":
        from spark_rapids_tpu.io import ParquetScanExec
        return DataFrame(self, L.Scan(ParquetScanExec(path, columns=columns,
                                                      **kw)))

    def read_orc(self, path, columns=None, **kw) -> "DataFrame":
        from spark_rapids_tpu.io import OrcScanExec
        return DataFrame(self, L.Scan(OrcScanExec(path, columns=columns,
                                                  **kw)))

    def read_csv(self, path, schema: T.Schema | None = None,
                 **kw) -> "DataFrame":
        from spark_rapids_tpu.io import CsvScanExec
        return DataFrame(self, L.Scan(CsvScanExec(path, schema=schema, **kw)))

    def from_pydict(self, data: dict, schema: T.Schema,
                    partitions: int = 1,
                    rows_per_batch: int | None = None) -> "DataFrame":
        from spark_rapids_tpu.exec import LocalScanExec
        return DataFrame(self, L.Scan(LocalScanExec.from_pydict(
            data, schema, partitions, rows_per_batch)))

    def from_arrow(self, table) -> "DataFrame":
        from spark_rapids_tpu.exec import LocalScanExec
        from spark_rapids_tpu.host.batch import HostBatch
        import pyarrow as pa
        if isinstance(table, pa.Table):
            batches = [HostBatch.from_arrow(rb)
                       for rb in table.to_batches()]
        else:
            batches = [HostBatch.from_arrow(table)]
        schema = T.Schema.from_arrow(
            table.schema if hasattr(table, "schema") else table.schema)
        return DataFrame(self, L.Scan(LocalScanExec(batches, schema)))

    def range(self, start: int, end: int | None = None, step: int = 1,
              partitions: int = 1) -> "DataFrame":
        from spark_rapids_tpu.exec import RangeExec
        if end is None:
            start, end = 0, start
        return DataFrame(self, L.Scan(RangeExec(start, end, step,
                                                partitions)))

    def set(self, key: str, value) -> "TpuSession":
        self.conf = self.conf.set(key, value)
        return self


class DataFrame:
    def __init__(self, session: TpuSession, plan: L.LogicalPlan):
        self._s = session
        self._plan = plan

    # -- schema --------------------------------------------------------
    @property
    def schema(self) -> T.Schema:
        return self._planned().exec_node.output_schema

    @property
    def columns(self) -> list[str]:
        return self.schema.names

    # -- transformations ----------------------------------------------
    def select(self, *exprs) -> "DataFrame":
        resolved = [self._col_or_expr(e) for e in exprs]
        return DataFrame(self._s, L.Project(resolved, self._plan))

    def where(self, condition: Expression) -> "DataFrame":
        return DataFrame(self._s, L.Filter(condition, self._plan))

    filter = where

    def with_column(self, name: str, expr: Expression) -> "DataFrame":
        # replacing an existing column keeps its position (Spark
        # semantics; round-1 advisor finding: the old code moved it last)
        names = self._schema_names()
        if name in names:
            exprs = [expr.alias(name) if n == name else col(n)
                     for n in names]
        else:
            exprs = [col(n) for n in names] + [expr.alias(name)]
        return self.select(*exprs)

    def group_by(self, *keys) -> "GroupedData":
        return GroupedData(self, [self._col_or_expr(k) for k in keys])

    def rollup(self, *keys) -> "GroupedData":
        """GROUP BY ROLLUP: grouping sets = every key-prefix down to the
        grand total (reference GpuExpandExec-backed rollup)."""
        ks = [self._col_or_expr(k) for k in keys]
        sets = [set(range(i)) for i in range(len(ks), -1, -1)]
        return GroupedData(self, ks, grouping_sets=sets)

    def cube(self, *keys) -> "GroupedData":
        """GROUP BY CUBE: all 2^n grouping sets."""
        from itertools import combinations
        ks = [self._col_or_expr(k) for k in keys]
        n = len(ks)
        sets = [set(c) for r in range(n, -1, -1)
                for c in combinations(range(n), r)]
        return GroupedData(self, ks, grouping_sets=sets)

    def grouping_sets(self, keys, sets) -> "GroupedData":
        """Explicit GROUPING SETS; ``sets`` lists per-set key names (or
        indices into ``keys``)."""
        ks = [self._col_or_expr(k) for k in keys]
        names = [output_name(k) for k in ks]
        idx_sets = []
        for s in sets:
            idx = set()
            for item in s:
                idx.add(item if isinstance(item, int) else
                        names.index(item))
            idx_sets.append(idx)
        return GroupedData(self, ks, grouping_sets=idx_sets)

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def join(self, other: "DataFrame", on=None, how: str = "inner",
             condition: Expression | None = None) -> "DataFrame":
        left_on, right_on = [], []
        if on is not None:
            if isinstance(on, str):
                on = [on]
            for o in on:
                if isinstance(o, str):
                    left_on.append(col(o))
                    right_on.append(col(o))
                else:
                    l, r = o
                    left_on.append(col(l) if isinstance(l, str) else l)
                    right_on.append(col(r) if isinstance(r, str) else r)
        if how == "cross" or not left_on:
            return DataFrame(self._s, L.Join(
                self._plan, other._plan, "cross", [], [], condition))
        return DataFrame(self._s, L.Join(self._plan, other._plan, how,
                                         left_on, right_on, condition))

    def explode(self, expr, output_name: str = "col", pos: bool = False,
                outer: bool = False) -> "DataFrame":
        """explode(array_col): one output row per element, child columns
        repeated; ``pos`` adds the element index, ``outer`` keeps
        null/empty-array rows (reference GpuGenerateExec explode over
        LIST columns)."""
        from spark_rapids_tpu.exec.generate import Explode
        gen = Explode(self._col_or_expr(expr))
        names = (["pos", output_name] if pos else [output_name])
        return DataFrame(self._s, L.Generate(gen, self._plan, outer=outer,
                                             pos=pos, output_names=names))

    def explode_split(self, expr, delimiter: str, output_name: str = "col",
                      pos: bool = False, outer: bool = False) -> "DataFrame":
        """explode(split(expr, delimiter)): one output row per piece, child
        columns repeated; ``pos`` adds the piece index, ``outer`` keeps
        null-input rows (reference GpuGenerateExec explode/posexplode)."""
        from spark_rapids_tpu.exec.generate import SplitExplode
        gen = SplitExplode(self._col_or_expr(expr), delimiter)
        names = (["pos", output_name] if pos else [output_name])
        return DataFrame(self._s, L.Generate(gen, self._plan, outer=outer,
                                             pos=pos, output_names=names))

    def map_in_pandas(self, fn, schema: T.Schema) -> "DataFrame":
        """``fn`` receives an iterator of pandas DataFrames (one
        partition's batches) and yields DataFrames conforming to
        ``schema``; output row count is unconstrained (Spark
        mapInPandas; reference GpuMapInPandasExec)."""
        return DataFrame(self._s, L.MapInPandas(fn, schema, self._plan))

    def order_by(self, *orders) -> "DataFrame":
        return DataFrame(self._s, L.Sort(list(orders), self._plan))

    sort = order_by

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._s, L.Limit(n, self._plan))

    def distinct(self) -> "DataFrame":
        """Deduplicate rows — a group-by on every column, so nulls and
        NaNs compare equal the way Spark's set operations require."""
        return self.group_by(*self.columns).agg()

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """Set intersection (distinct rows present in BOTH inputs).

        Implemented as union + marker max + group-by on all columns
        instead of a join: group-by keys are null-safe, matching Spark's
        INTERSECT semantics where NULL == NULL (a plain join would drop
        null-keyed rows)."""
        return self._set_op(other, want_a=True, want_b=True)

    def subtract(self, other: "DataFrame") -> "DataFrame":
        """Set difference (distinct rows of self not in other); Spark's
        ``EXCEPT [DISTINCT]`` / ``DataFrame.exceptAll``-less cousin."""
        return self._set_op(other, want_a=True, want_b=False)

    def _set_op(self, other: "DataFrame", want_a: bool,
                want_b: bool) -> "DataFrame":
        from spark_rapids_tpu.expr.aggregates import Max
        names = self.columns
        if len(names) != len(other.columns):
            raise ValueError(
                f"set operation arity mismatch: {len(names)} vs "
                f"{len(other.columns)} columns")

        def uniq(stem: str) -> str:
            nm, i = stem, 0
            while nm in names:
                nm, i = f"{stem}{i}", i + 1
            return nm

        ma, mb = uniq("_sop_a"), uniq("_sop_b")
        ia, ib = uniq("_sop_ia"), uniq("_sop_ib")
        a = self.select(*[col(n) for n in names],
                        lit(1).alias(ma), lit(0).alias(mb))
        b = other.select(*[col(bn).alias(an)
                           for an, bn in zip(names, other.columns)],
                         lit(0).alias(ma), lit(1).alias(mb))
        g = a.union(b).group_by(*names).agg(
            Max(col(ma)).alias(ia), Max(col(mb)).alias(ib))
        cond = (col(ia) == lit(1))
        cond = cond & ((col(ib) == lit(1)) if want_b
                       else (col(ib) == lit(0)))
        return g.where(cond).select(*[col(n) for n in names])

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._s, L.Union([self._plan, other._plan]))

    def cache(self) -> "DataFrame":
        """Materialized columnar caching (reference
        ParquetCachedBatchSerializer, SURVEY §5.4): the plan runs once
        on its tagged backend into codec-compressed Arrow blobs; every
        later execution scans the cache.  Lazy: materializes on first
        use.  Call ``unpersist()`` on the RETURNED frame to free it."""
        from spark_rapids_tpu.exec.cache_exec import CachedScanExec
        ov, meta = self._overridden(quiet=True)
        cached = CachedScanExec(meta.exec_node, meta.backend, self._s.conf)
        return DataFrame(self._s, L.Scan(cached))

    def unpersist(self) -> "DataFrame":
        """Free this frame's cache blobs (no-op unless the plan root is
        a cache scan)."""
        from spark_rapids_tpu.exec.cache_exec import CachedScanExec
        node = getattr(self._plan, "exec_node", None)
        if isinstance(node, CachedScanExec):
            node.unpersist()
        return self

    def repartition(self, num_partitions: int, *keys) -> "DataFrame":
        return DataFrame(self._s, L.Repartition(
            num_partitions, [self._col_or_expr(k) for k in keys],
            self._plan))

    # -- actions -------------------------------------------------------
    def collect(self, timeout: float | None = None,
                tenant: str | None = None) -> list[tuple]:
        """Run the query and return every row as a python tuple.

        ``timeout`` (seconds) sets a per-call deadline, combined with
        ``spark.rapids.sql.queryTimeout`` (the tighter wins): past it,
        the run unwinds at its next cancellation point and raises
        QueryDeadlineExceeded.  The run is registered with the session
        while in flight, so ``session.cancel(query_id)`` /
        ``cancel_all()`` raise QueryCancelled from here, and admission
        control (``spark.rapids.sql.admission.*``) may make this call
        wait its turn or raise QueryRejected under overload.

        ``tenant`` names the weighted-fair admission tenant this query
        runs under (default: ``spark.rapids.sql.tenant``).  A repeated
        identical query over unchanged inputs may be served from the
        process-wide result cache (``spark.rapids.sql.resultCache.*``)
        without touching the executor."""
        # control-plane routing: with the controller on, a repeated
        # plan may run under a history-learned conf (express lane /
        # best mesh shape); otherwise this is self._s.conf unchanged
        conf = self._s._routed_conf(self._plan)
        ov, meta = self._overridden(conf=conf)
        backend = "device" if meta.backend == "device" else "host"
        return self._s._run_query(meta.exec_node, backend,
                                  timeout=timeout, logical=self._plan,
                                  tenant=tenant, conf=conf)

    def to_arrow(self):
        import pyarrow as pa
        ov, meta = self._overridden()
        backend = meta.backend
        ctx = ExecCtx(backend=backend, conf=self._s.conf)
        from spark_rapids_tpu.exec.core import device_to_host
        rbs = []
        for b in meta.exec_node.execute(ctx):
            hb = device_to_host(b) if backend == "device" else b
            rbs.append(hb.to_arrow())
        if not rbs:
            return pa.table([], schema=self.schema.to_arrow())
        return pa.Table.from_batches(rbs)

    def count(self) -> int:
        from spark_rapids_tpu.expr.aggregates import CountStar
        rows = self.agg(CountStar().alias("count")).collect()
        return rows[0][0]

    # -- ML interop (reference ColumnarRdd.scala:42-49) ----------------
    def device_batches(self):
        """Iterate device ColumnBatches without a final D2H — the
        ColumnarRdd analog for ML consumers (interop.py)."""
        from spark_rapids_tpu.interop import device_batches
        return device_batches(self)

    def to_jax(self, include_strings: bool = False) -> dict:
        """{name: (jax values, validity)} of the query result."""
        from spark_rapids_tpu.interop import to_jax
        return to_jax(self, include_strings=include_strings)

    def to_torch(self) -> dict:
        """{name: torch.Tensor} (CPU) of the numeric result columns."""
        from spark_rapids_tpu.interop import to_torch
        return to_torch(self)

    def explain(self) -> str:
        ov, meta = self._overridden(quiet=True)
        return ov.explain(meta)

    def explain_analyze(self) -> str:
        """EXECUTE the query and render the plan annotated with runtime
        metrics: per-node time/batches/rows plus spill, retry, and
        recovery counters recorded during the run (EXPLAIN ANALYZE; the
        reference surfaces the same GpuExec metrics in the SQL UI)."""
        from spark_rapids_tpu.plan.overrides import explain_analyze
        ov, meta = self._overridden(quiet=True)
        with ExecCtx(backend=meta.backend, conf=self._s.conf) as ctx:
            for _ in meta.exec_node.execute(ctx):
                pass
            return explain_analyze(meta.exec_node, ctx)

    def write_parquet(self, path: str, partition_by=None, **kw):
        """Directory write (Spark protocol).  ``partition_by`` enables
        hive-style dynamic-partition output; returns WriteStats.

        With ``spark.rapids.io.write.transactional.enabled`` (the
        default) the write runs as a planned :class:`CreateDataWriteExec`
        job under the two-phase task-attempt commit protocol — through
        the cluster runtime when one is attached — and the committed
        directory carries ``_MANIFEST.json`` + ``_SUCCESS``.  Off =
        the legacy direct in-place writer (no exactly-once guarantee
        under retries)."""
        from spark_rapids_tpu.io.writer import (WRITE_TRANSACTIONAL,
                                                WriteStats)
        if isinstance(partition_by, str):
            partition_by = [partition_by]
        if not self._s.conf.get(WRITE_TRANSACTIONAL):
            from spark_rapids_tpu.io import write_parquet
            ov, meta = self._overridden()
            stats = WriteStats()
            with ExecCtx(backend=meta.backend, conf=self._s.conf) as ctx:
                write_parquet(meta.exec_node, path, ctx=ctx,
                              partition_by=partition_by, stats=stats, **kw)
            return stats
        wdf = DataFrame(self._s, L.DataWrite(
            "parquet", path, list(partition_by or []), dict(kw),
            self._plan))
        ov, meta = wdf._overridden()
        # logical=None: a side-effecting job must execute — it never
        # serves from (or populates) the result cache
        self._s._run_query(meta.exec_node, meta.backend, logical=None)
        return meta.exec_node.stats

    # -- internals -----------------------------------------------------
    def _schema_names(self) -> list[str]:
        return self.schema.names

    def _col_or_expr(self, e):
        return col(e) if isinstance(e, str) else e

    def _planned(self, conf: "TpuConf | None" = None) -> PlannedNode:
        from spark_rapids_tpu.plan.maps import decompose_maps
        conf = self._s.conf if conf is None else conf
        return lower(decompose_maps(self._plan, conf), conf)

    def _overridden(self, quiet: bool = False,
                    conf: "TpuConf | None" = None):
        conf = self._s.conf if conf is None else conf
        meta = self._planned(conf=conf)
        ov = TpuOverrides(conf)
        ov.prepare(meta, explain=not quiet)
        return ov, meta


class GroupedData:
    def __init__(self, df: DataFrame, keys: list, grouping_sets=None):
        self._df = df
        self._keys = keys
        self._sets = grouping_sets  # list[set[int]] of ACTIVE key indices

    def _key_columns(self, what: str) -> list:
        """The grouped pandas ops hand ``fn`` the CHILD's columns, so
        their keys must be plain column references (Spark's
        applyInPandas has the same restriction in practice)."""
        from spark_rapids_tpu.expr.core import UnresolvedAttribute
        for k in self._keys:
            if not isinstance(k, UnresolvedAttribute):
                raise NotImplementedError(
                    f"{what} requires plain column keys, got {k!r}")
        return list(self._keys)

    def apply_in_pandas(self, fn, schema: T.Schema) -> DataFrame:
        """``fn`` receives each group as one pandas DataFrame (all child
        columns) and returns a DataFrame conforming to ``schema`` (Spark
        groupBy().applyInPandas; reference
        GpuFlatMapGroupsInPandasExec)."""
        if self._sets is not None:
            raise NotImplementedError(
                "apply_in_pandas with grouping sets is not supported")
        return DataFrame(self._df._s, L.FlatMapGroupsInPandas(
            self._key_columns("apply_in_pandas"), fn, schema,
            self._df._plan))

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """Pair two grouped frames by key for a joint pandas apply
        (Spark cogroup; reference GpuFlatMapCoGroupsInPandasExec)."""
        return CoGroupedData(self, other)

    def agg(self, *aggs) -> DataFrame:
        from spark_rapids_tpu.expr.aggregates import CountDistinct
        from spark_rapids_tpu.exec.python_exec import PandasAggUDF
        inners = [(a.children[0] if isinstance(a, Alias) else a)
                  for a in aggs]
        if any(isinstance(i, PandasAggUDF) for i in inners):
            if not all(isinstance(i, PandasAggUDF) for i in inners):
                raise NotImplementedError(
                    "mixing pandas_agg_udf with built-in aggregates in "
                    "one agg() is not supported")
            if self._sets is not None:
                raise NotImplementedError(
                    "pandas_agg_udf with grouping sets is not supported")
            udfs = [(output_name(a), i) for a, i in zip(aggs, inners)]
            return DataFrame(self._df._s, L.AggregateInPandas(
                self._key_columns("agg(pandas_agg_udf)"), udfs,
                self._df._plan))
        if any(isinstance(i, CountDistinct) for i in inners):
            return self._agg_with_distinct(list(aggs))
        if self._sets is None:
            exprs = list(self._keys) + list(aggs)
            return DataFrame(self._df._s, L.Aggregate(
                list(self._keys), exprs, self._df._plan))
        return self._agg_grouping_sets(list(aggs))

    def _agg_with_distinct(self, aggs: list) -> DataFrame:
        """Rewrite count(DISTINCT ...) into dedupe-then-count plans
        (Spark plans the same shape via Expand + two-phase aggregation;
        reference distinct-workaround projections, aggregate.scala).

        Supported: any number of CountDistinct aggs (a) with no group
        keys — each becomes a 1-row frame combined by cross join — or
        (b) grouped WITHOUT plain aggs alongside (dedupe on keys+value,
        then count per key).  Grouped mixing of distinct and plain aggs
        would need a null-safe key join; not yet implemented."""
        from spark_rapids_tpu.expr.aggregates import Count, CountDistinct
        from spark_rapids_tpu.expr.predicates import IsNotNull
        if self._sets is not None:
            raise NotImplementedError(
                "count(distinct) with grouping sets is not supported")
        plain, cds = [], []
        for a in aggs:
            inner = a.children[0] if isinstance(a, Alias) else a
            if isinstance(inner, CountDistinct):
                cds.append((output_name(a), inner))
            else:
                plain.append(a)
        base = self._df
        key_names = [output_name(k) for k in self._keys]

        def distinct_count_frame(name: str, cd: CountDistinct,
                                 keys: list) -> DataFrame:
            tmps = [f"_cdv_{name}_{j}" for j in range(len(cd.children))]
            dd = GroupedData(base, list(keys) + [
                Alias(c, t) for c, t in zip(cd.children, tmps)]).agg()
            # count the deduped tuples whose components are ALL non-null
            # WITHOUT filtering rows out first: a group whose values are
            # all null must still appear with count 0 (Spark semantics)
            if len(tmps) == 1:
                cnt_in = col(tmps[0])
            else:
                from spark_rapids_tpu.expr.conditional import If
                cond = None
                for t in tmps:
                    p = IsNotNull(col(t))
                    cond = p if cond is None else cond & p
                cnt_in = If(cond, lit(1),
                            Literal(None, T.LongType()))
            knames = [output_name(k) for k in keys]
            return GroupedData(dd, [col(k) for k in knames]).agg(
                Count(cnt_in).alias(name))

        if not key_names:
            frames = []
            if plain:
                frames.append(GroupedData(base, []).agg(*plain))
            frames.extend(distinct_count_frame(n, cd, []) for n, cd in cds)
            cur = frames[0]
            for f in frames[1:]:
                cur = cur.join(f, how="cross")
            order = [output_name(a) for a in aggs]
            return cur.select(*[col(n) for n in order])
        if plain:
            raise NotImplementedError(
                "grouped count(distinct) mixed with other aggregates "
                "needs a null-safe key join; split into separate "
                "aggregations and join explicitly")
        if len(cds) > 1:
            raise NotImplementedError(
                "one count(distinct) per grouped aggregation")
        name, cd = cds[0]
        return distinct_count_frame(name, cd, list(self._keys))

    def _agg_grouping_sets(self, aggs: list) -> DataFrame:
        """Rollup/cube/grouping-sets: Expand with nulled-out key columns +
        a spark_grouping_id literal per set, then a plain group-by over
        (keys..., spark_grouping_id) so rollup-nulls never merge with
        data-nulls (reference GpuExpandExec + Spark's Expand planning).

        When every aggregate is re-aggregable (sum/count/min/max/avg),
        the input is FIRST aggregated at full key granularity and the
        Expand runs over the (much smaller) group list, re-merging per
        set — N projections over |groups| rows instead of N x |input|
        (the classic rollup-as-reaggregation optimization; the
        reference's expand feeds the same partial-merge machinery,
        aggregate.scala:348-560)."""
        from spark_rapids_tpu.expr.core import Literal, UnresolvedAttribute
        user_names = [output_name(k) for k in self._keys]
        child_cols = self._df.columns
        pre_exprs = [col(n) for n in child_cols]
        key_names = []
        for k, name in zip(self._keys, user_names):
            inner = k.children[0] if isinstance(k, Alias) else k
            if isinstance(inner, UnresolvedAttribute) and \
                    inner.name in child_cols and name == inner.name:
                key_names.append(name)  # plain column key
                continue
            # computed key: project under a collision-proof name so an
            # existing child column of the same name can't shadow it
            resolved = name if name not in child_cols else f"_gs_{name}"
            pre_exprs.append(inner.alias(resolved))
            key_names.append(resolved)
        pre = self._df.select(*pre_exprs)
        decomposed = _decompose_reagg(aggs)
        if decomposed is not None:
            base_aggs, aggs = decomposed
            pre = DataFrame(self._df._s, L.Aggregate(
                [col(n) for n in key_names],
                [col(n) for n in key_names] + base_aggs, pre._plan))
        pre_schema = pre.schema
        nk = len(self._keys)
        projections = []
        for s in self._sets:
            proj = []
            for n in pre_schema.names:
                if n in key_names and key_names.index(n) not in s:
                    f = pre_schema.field(n)
                    proj.append(Literal(None, f.data_type).alias(n))
                else:
                    proj.append(col(n))
            gid = sum(1 << (nk - 1 - i) for i in range(nk) if i not in s)
            proj.append(Literal(gid, T.LongType()).alias("spark_grouping_id"))
            projections.append(proj)
        expanded = DataFrame(self._df._s, L.Expand(projections, pre._plan))
        group_exprs = [col(n) for n in key_names] + [col("spark_grouping_id")]
        result_exprs = [col(n) if n == u else col(n).alias(u)
                        for n, u in zip(key_names, user_names)] + aggs
        return DataFrame(self._df._s, L.Aggregate(
            group_exprs, result_exprs, expanded._plan))


def _decompose_reagg(aggs: list):
    """Split aggregate expressions for grouping-sets re-aggregation:
    base-level partial aggregates at full key granularity plus final
    expressions over the re-merged columns.  sum->sum-of-sums,
    count->sum-of-counts, min/max->min/max, avg->sum(sum)/sum(count).
    Returns (base_aggs, rewritten_aggs), or None when any aggregate is
    not re-aggregable (first/last/count-distinct) — the caller then
    expands the raw input instead."""
    from spark_rapids_tpu.expr.aggregates import (AggregateFunction,
                                                  Average, Count,
                                                  CountDistinct, CountStar,
                                                  Max, Min, Sum)
    base_aggs: list = []
    cache: dict[str, str] = {}
    bad: list = []

    def base_col(fn):
        key = repr(fn)
        if key not in cache:
            name = f"_ra_{len(base_aggs)}"
            base_aggs.append(Alias(fn, name))
            cache[key] = name
        return col(cache[key])

    def rewrite(node):
        if isinstance(node, CountDistinct):
            bad.append(node)
            return node
        if not isinstance(node, AggregateFunction):
            return node
        if isinstance(node, CountStar):
            return Sum(base_col(CountStar()))
        if isinstance(node, Count):
            return Sum(base_col(node))
        if isinstance(node, (Sum, Min, Max)):
            return type(node)(base_col(node))
        if isinstance(node, Average):
            x = node.children[0]
            s, c = base_col(Sum(x)), base_col(Count(x))
            return (Sum(s).cast(T.DoubleType())
                    / Sum(c).cast(T.DoubleType()))
        bad.append(node)
        return node

    rewritten = [a.transform_up(rewrite) for a in aggs]
    if bad:
        return None
    return base_aggs, rewritten


class CoGroupedData:
    """Two grouped frames paired by key; ``apply_in_pandas(fn, schema)``
    calls ``fn(left_group_pdf, right_group_pdf)`` once per key present
    on either side (Spark's cogroup; reference
    GpuFlatMapCoGroupsInPandasExec)."""

    def __init__(self, left: GroupedData, right: GroupedData):
        if len(left._keys) != len(right._keys):
            raise ValueError("cogroup requires the same number of keys "
                             "on both sides")
        self._left = left
        self._right = right

    def apply_in_pandas(self, fn, schema: T.Schema) -> DataFrame:
        lk = self._left._key_columns("cogroup.apply_in_pandas")
        rk = self._right._key_columns("cogroup.apply_in_pandas")
        # both sides are hash-partitioned independently with
        # dtype-width-sensitive murmur3: mismatched key types would
        # route equal values to DIFFERENT partitions and silently split
        # matching groups (review finding) — refuse up front
        ls, rs = self._left._df.schema, self._right._df.schema
        for a, b in zip(lk, rk):
            lt = ls.field(output_name(a)).data_type
            rt = rs.field(output_name(b)).data_type
            if lt != rt:
                raise TypeError(
                    f"cogroup key types must match: left "
                    f"{output_name(a)}:{lt!r} vs right "
                    f"{output_name(b)}:{rt!r} (hash routing is "
                    f"dtype-sensitive)")
        return DataFrame(self._left._df._s, L.FlatMapCoGroupsInPandas(
            lk, rk, fn, schema, self._left._df._plan,
            self._right._df._plan))

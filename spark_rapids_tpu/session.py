"""TpuSession + DataFrame: the user-facing query API.

The reference has no API of its own — it transparently accelerates
Spark SQL (`spark.plugins=com.nvidia.spark.SQLPlugin`,
SQLPlugin.scala:26-31).  Standalone, this engine exposes a PySpark-like
DataFrame API whose plans run through the same rewrite pipeline: build
logical plan -> lower to dual-backend execs -> TpuOverrides tagging
(per-op conf keys, fallback reasons, explain) -> transitions -> execute
on the TPU with the CPU engine as automatic fallback per node.
"""
from __future__ import annotations

from typing import Iterable, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode, collect_device, \
    collect_host
from spark_rapids_tpu.expr.core import Expression, col, lit, output_name
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.overrides import PlannedNode, TpuOverrides, lower

__all__ = ["TpuSession", "DataFrame"]


class TpuSession:
    """Session: conf + data sources (reference: SparkSession + the
    plugin's RapidsConf snapshot, Plugin.scala:116)."""

    def __init__(self, conf: dict | TpuConf | None = None):
        self.conf = conf if isinstance(conf, TpuConf) else TpuConf(conf or {})
        from spark_rapids_tpu.runtime import ensure_runtime
        ensure_runtime(self.conf)

    # -- sources -------------------------------------------------------
    def read_parquet(self, path, columns=None, **kw) -> "DataFrame":
        from spark_rapids_tpu.io import ParquetScanExec
        return DataFrame(self, L.Scan(ParquetScanExec(path, columns=columns,
                                                      **kw)))

    def read_orc(self, path, columns=None, **kw) -> "DataFrame":
        from spark_rapids_tpu.io import OrcScanExec
        return DataFrame(self, L.Scan(OrcScanExec(path, columns=columns,
                                                  **kw)))

    def read_csv(self, path, schema: T.Schema | None = None,
                 **kw) -> "DataFrame":
        from spark_rapids_tpu.io import CsvScanExec
        return DataFrame(self, L.Scan(CsvScanExec(path, schema=schema, **kw)))

    def from_pydict(self, data: dict, schema: T.Schema,
                    partitions: int = 1,
                    rows_per_batch: int | None = None) -> "DataFrame":
        from spark_rapids_tpu.exec import LocalScanExec
        return DataFrame(self, L.Scan(LocalScanExec.from_pydict(
            data, schema, partitions, rows_per_batch)))

    def from_arrow(self, table) -> "DataFrame":
        from spark_rapids_tpu.exec import LocalScanExec
        from spark_rapids_tpu.host.batch import HostBatch
        import pyarrow as pa
        if isinstance(table, pa.Table):
            batches = [HostBatch.from_arrow(rb)
                       for rb in table.to_batches()]
        else:
            batches = [HostBatch.from_arrow(table)]
        schema = T.Schema.from_arrow(
            table.schema if hasattr(table, "schema") else table.schema)
        return DataFrame(self, L.Scan(LocalScanExec(batches, schema)))

    def range(self, start: int, end: int | None = None, step: int = 1,
              partitions: int = 1) -> "DataFrame":
        from spark_rapids_tpu.exec import RangeExec
        if end is None:
            start, end = 0, start
        return DataFrame(self, L.Scan(RangeExec(start, end, step,
                                                partitions)))

    def set(self, key: str, value) -> "TpuSession":
        self.conf = self.conf.set(key, value)
        return self


class DataFrame:
    def __init__(self, session: TpuSession, plan: L.LogicalPlan):
        self._s = session
        self._plan = plan

    # -- schema --------------------------------------------------------
    @property
    def schema(self) -> T.Schema:
        return self._planned().exec_node.output_schema

    @property
    def columns(self) -> list[str]:
        return self.schema.names

    # -- transformations ----------------------------------------------
    def select(self, *exprs) -> "DataFrame":
        resolved = [self._col_or_expr(e) for e in exprs]
        return DataFrame(self._s, L.Project(resolved, self._plan))

    def where(self, condition: Expression) -> "DataFrame":
        return DataFrame(self._s, L.Filter(condition, self._plan))

    filter = where

    def with_column(self, name: str, expr: Expression) -> "DataFrame":
        existing = [col(n) for n in self._schema_names() if n != name]
        return self.select(*existing, expr.alias(name))

    def group_by(self, *keys) -> "GroupedData":
        return GroupedData(self, [self._col_or_expr(k) for k in keys])

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def join(self, other: "DataFrame", on=None, how: str = "inner",
             condition: Expression | None = None) -> "DataFrame":
        left_on, right_on = [], []
        if on is not None:
            if isinstance(on, str):
                on = [on]
            for o in on:
                if isinstance(o, str):
                    left_on.append(col(o))
                    right_on.append(col(o))
                else:
                    l, r = o
                    left_on.append(col(l) if isinstance(l, str) else l)
                    right_on.append(col(r) if isinstance(r, str) else r)
        if how == "cross" or not left_on:
            return DataFrame(self._s, L.Join(
                self._plan, other._plan, "cross", [], [], condition))
        return DataFrame(self._s, L.Join(self._plan, other._plan, how,
                                         left_on, right_on, condition))

    def order_by(self, *orders) -> "DataFrame":
        return DataFrame(self._s, L.Sort(list(orders), self._plan))

    sort = order_by

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._s, L.Limit(n, self._plan))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._s, L.Union([self._plan, other._plan]))

    def repartition(self, num_partitions: int, *keys) -> "DataFrame":
        return DataFrame(self._s, L.Repartition(
            num_partitions, [self._col_or_expr(k) for k in keys],
            self._plan))

    # -- actions -------------------------------------------------------
    def collect(self) -> list[tuple]:
        ov, meta = self._overridden()
        if meta.backend == "device":
            return collect_device(meta.exec_node, self._s.conf)
        return collect_host(meta.exec_node, self._s.conf)

    def to_arrow(self):
        import pyarrow as pa
        ov, meta = self._overridden()
        backend = meta.backend
        ctx = ExecCtx(backend=backend, conf=self._s.conf)
        from spark_rapids_tpu.exec.core import device_to_host
        rbs = []
        for b in meta.exec_node.execute(ctx):
            hb = device_to_host(b) if backend == "device" else b
            rbs.append(hb.to_arrow())
        if not rbs:
            return pa.table([], schema=self.schema.to_arrow())
        return pa.Table.from_batches(rbs)

    def count(self) -> int:
        from spark_rapids_tpu.expr.aggregates import CountStar
        rows = self.agg(CountStar().alias("count")).collect()
        return rows[0][0]

    def explain(self) -> str:
        ov, meta = self._overridden(quiet=True)
        return ov.explain(meta)

    def write_parquet(self, path: str, **kw) -> None:
        from spark_rapids_tpu.io import write_parquet
        ov, meta = self._overridden()
        ctx = ExecCtx(backend=meta.backend, conf=self._s.conf)
        write_parquet(meta.exec_node, path, ctx=ctx, **kw)

    # -- internals -----------------------------------------------------
    def _schema_names(self) -> list[str]:
        return self.schema.names

    def _col_or_expr(self, e):
        return col(e) if isinstance(e, str) else e

    def _planned(self) -> PlannedNode:
        return lower(self._plan, self._s.conf)

    def _overridden(self, quiet: bool = False):
        meta = self._planned()
        ov = TpuOverrides(self._s.conf)
        if quiet:
            ov._tag(meta)
            ov._insert_transitions(meta)
        else:
            ov.apply(meta)
        return ov, meta


class GroupedData:
    def __init__(self, df: DataFrame, keys: list):
        self._df = df
        self._keys = keys

    def agg(self, *aggs) -> DataFrame:
        exprs = list(self._keys) + list(aggs)
        return DataFrame(self._df._s, L.Aggregate(
            list(self._keys), exprs, self._df._plan))

"""Query observability plane: span tracing, metrics registry, EXPLAIN
ANALYZE support, and failure diagnostics.

Reference mapping: the plugin wires a standard metric set into every
GpuExec (GpuMetricNames, GpuExec.scala:27-56) and brackets hot paths in
NVTX ranges so the SQL UI and nsight timelines can explain a query; this
headless engine unifies its equivalents here:

* ``obs.trace``    — Dapper-style request-scoped span tracing (Sigelman
  et al., 2010): one ``query_id``/``trace_id`` pair per execution,
  propagated across the TCP shuffle wire, exported as Perfetto/Chrome
  ``trace_event`` JSON alongside the existing xprof hook.
* ``obs.registry`` — one process-wide metrics registry unifying operator
  Metrics, BufferCatalog counters, and shuffle-plane counters, with
  snapshot/delta semantics and JSON + Prometheus-text exposition.
* ``obs.diag``     — bounded diagnostic bundles emitted on query failure
  (annotated plan, metrics snapshot, last span events, fault config,
  catalog tier occupancy, recent query-history tail).
* ``obs.http``     — stdlib-only live metrics endpoint (/metrics in
  Prometheus text, /healthz, /queries) bound to 127.0.0.1, owned by the
  session and off by default (``spark.rapids.obs.http.port``).
* ``obs.history``  — append-only JSONL query history log with atomic
  rotation (``spark.rapids.obs.history.dir``), browsed offline by
  ``python -m tools.history``.
* ``obs.profile``  — cost-attribution plane: per-operator device/wall
  attribution (fused/mesh members included), HBM occupancy timeline,
  collapsed-stack flamegraphs + Perfetto counter tracks
  (``spark.rapids.obs.profile.enabled``).
* ``obs.metering`` — per-tenant / per-fingerprint resource metering
  (device-seconds, HBM-byte-seconds, bytes) with a conservation
  cross-check, served at ``/tenants``.

Import discipline: the hot path must stay obs-free when observability is
disabled, so this package __init__ resolves submodule attributes LAZILY
— ``spark_rapids_tpu.obs.trace`` / ``obs.diag`` are only imported when a
tracer is enabled or a query actually fails (ci/premerge.sh asserts the
disabled path leaves them out of sys.modules).
"""
from __future__ import annotations

__all__ = ["Tracer", "MetricsRegistry", "get_registry",
           "query_metrics_snapshot", "maybe_emit_bundle",
           "ObsHttpServer", "QueryHistoryLog", "history_log",
           "QueryProfiler", "TenantMeter", "get_meter"]

_LAZY = {
    "Tracer": ("spark_rapids_tpu.obs.trace", "Tracer"),
    "QueryProfiler": ("spark_rapids_tpu.obs.profile", "QueryProfiler"),
    "TenantMeter": ("spark_rapids_tpu.obs.metering", "TenantMeter"),
    "get_meter": ("spark_rapids_tpu.obs.metering", "get_meter"),
    "MetricsRegistry": ("spark_rapids_tpu.obs.registry", "MetricsRegistry"),
    "get_registry": ("spark_rapids_tpu.obs.registry", "get_registry"),
    "query_metrics_snapshot": ("spark_rapids_tpu.obs.registry",
                               "query_metrics_snapshot"),
    "maybe_emit_bundle": ("spark_rapids_tpu.obs.diag", "maybe_emit_bundle"),
    "ObsHttpServer": ("spark_rapids_tpu.obs.http", "ObsHttpServer"),
    "QueryHistoryLog": ("spark_rapids_tpu.obs.history", "QueryHistoryLog"),
    "history_log": ("spark_rapids_tpu.obs.history", "history_log"),
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(target[0]), target[1])

"""Persistent query history: the engine's Spark-history-server analog.

Every query that reaches a lifecycle terminal state appends ONE JSON
line to ``<dir>/query_history.jsonl`` — plan fingerprint, analyzed plan,
tenant, wall time, registry delta (counters + histogram movement),
cache/AQE decisions, and the failure taxonomy when it failed — so
post-hoc forensics ("what ran at 3am and why was p99 bad") survive the
process, the way the reference ecosystem leans on the Spark history
server + event log (PAPER.md §L3).

Durability/bounds: append is a single ``write()`` of one line on a
line-buffered handle under a lock; rotation past
``spark.rapids.obs.history.maxEntries`` keeps the newest entries by
rewriting to a temp file and ``os.replace`` (atomic on POSIX — readers
see the old or the new file, never a torn one).

Import discipline: the session gates on the raw conf string, so with
``spark.rapids.obs.history.dir`` unset this module is never imported
(ci/premerge.sh asserts it).  ``python -m tools.history`` reads the log
with NO engine imports at all.
"""
from __future__ import annotations

import collections
import json
import os
import statistics
import threading
import time

from spark_rapids_tpu.conf import ConfEntry, register

__all__ = ["HISTORY_DIR", "HISTORY_MAX", "HistoryIndex", "QueryHistoryLog",
           "history_log", "read_entries", "read_history_tail",
           "HISTORY_FILE"]

HISTORY_DIR = register(ConfEntry(
    "spark.rapids.obs.history.dir", "",
    "When set, every query reaching a lifecycle terminal state appends "
    "one JSON line (plan fingerprint, analyzed plan, tenant, wall, "
    "registry delta, failure taxonomy) to <dir>/query_history.jsonl; "
    "inspect with `python -m tools.history`. Empty (default): no "
    "history, no overhead (the module is never imported)."))
HISTORY_MAX = register(ConfEntry(
    "spark.rapids.obs.history.maxEntries", 512,
    "History log rotation bound: once the log exceeds this many "
    "entries it is atomically rewritten keeping the newest ones.",
    conv=int))

HISTORY_FILE = "query_history.jsonl"


class QueryHistoryLog:
    """Append-only bounded JSONL log, safe for concurrent appenders in
    one process (lock) and for concurrent readers across processes
    (atomic rotation via ``os.replace``)."""

    def __init__(self, directory: str, max_entries: int = 512):
        self.dir = directory
        self.path = os.path.join(directory, HISTORY_FILE)
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._count: int | None = None  # lazily counted on first append

    def _count_locked(self) -> int:
        if self._count is None:
            n = 0
            try:
                with open(self.path, "rb") as f:
                    for _ in f:
                        n += 1
            except FileNotFoundError:
                pass
            self._count = n
        return self._count

    def append(self, entry: dict) -> None:
        line = json.dumps(entry, sort_keys=True, default=str)
        with self._lock:
            os.makedirs(self.dir, exist_ok=True)
            self._count_locked()
            with open(self.path, "ab") as f:
                # a crash mid-append can leave a torn final line with no
                # newline; terminate it first so THIS entry stays parseable
                # (the reader already skips the torn fragment)
                if f.tell() > 0:
                    with open(self.path, "rb") as r:
                        r.seek(-1, os.SEEK_END)
                        if r.read(1) != b"\n":
                            f.write(b"\n")
                f.write(line.encode("utf-8") + b"\n")
                f.flush()
            self._count += 1
            if self._count > self.max_entries:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        with open(self.path, "r", encoding="utf-8") as f:
            lines = f.readlines()
        keep = lines[-self.max_entries:]
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.writelines(keep)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._count = len(keep)

    def entries(self, last: int | None = None) -> list[dict]:
        return read_entries(self.path, last=last)


def read_entries(path: str, last: int | None = None) -> list[dict]:
    """Parse the log, newest last; torn/garbage lines are skipped (a
    crash mid-append must not poison forensics of every other query).

    Rotation-tolerant: ``_rotate_locked`` swaps the file out with
    ``os.replace`` while readers may be mid-iteration.  The swap is
    atomic but a read that STRADDLES it returns a mix of a
    half-consumed old inode and nothing of the new one — so the inode
    is compared before and after the read, and a read whose file was
    replaced underneath it retries against the fresh file (bounded
    retries: under pathological rotation churn the last read wins,
    torn or not, rather than spinning)."""
    out: list[dict] = []
    for _attempt in range(4):
        try:
            st_before = os.stat(path)
        except OSError:
            return []
        out = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
        try:
            st_after = os.stat(path)
        except OSError:
            # rotated away (or the dir vanished) right after the read:
            # what was read is the newest complete view there was
            break
        if (st_after.st_ino, st_after.st_dev) == \
                (st_before.st_ino, st_before.st_dev):
            break
    return out if last is None else out[-last:]


def read_history_tail(directory: str, last: int = 16) -> list[dict]:
    """Bounded newest-entries summary for diag bundles: one compact
    dict per query, heavy fields (analyzed plan, registry delta)
    dropped."""
    tail = read_entries(os.path.join(directory, HISTORY_FILE), last=last)
    out = []
    for e in tail:
        out.append({k: e.get(k) for k in
                    ("query_id", "state", "tenant", "wall_s",
                     "submitted_unix_s", "plan_fingerprint", "error")
                    if e.get(k) is not None})
    return out


class HistoryIndex:
    """Bounded in-memory fingerprint → wall-time index over the
    history log, so plan routing is a dict lookup on the query path
    instead of a ``query_history.jsonl`` re-read per query.

    Two feeds: :meth:`note_entry` (the in-process fast path — the
    session indexes each entry as it appends it) and
    :meth:`refresh_from` (rebuild from the file when its identity
    changed — history written by OTHER processes sharing the
    directory, or a rotation).  ``refresh_from`` is stat-gated and
    rate-limited, and a rebuild REPLACES the index, so the two feeds
    never double-count an entry.  LRU-bounded on fingerprints and
    sample-bounded per fingerprint: a long-lived driver seeing
    unbounded distinct plans stays at a fixed footprint."""

    def __init__(self, max_fingerprints: int = 512,
                 max_samples: int = 32,
                 min_refresh_s: float = 1.0):
        self.max_fingerprints = max(1, int(max_fingerprints))
        self.max_samples = max(1, int(max_samples))
        self.min_refresh_s = float(min_refresh_s)
        self._lock = threading.Lock()
        # fp -> deque of (wall_s, mesh_devices, rows_processed,
        # device_seconds), LRU order; rows/device_s are 0 when the
        # entry predates the cost-attribution plane (PR 19)
        self._fps: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._file_id: "tuple | None" = None
        self._last_refresh: "float | None" = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._fps)

    def note_entry(self, entry: dict) -> None:
        """Index one history entry (only FINISHED runs teach the
        router — a failed or cancelled wall says nothing about the
        plan's true cost)."""
        with self._lock:
            self._note_locked(entry)

    def _note_locked(self, entry: dict) -> None:
        fp = entry.get("plan_fingerprint")
        if not fp or entry.get("state") != "FINISHED":
            return
        wall = entry.get("wall_s")
        if not isinstance(wall, (int, float)) or wall < 0:
            return
        try:
            mesh = int(entry.get("mesh_devices") or 1)
        except (TypeError, ValueError):
            mesh = 1
        try:
            rows = int(entry.get("rows_processed") or 0)
        except (TypeError, ValueError):
            rows = 0
        metering = entry.get("metering")
        try:
            dev = float((metering or {}).get("device_seconds") or 0.0)
        except (TypeError, ValueError):
            dev = 0.0
        dq = self._fps.get(fp)
        if dq is None:
            dq = self._fps[fp] = collections.deque(
                maxlen=self.max_samples)
        dq.append((float(wall), mesh, rows, dev))
        self._fps.move_to_end(fp)
        while len(self._fps) > self.max_fingerprints:
            self._fps.popitem(last=False)

    def refresh_from(self, path: str) -> bool:
        """Rebuild from the log file iff its identity (inode + size +
        mtime) moved since the last look, at most every
        ``min_refresh_s``.  Returns True when a rebuild happened."""
        now = time.monotonic()
        with self._lock:
            if self._last_refresh is not None and \
                    now - self._last_refresh < self.min_refresh_s:
                return False
            self._last_refresh = now
            try:
                st = os.stat(path)
                file_id = (st.st_ino, st.st_dev, st.st_size,
                           st.st_mtime_ns)
            except OSError:
                file_id = None
            if file_id == self._file_id:
                return False
            self._file_id = file_id
        entries = read_entries(path)  # outside the lock: file I/O
        with self._lock:
            self._fps.clear()
            for e in entries:
                self._note_locked(e)
        return True

    def lookup(self, fingerprint: str) -> "dict | None":
        """Observed-wall stats for one plan fingerprint, or None if it
        was never (successfully) seen: total samples, overall median
        wall, a per-mesh-shape breakdown, and — when the history
        carries cost-attribution data — median rows processed (the
        /queries progress denominator) and median metered
        device-seconds."""
        with self._lock:
            dq = self._fps.get(fingerprint)
            if not dq:
                return None
            self._fps.move_to_end(fingerprint)
            samples = list(dq)
        by_mesh: dict = {}
        for wall, mesh, _rows, _dev in samples:
            by_mesh.setdefault(mesh, []).append(wall)
        rows = [r for _w, _m, r, _d in samples if r > 0]
        devs = [d for _w, _m, _r, d in samples if d > 0]
        return {
            "samples": len(samples),
            "median_wall_s": statistics.median(
                w for w, _m, _r, _d in samples),
            "median_rows": statistics.median(rows) if rows else None,
            "median_device_s": statistics.median(devs) if devs else None,
            "by_mesh": {m: {"samples": len(ws),
                            "median_wall_s": statistics.median(ws)}
                        for m, ws in by_mesh.items()},
        }


_logs: dict[tuple[str, int], QueryHistoryLog] = {}
_logs_lock = threading.Lock()


def history_log(conf) -> "QueryHistoryLog | None":
    """Process-wide per-directory singleton (two sessions pointed at
    one dir share a lock and a rotation count)."""
    settings = getattr(conf, "settings", None) or {}
    d = HISTORY_DIR.get(settings)
    if not d:
        return None
    key = (os.path.abspath(d), HISTORY_MAX.get(settings))
    with _logs_lock:
        log = _logs.get(key)
        if log is None:
            log = _logs[key] = QueryHistoryLog(key[0], key[1])
        return log

"""Process-wide metrics registry: one place where operator ``Metrics``,
``BufferCatalog`` counters, and shuffle-plane counters meet.

The reference plugin threads a standard metric set (GpuMetricNames)
through every GpuExec and lets Spark's accumulator machinery aggregate
and expose it; this engine has no driver/UI, so the registry plays that
role: monotonically increasing **counters** (``inc``), point-in-time
**gauges** (``set_gauge``), and pull-style **sources** (callables
returning flat dicts — the existing per-object metrics dicts on
catalogs/transports register themselves here without copying code).

Snapshot/delta semantics let the bench runner report per-query counter
movement, and ``to_prometheus`` renders the standard text exposition so
a scrape endpoint is one ``open().write()`` away.

Dependency discipline: this module imports nothing from the engine (only
stdlib), so hot modules (shuffle/retry.py, faults.py) may import it at
module level without creating cycles or dragging jax into light paths.

Well-known counter families (beyond per-object sources):
``shuffle.fetch.*`` (retry ladder), ``faults.injected[.point]``
(injection sites), and the query lifecycle plane's
``queries_admitted`` / ``queries_rejected`` / ``queries_cancelled`` /
``queries_deadline_exceeded`` (exec/lifecycle.py — incremented exactly
once per query at the admission decision or the first terminal
transition, so a delta over a run counts QUERIES, not checkpoints); and
the compile plane's ``compile_count`` / ``compile_wall_s`` (one move per
NEW jit input signature — a zero delta across a repeated query proves
pure cache reuse) plus ``fusion_cache_hits`` / ``fusion_cache_misses``
(process-wide program-cache lookups, exec/compile_cache.py); and the
adaptive-execution plane's ``aqe_broadcast_switches`` (shuffle-join ->
broadcast-join rewrites, plan/adaptive.py) /
``aqe_partitions_coalesced`` / ``aqe_skew_splits`` (reader-group
regrouping from map-output sizes, exec/exchange.py) /
``aqe_dynamic_filters`` (build-side IN-set/min-max filters pushed into
probe scans) — each incremented at the decision site, so a per-query
delta shows exactly what the re-optimizer did; and the cross-query
memory governor's ``governor_*`` family (memory/governor.py):
``governor_reclaims`` / ``governor_spill_bytes_own`` /
``governor_spills_peer`` / ``governor_spill_bytes_peer`` (need-sized
arbitration, own-then-younger-peer order), ``governor_grant_waits`` /
``governor_grants`` / ``governor_grant_timeouts`` (wound-wait losers
parked for memory), ``governor_background_spills`` /
``governor_spill_bytes_background`` (watermark thread),
``governor_pressure_sheds`` (admissions rejected under sustained
occupancy), ``governor_victim_errors`` (peer spills skipped because
the victim failed), and ``governor_storm_denials`` (injected
``memory.governor.oom_storm`` reclaim denials) — plus the ``governor`` pull source's aggregate and
per-query ``q.<query_id>.{device,pinned,peak}_bytes`` gauges; and the
serving tier's two families: the result-cache plane's
``result_cache_hits`` / ``result_cache_misses`` (whole-query serves vs
computes — a hit moves NO ``queries_executed`` and NO
``compile_count``), ``result_cache_fragment_hits`` /
``result_cache_fragment_misses`` (cross-query shared-scan
materializations), ``result_cache_corrupt`` (CRC-failed hits dropped
and recomputed), ``result_cache_evictions``,
``result_cache_coalesced`` (waiters single-flighted onto an in-flight
identical query), ``governor_cache_evict_bytes`` (cache bytes the
governor reclaimed under pressure) plus the ``result_cache`` pull
source (entries/bytes gauges, exec/result_cache.py); and the
multi-tenant admission plane's ``queries_executed`` (incremented at
executor entry — the zero-delta proof that a cache hit never touched
the executor), per-tenant ``admission.tenant.<t>.admitted`` /
``admission.tenant.<t>.rejected``, and ``admission_pressure_spared``
(pressure sheds skipped because the arriving tenant was under its
weighted share — exec/lifecycle.py).

Beyond counters and gauges the registry carries log-bucketed
**histograms** (``observe``) for the hot latency distributions the
serving tier's SLOs are defined by: ``query.wall_seconds`` (plus
per-tenant ``query.tenant.<t>.wall_seconds``), ``admission.queue_wait_seconds``,
``shuffle.fetch.round_trip_seconds``, ``compile.wall_seconds``,
``spill.io_seconds``, and ``cluster.rpc.round_trip_seconds`` — each
observed at its existing chokepoint.  Histogram snapshots ride the same
snapshot/delta plane as counters (worker heartbeats ship them; the
driver merges them with :func:`merge_histogram_snapshots`), and
``to_prometheus`` renders the standard cumulative
``_bucket``/``_sum``/``_count`` exposition.
"""
from __future__ import annotations

import bisect
import json
import re
import threading
import weakref

_SAN = re.compile(r"[^a-zA-Z0-9_]")

#: dotted metric names that ENCODE a label in the name: rendered as one
#: Prometheus family with a proper label instead of one invalid family
#: per tenant/point/peer.  (pattern, family template, label name) —
#: ``val`` is the label value, ``leaf`` the trailing metric leaf.
_LABELED = (
    (re.compile(r"^admission\.tenant\.(?P<val>.+)\.(?P<leaf>admitted|rejected|pressure_spared)$"),
     "admission_tenant_{leaf}", "tenant"),
    (re.compile(r"^query\.tenant\.(?P<val>.+)\.(?P<leaf>wall_seconds|e2e_seconds)$"),
     "query_tenant_{leaf}", "tenant"),
    (re.compile(r"^control\.decision\.(?P<val>.+)$"),
     "control_decisions_by_rule", "decision"),
    (re.compile(r"^control\.route\.(?P<val>.+)$"),
     "control_routes_by_kind", "kind"),
    (re.compile(r"^faults\.injected\.(?P<val>.+)$"),
     "faults_injected", "point"),
    (re.compile(r"^shuffle\.peer\.(?P<val>.+)\.(?P<leaf>[A-Za-z0-9_]+)$"),
     "shuffle_peer_{leaf}", "peer"),
    (re.compile(r"^shuffle\.breaker\.(?P<val>.+)\.(?P<leaf>failures|open)$"),
     "shuffle_breaker_{leaf}", "peer"),
    (re.compile(r"^cluster\.workers\.state\.(?P<val>.+)$"),
     "cluster_workers", "state"),
)


def _series_parts(name: str) -> "tuple[str, str | None]":
    """(family, label) for one dotted metric name; label is a rendered
    ``key="value"`` pair (escaped) or None for plain names."""
    for pat, fam, label in _LABELED:
        m = pat.match(name)
        if m is None:
            continue
        gd = m.groupdict()
        family = _SAN.sub("_", fam.format(leaf=gd.get("leaf", "")))
        val = gd["val"].replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n")
        return family, f'{label}="{val}"'
    return _SAN.sub("_", name), None


# -- histograms -------------------------------------------------------------

#: default log-bucketed boundaries: 1ms doubling up to ~35 minutes —
#: wide enough for spill I/O at the bottom and stuck cluster RPCs at
#: the top.  Every histogram in the process shares these bounds, so
#: cross-process snapshot merges are bucket-aligned by construction.
_DEFAULT_BOUNDS = tuple(0.001 * (2.0 ** i) for i in range(22))


def empty_histogram_snapshot(bounds=_DEFAULT_BOUNDS) -> dict:
    le = [float(b) for b in bounds]
    return {"le": le, "counts": [0] * (len(le) + 1), "sum": 0.0,
            "count": 0}


def histogram_percentile(snap: "dict | None", q: float) -> "float | None":
    """Estimate the q-th percentile (q in (0, 100]) from a histogram
    snapshot by linear interpolation inside the covering bucket.
    Monotone in q by construction; None for an empty histogram."""
    if not snap or not snap.get("count"):
        return None
    le = snap["le"]
    counts = snap["counts"]
    target = (q / 100.0) * snap["count"]
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= target:
            lo = le[i - 1] if i > 0 else 0.0
            # the +Inf bucket has no upper bound: report its lower edge
            hi = le[i] if i < len(le) else le[-1]
            return lo + (hi - lo) * max(0.0, min(1.0, (target - cum) / c))
        cum += c
    return float(le[-1])


def merge_histogram_snapshots(a: "dict | None",
                              b: "dict | None") -> dict:
    """Combine two snapshots (worker heartbeat deltas, partial buffers
    from a worker that died mid-run).  Either side may be None/empty —
    an empty delta is inert.  Mismatched bucket bounds (a worker on an
    older build) are re-bucketed conservatively by upper bound."""
    if not a or not a.get("count"):
        return dict(b) if b and b.get("count") \
            else empty_histogram_snapshot((a or b or {}).get(
                "le", _DEFAULT_BOUNDS))
    if not b or not b.get("count"):
        return dict(a)
    le = list(a["le"])
    counts = list(a["counts"])
    if list(b["le"]) == le:
        counts = [x + y for x, y in zip(counts, b["counts"])]
    else:
        for j, c in enumerate(b["counts"]):
            if not c:
                continue
            if j < len(b["le"]):
                i = bisect.bisect_left(le, float(b["le"][j]))
            else:
                i = len(le)
            counts[i] += c
    return {"le": le, "counts": counts,
            "sum": a["sum"] + b["sum"], "count": a["count"] + b["count"]}


def delta_histogram_snapshot(cur: dict,
                             prev: "dict | None") -> "dict | None":
    """Per-bucket movement since ``prev``; None when no new samples
    landed (so empty deltas disappear instead of accumulating)."""
    if prev is None or list(prev.get("le", ())) != list(cur["le"]):
        prev = empty_histogram_snapshot(cur["le"])
    moved = cur["count"] - prev.get("count", 0)
    if moved <= 0:
        return None
    return {"le": list(cur["le"]),
            "counts": [max(0, c - p) for c, p in
                       zip(cur["counts"], prev["counts"])],
            "sum": max(0.0, cur["sum"] - prev.get("sum", 0.0)),
            "count": moved}


class Histogram:
    """Thread-safe log-bucketed latency histogram.

    Fixed bucket boundaries (``_DEFAULT_BOUNDS`` unless given) keep
    ``observe`` at one bisect + three adds, make snapshots mergeable
    across processes, and render directly as the Prometheus cumulative
    ``_bucket{le=...}`` exposition."""

    __slots__ = ("le", "_counts", "_sum", "_count", "_lock")

    def __init__(self, bounds=None):
        self.le = tuple(float(b) for b in (bounds or _DEFAULT_BOUNDS))
        self._counts = [0] * (len(self.le) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.le, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"le": list(self.le), "counts": list(self._counts),
                    "sum": self._sum, "count": self._count}

    def percentile(self, q: float) -> "float | None":
        return histogram_percentile(self.snapshot(), q)

    def merge_snapshot(self, snap: "dict | None") -> None:
        """Fold a shipped snapshot (another process's delta) into this
        histogram; an empty/None snapshot is a no-op."""
        if not snap or not snap.get("count"):
            return
        with self._lock:
            cur = {"le": list(self.le), "counts": list(self._counts),
                   "sum": self._sum, "count": self._count}
            merged = merge_histogram_snapshots(cur, snap)
            self._counts = list(merged["counts"])
            self._sum = merged["sum"]
            self._count = merged["count"]


class MetricsRegistry:
    """Thread-safe counters + gauges + histograms + pull sources."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._sources: dict[str, object] = {}
        self._histograms: dict[str, Histogram] = {}

    # -- write side --------------------------------------------------------

    def inc(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def histogram(self, name: str, bounds=None) -> Histogram:
        """Get-or-create the named histogram (bounds only apply on
        first creation; everyone after shares the instance)."""
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.get(name)
                if h is None:
                    h = self._histograms[name] = Histogram(bounds)
        return h

    def observe(self, name: str, value: float) -> None:
        """Record one latency sample into the named histogram."""
        self.histogram(name).observe(value)

    def register_source(self, name: str, fn) -> None:
        """``fn() -> dict[str, number]``; folded into snapshots under
        ``<name>.<key>``. A source raising or returning junk is dropped
        from that snapshot, never propagated — observability must not
        fail the query."""
        with self._lock:
            self._sources[name] = fn

    def unregister_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def register_object_source(self, name: str, obj, attr: str = "metrics"):
        """Register ``obj.<attr>`` (a plain dict) as a source via weakref
        so the registry never keeps a catalog/transport alive."""
        ref = weakref.ref(obj)

        def _pull(_ref=ref, _attr=attr):
            o = _ref()
            if o is None:
                return {}
            d = getattr(o, _attr, None)
            return dict(d) if isinstance(d, dict) else {}

        self.register_source(name, _pull)
        return name

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            sources = list(self._sources.items())
            hists = list(self._histograms.items())
        for name, fn in sources:
            try:
                vals = fn()
            # enginelint: disable=RL001 (metric source callbacks are best-effort; a failing source is skipped)
            except Exception:
                continue
            if not isinstance(vals, dict):
                continue
            for k, v in vals.items():
                if isinstance(v, (int, float)):
                    gauges[f"{name}.{k}"] = v
        return {"counters": counters, "gauges": gauges,
                "histograms": {n: h.snapshot() for n, h in hists}}

    def delta(self, prev: dict) -> dict:
        """Counter and histogram movement since ``prev`` (a prior
        ``snapshot()``); gauges are point-in-time and reported as-is.
        Histograms with no new samples are omitted — an empty delta is
        inert (it merges to nothing on the other side)."""
        cur = self.snapshot()
        before = prev.get("counters", {}) if prev else {}
        moved = {}
        for k, v in cur["counters"].items():
            d = v - before.get(k, 0)
            if d:
                moved[k] = d
        hbefore = prev.get("histograms", {}) if prev else {}
        hmoved = {}
        for k, snap in cur.get("histograms", {}).items():
            d = delta_histogram_snapshot(snap, hbefore.get(k))
            if d is not None:
                hmoved[k] = d
        return {"counters": moved, "gauges": cur["gauges"],
                "histograms": hmoved}

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def to_prometheus(self, prefix: str = "srt_") -> str:
        """Standard Prometheus text exposition (version 0.0.4).

        Metric names are sanitized to ``[a-zA-Z0-9_]``; dotted names
        that encode a tenant/point/peer (``admission.tenant.<t>.admitted``,
        ``faults.injected.<point>``, ``shuffle.peer.<addr>.*``) become
        one family with a proper label.  Histograms render as the
        cumulative ``_bucket{le=...}``/``_sum``/``_count`` triple."""
        snap = self.snapshot()
        lines = []
        for kind, bucket in (("counter", snap["counters"]),
                             ("gauge", snap["gauges"])):
            fams: dict = {}
            for name in sorted(bucket):
                family, label = _series_parts(name)
                metric = prefix + family
                v = bucket[name]
                val = f"{v:g}" if isinstance(v, float) else str(v)
                series = f"{metric}{{{label}}} {val}" if label \
                    else f"{metric} {val}"
                fams.setdefault(metric, []).append(series)
            for metric in sorted(fams):
                lines.append(f"# TYPE {metric} {kind}")
                lines.extend(fams[metric])
        hfams: dict = {}
        for name in sorted(snap.get("histograms", {})):
            family, label = _series_parts(name)
            hfams.setdefault(prefix + family, []).append(
                (label, snap["histograms"][name]))
        for metric in sorted(hfams):
            lines.append(f"# TYPE {metric} histogram")
            for label, h in hfams[metric]:
                lbl = f"{label}," if label else ""
                suffix = f"{{{label}}}" if label else ""
                cum = 0
                for bound, c in zip(h["le"], h["counts"]):
                    cum += c
                    lines.append(
                        f'{metric}_bucket{{{lbl}le="{bound:g}"}} {cum}')
                cum += h["counts"][-1]
                lines.append(f'{metric}_bucket{{{lbl}le="+Inf"}} {cum}')
                lines.append(f"{metric}_sum{suffix} {h['sum']:g}")
                lines.append(f"{metric}_count{suffix} {h['count']}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Test hook: drop all counters/gauges/sources/histograms."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._sources.clear()
            self._histograms.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry singleton."""
    return _REGISTRY


def query_metrics_snapshot(ctx) -> dict:
    """Unified per-query view: operator Metrics aggregated by operator
    class, plus the registry snapshot. Used by EXPLAIN ANALYZE footers,
    diagnostics bundles, and the bench runner."""
    ops: dict[str, dict] = {}
    for key, m in getattr(ctx, "metrics", {}).items():
        name = key.split("@")[0]
        agg = ops.setdefault(name, {})
        for k, v in m.values.items():
            agg[k] = agg.get(k, 0) + v
    return {"operators": ops, "registry": _REGISTRY.snapshot()}

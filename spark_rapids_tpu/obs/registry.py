"""Process-wide metrics registry: one place where operator ``Metrics``,
``BufferCatalog`` counters, and shuffle-plane counters meet.

The reference plugin threads a standard metric set (GpuMetricNames)
through every GpuExec and lets Spark's accumulator machinery aggregate
and expose it; this engine has no driver/UI, so the registry plays that
role: monotonically increasing **counters** (``inc``), point-in-time
**gauges** (``set_gauge``), and pull-style **sources** (callables
returning flat dicts — the existing per-object metrics dicts on
catalogs/transports register themselves here without copying code).

Snapshot/delta semantics let the bench runner report per-query counter
movement, and ``to_prometheus`` renders the standard text exposition so
a scrape endpoint is one ``open().write()`` away.

Dependency discipline: this module imports nothing from the engine (only
stdlib), so hot modules (shuffle/retry.py, faults.py) may import it at
module level without creating cycles or dragging jax into light paths.

Well-known counter families (beyond per-object sources):
``shuffle.fetch.*`` (retry ladder), ``faults.injected[.point]``
(injection sites), and the query lifecycle plane's
``queries_admitted`` / ``queries_rejected`` / ``queries_cancelled`` /
``queries_deadline_exceeded`` (exec/lifecycle.py — incremented exactly
once per query at the admission decision or the first terminal
transition, so a delta over a run counts QUERIES, not checkpoints); and
the compile plane's ``compile_count`` / ``compile_wall_s`` (one move per
NEW jit input signature — a zero delta across a repeated query proves
pure cache reuse) plus ``fusion_cache_hits`` / ``fusion_cache_misses``
(process-wide program-cache lookups, exec/compile_cache.py); and the
adaptive-execution plane's ``aqe_broadcast_switches`` (shuffle-join ->
broadcast-join rewrites, plan/adaptive.py) /
``aqe_partitions_coalesced`` / ``aqe_skew_splits`` (reader-group
regrouping from map-output sizes, exec/exchange.py) /
``aqe_dynamic_filters`` (build-side IN-set/min-max filters pushed into
probe scans) — each incremented at the decision site, so a per-query
delta shows exactly what the re-optimizer did; and the cross-query
memory governor's ``governor_*`` family (memory/governor.py):
``governor_reclaims`` / ``governor_spill_bytes_own`` /
``governor_spills_peer`` / ``governor_spill_bytes_peer`` (need-sized
arbitration, own-then-younger-peer order), ``governor_grant_waits`` /
``governor_grants`` / ``governor_grant_timeouts`` (wound-wait losers
parked for memory), ``governor_background_spills`` /
``governor_spill_bytes_background`` (watermark thread),
``governor_pressure_sheds`` (admissions rejected under sustained
occupancy), ``governor_victim_errors`` (peer spills skipped because
the victim failed), and ``governor_storm_denials`` (injected
``memory.governor.oom_storm`` reclaim denials) — plus the ``governor`` pull source's aggregate and
per-query ``q.<query_id>.{device,pinned,peak}_bytes`` gauges; and the
serving tier's two families: the result-cache plane's
``result_cache_hits`` / ``result_cache_misses`` (whole-query serves vs
computes — a hit moves NO ``queries_executed`` and NO
``compile_count``), ``result_cache_fragment_hits`` /
``result_cache_fragment_misses`` (cross-query shared-scan
materializations), ``result_cache_corrupt`` (CRC-failed hits dropped
and recomputed), ``result_cache_evictions``,
``result_cache_coalesced`` (waiters single-flighted onto an in-flight
identical query), ``governor_cache_evict_bytes`` (cache bytes the
governor reclaimed under pressure) plus the ``result_cache`` pull
source (entries/bytes gauges, exec/result_cache.py); and the
multi-tenant admission plane's ``queries_executed`` (incremented at
executor entry — the zero-delta proof that a cache hit never touched
the executor), per-tenant ``admission.tenant.<t>.admitted`` /
``admission.tenant.<t>.rejected``, and ``admission_pressure_spared``
(pressure sheds skipped because the arriving tenant was under its
weighted share — exec/lifecycle.py).
"""
from __future__ import annotations

import json
import re
import threading
import weakref

_SAN = re.compile(r"[^a-zA-Z0-9_:]")


class MetricsRegistry:
    """Thread-safe counters + gauges + pull sources."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._sources: dict[str, object] = {}

    # -- write side --------------------------------------------------------

    def inc(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def register_source(self, name: str, fn) -> None:
        """``fn() -> dict[str, number]``; folded into snapshots under
        ``<name>.<key>``. A source raising or returning junk is dropped
        from that snapshot, never propagated — observability must not
        fail the query."""
        with self._lock:
            self._sources[name] = fn

    def unregister_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def register_object_source(self, name: str, obj, attr: str = "metrics"):
        """Register ``obj.<attr>`` (a plain dict) as a source via weakref
        so the registry never keeps a catalog/transport alive."""
        ref = weakref.ref(obj)

        def _pull(_ref=ref, _attr=attr):
            o = _ref()
            if o is None:
                return {}
            d = getattr(o, _attr, None)
            return dict(d) if isinstance(d, dict) else {}

        self.register_source(name, _pull)
        return name

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            sources = list(self._sources.items())
        for name, fn in sources:
            try:
                vals = fn()
            # enginelint: disable=RL001 (metric source callbacks are best-effort; a failing source is skipped)
            except Exception:
                continue
            if not isinstance(vals, dict):
                continue
            for k, v in vals.items():
                if isinstance(v, (int, float)):
                    gauges[f"{name}.{k}"] = v
        return {"counters": counters, "gauges": gauges}

    def delta(self, prev: dict) -> dict:
        """Counter movement since ``prev`` (a prior ``snapshot()``);
        gauges are point-in-time and reported as-is."""
        cur = self.snapshot()
        before = prev.get("counters", {}) if prev else {}
        moved = {}
        for k, v in cur["counters"].items():
            d = v - before.get(k, 0)
            if d:
                moved[k] = d
        return {"counters": moved, "gauges": cur["gauges"]}

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def to_prometheus(self, prefix: str = "srt_") -> str:
        """Standard Prometheus text exposition (version 0.0.4)."""
        snap = self.snapshot()
        lines = []
        for kind, bucket in (("counter", snap["counters"]),
                             ("gauge", snap["gauges"])):
            for name in sorted(bucket):
                metric = prefix + _SAN.sub("_", name)
                lines.append(f"# TYPE {metric} {kind}")
                v = bucket[name]
                lines.append(f"{metric} {v:g}" if isinstance(v, float)
                             else f"{metric} {v}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Test hook: drop all counters/gauges/sources."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._sources.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry singleton."""
    return _REGISTRY


def query_metrics_snapshot(ctx) -> dict:
    """Unified per-query view: operator Metrics aggregated by operator
    class, plus the registry snapshot. Used by EXPLAIN ANALYZE footers,
    diagnostics bundles, and the bench runner."""
    ops: dict[str, dict] = {}
    for key, m in getattr(ctx, "metrics", {}).items():
        name = key.split("@")[0]
        agg = ops.setdefault(name, {})
        for k, v in m.values.items():
            agg[k] = agg.get(k, 0) + v
    return {"operators": ops, "registry": _REGISTRY.snapshot()}

"""Per-tenant resource metering: who burned the device, the HBM, and
the bytes.

The reference ecosystem answers "which team's queries cost us this
cluster" with the Spark history server + per-stage task metrics rolled
up by external billing jobs; this engine meters in-process.  Every
profiled query (``spark.rapids.obs.profile.enabled``) is charged to its
admission tenant and its plan fingerprint at lifecycle end:

* ``device_seconds``     — operator active time (profiler attribution)
* ``hbm_byte_seconds``   — integrated device-buffer occupancy
* ``shuffle_bytes``      — shuffle fetch traffic during the query
* ``spill_bytes``        — host+disk spill written by its catalog
* ``scan_bytes``         — input file bytes decoded
* ``compile_seconds``    — jit tracing/compilation wall charged to it
* ``queries``            — executed runs (cache hits never meter)

Conservation invariant: the per-tenant charge path is INDEPENDENT of
the process-totals path (charges come from each query's own profiler /
catalog / registry delta; totals from the raw instrumentation counters
and the HBM sampler's process integration), so ``conservation()`` is a
real cross-check — tenant sums within 5% of process totals — not a
tautology.  Under concurrent queries the registry-delta byte charges
can overlap (two in-flight queries each observe the other's counter
movement); the invariant is asserted on serial runs (tests,
ci/premerge.sh) where the two paths must agree.

Import discipline: this module is only imported when the raw conf
string enables profiling (ci/premerge.sh asserts ``obs.metering``
stays out of sys.modules on the disabled path).
"""
from __future__ import annotations

import threading

from spark_rapids_tpu.obs.registry import get_registry

__all__ = ["TenantMeter", "get_meter", "USAGE_METRICS"]

#: every metric a query charge may carry, in exposition order
USAGE_METRICS = ("device_seconds", "hbm_byte_seconds", "shuffle_bytes",
                 "spill_bytes", "scan_bytes", "compile_seconds", "queries")

#: process totals derived from raw registry counters (incremented at
#: the I/O chokepoints themselves, not by the charge path)
_REGISTRY_TOTALS = {
    "shuffle_bytes": ("shuffle.fetch.bytes",),
    "scan_bytes": ("scan.bytes",),
    "compile_seconds": ("compile_wall_s",),
    "queries": ("queries_executed",),
}

#: fingerprint table bound: a long-lived driver seeing unbounded
#: distinct plans keeps a fixed metering footprint (LRU on charge)
_MAX_FINGERPRINTS = 512


class TenantMeter:
    """Process-wide accumulator of per-tenant / per-fingerprint usage.

    ``charge`` is the query-side path (session lifecycle end);
    ``add_total`` is the instrumentation-side path (profiler record_op,
    HBM sampler tick).  The two never share a call site — that is what
    makes ``conservation()`` worth checking.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: dict[str, dict[str, float]] = {}
        self._fps: dict[str, dict[str, float]] = {}
        self._totals: dict[str, float] = {}
        # per-worker totals folded in from cluster heartbeats — kept
        # OUT of conservation (each process conserves its own books)
        self._workers: dict[str, dict[str, float]] = {}
        # registry-counter baseline so totals are meter-relative, not
        # process-lifetime-relative (profiling may be enabled late)
        self._baseline = self._registry_read()
        # last-shipped copies for cluster heartbeat deltas
        self._shipped_tenants: dict[str, dict[str, float]] = {}
        self._shipped_totals: dict[str, float] = {}

    # -- write side ----------------------------------------------------
    def charge(self, tenant: str, fingerprint: "str | None",
               usage: dict) -> None:
        """Attribute one query's usage to its tenant (and fingerprint
        when the plan has one).  Only :data:`USAGE_METRICS` keys are
        folded — the vocabulary is closed so a buggy caller can never
        grow per-tenant key cardinality without bound."""
        tenant = tenant or "default"
        usage = {k: v for k, v in (usage or {}).items()
                 if k in USAGE_METRICS}
        with self._lock:
            self._fold(self._tenants.setdefault(tenant, {}), usage)
            if fingerprint:
                self._fold(self._fps.setdefault(fingerprint, {}), usage)
                if len(self._fps) > _MAX_FINGERPRINTS:
                    # dict preserves insertion order: drop the oldest
                    self._fps.pop(next(iter(self._fps)))

    def add_total(self, metric: str, amount: float) -> None:
        """Instrumentation-side process total (never called by the
        charge path — see the conservation contract above)."""
        if not amount:
            return
        with self._lock:
            self._totals[metric] = self._totals.get(metric, 0.0) \
                + float(amount)

    def ingest_worker(self, worker_id: str, totals: dict) -> None:
        """Fold a cluster worker's shipped totals delta under its own
        ledger (heartbeat path, cluster/driver.py)."""
        with self._lock:
            self._fold(self._workers.setdefault(str(worker_id), {}),
                       totals)

    @staticmethod
    def _fold(dst: dict, src: dict) -> None:
        for k, v in (src or {}).items():
            if isinstance(v, (int, float)):
                dst[k] = dst.get(k, 0.0) + float(v)

    # -- read side -----------------------------------------------------
    def _registry_read(self) -> dict[str, float]:
        counters = get_registry().snapshot().get("counters", {})
        return {m: sum(float(counters.get(n, 0.0)) for n in names)
                for m, names in _REGISTRY_TOTALS.items()}

    def totals(self) -> dict[str, float]:
        now = self._registry_read()
        with self._lock:
            out = dict(self._totals)
            for m, v in now.items():
                out[m] = out.get(m, 0.0) + v - self._baseline.get(m, 0.0)
            return out

    def snapshot(self) -> dict:
        with self._lock:
            tenants = {t: dict(u) for t, u in self._tenants.items()}
            fps = {f: dict(u) for f, u in self._fps.items()}
            workers = {w: dict(u) for w, u in self._workers.items()}
        return {"tenants": tenants, "fingerprints": fps,
                "totals": self.totals(), "workers": workers}

    def conservation(self, tolerance: float = 0.05) -> dict:
        """Per-metric cross-check of the two accounting paths: the sum
        of tenant charges vs. the independently-accumulated process
        total.  ``ok`` when they agree within ``tolerance`` (or both
        are ~zero).  A failing metric means attribution double-counted
        or dropped work — exactly the bug class this plane must not
        have."""
        snap = self.snapshot()
        out = {}
        for m in USAGE_METRICS:
            s = sum(u.get(m, 0.0) for u in snap["tenants"].values())
            t = snap["totals"].get(m, 0.0)
            hi = max(abs(s), abs(t))
            ok = hi <= 1e-9 or abs(s - t) <= tolerance * hi
            out[m] = {"tenants_sum": s, "total": t, "ok": ok}
        out["ok"] = all(v["ok"] for v in out.values()
                        if isinstance(v, dict))
        return out

    # -- cluster shipping ---------------------------------------------
    def drain_delta(self) -> "dict | None":
        """Per-tenant charges + accumulated totals moved since the last
        drain — the heartbeat payload a worker ships (registry-derived
        totals ride the existing metrics snapshot, so only the
        instrumentation accumulators ship here)."""
        with self._lock:
            d_tenants: dict = {}
            for t, u in self._tenants.items():
                prev = self._shipped_tenants.setdefault(t, {})
                moved = {k: v - prev.get(k, 0.0) for k, v in u.items()
                         if v != prev.get(k, 0.0)}
                if moved:
                    d_tenants[t] = moved
                self._shipped_tenants[t] = dict(u)
            d_totals = {k: v - self._shipped_totals.get(k, 0.0)
                        for k, v in self._totals.items()
                        if v != self._shipped_totals.get(k, 0.0)}
            self._shipped_totals = dict(self._totals)
        if not d_tenants and not d_totals:
            return None
        return {"tenants": d_tenants, "totals": d_totals}

    def merge_delta(self, delta: dict) -> None:
        """Fold a shipped delta's tenant charges into this process's
        books (driver side of :meth:`drain_delta`)."""
        with self._lock:
            for t, u in (delta.get("tenants") or {}).items():
                self._fold(self._tenants.setdefault(str(t), {}), u)
            self._fold(self._totals, delta.get("totals") or {})


_meter: "TenantMeter | None" = None
_meter_lock = threading.Lock()


def get_meter() -> TenantMeter:
    """Process-wide meter singleton (first call sets the registry
    baseline for the counter-derived totals)."""
    global _meter
    with _meter_lock:
        if _meter is None:
            _meter = TenantMeter()
        return _meter

"""Live telemetry endpoint: a stdlib-only HTTP server owned by the
session.

The reference ecosystem operates through the Spark UI + a Prometheus
sink (PAPER.md §L3 GpuMetric plumbing); this headless engine exposes the
same operational surface as three read-only routes:

* ``/metrics`` — the process-wide :class:`MetricsRegistry` in Prometheus
  text exposition (counters, gauges, and the latency histograms with
  cumulative ``_bucket``/``_sum``/``_count`` series).
* ``/healthz`` — liveness + readiness: admission state (active/queued /
  shutting-down), memory-governor pressure, cluster worker liveness.
  Returns 503 once the session begins shutdown — load balancers drain
  on readiness, not liveness.
* ``/queries`` — the in-flight query table (query_id -> lifecycle
  state/tenant/tenant wall so far), the live analog of the history log;
  with profiling on each row also carries rows-processed,
  percent-complete, and ETA against the plan's history medians.
* ``/control`` — the self-driving control plane's learned state
  (current admission cap, adapted governor watermarks, per-tenant SLO
  status, last 32 decisions), or ``{"enabled": false}`` when the
  control loop is off.
* ``/profile`` — the cost-attribution plane (obs/profile.py): HBM
  occupancy timeline and per-fingerprint operator cost tables, or
  ``{"enabled": false}`` with ``spark.rapids.obs.profile.enabled``
  unset (the profiler modules are never imported then).
* ``/tenants`` — per-tenant resource metering (device-seconds,
  HBM-byte-seconds, shuffle/spill/scan bytes, compile-seconds) with
  the tenant-sums-vs-process-totals conservation cross-check.

Security: binds 127.0.0.1 ONLY.  The registry carries operational
detail (tenant names, peer addresses, plan fingerprints) that must not
face a network; operators who need remote scrape should sidecar a real
exporter.  Off by default (``spark.rapids.obs.http.port`` = 0) and the
module is never imported on the disabled path (session gates on the raw
conf string; ci/premerge.sh asserts sys.modules stays clean).
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from spark_rapids_tpu.conf import ConfEntry, register
from spark_rapids_tpu.obs.registry import get_registry

__all__ = ["OBS_HTTP_PORT", "ObsHttpServer"]

OBS_HTTP_PORT = register(ConfEntry(
    "spark.rapids.obs.http.port", 0,
    "TCP port for the live telemetry endpoint (/metrics Prometheus "
    "text, /healthz, /queries), bound to 127.0.0.1 only. 0 (default): "
    "no server, and the HTTP module is never imported.",
    conv=int))

_BIND_HOST = "127.0.0.1"


class _Handler(BaseHTTPRequestHandler):
    # the protocol default (HTTP/1.0) closes per request; 1.1 lets a
    # scraper keep its connection
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: ARG002 - silence stderr
        pass

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj) -> None:
        self._reply(code, json.dumps(obj, indent=1, sort_keys=True,
                                     default=str).encode(),
                    "application/json")

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        srv: "ObsHttpServer" = self.server.obs  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(200, get_registry().to_prometheus().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                body = srv.health()
                self._json(200 if body["status"] == "ok" else 503, body)
            elif path == "/queries":
                self._json(200, srv.queries())
            elif path == "/control":
                self._json(200, srv.control())
            elif path == "/profile":
                self._json(200, srv.profile())
            elif path == "/tenants":
                self._json(200, srv.tenants())
            else:
                self._reply(404,
                            b"not found: /metrics /healthz /queries "
                            b"/control /profile /tenants\n",
                            "text/plain")
        except BrokenPipeError:  # scraper hung up mid-reply
            pass
        # enginelint: disable=RL001 (endpoint must never kill the engine)
        except Exception as e:
            try:
                self._reply(500, f"{type(e).__name__}: {e}\n".encode(),
                            "text/plain")
            except OSError:
                pass


class ObsHttpServer:
    """One telemetry server per :class:`TpuSession`, 127.0.0.1-bound.

    ``port=0`` binds an ephemeral port (tests); the session itself
    treats conf port 0 as "off" and never constructs one."""

    def __init__(self, session, port: int):
        self._session = session
        self._server = ThreadingHTTPServer((_BIND_HOST, port), _Handler)
        self._server.daemon_threads = True
        self._server.obs = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="obs-http",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def address(self) -> str:
        return f"http://{_BIND_HOST}:{self.port}"

    # -- route bodies (also the programmatic surface for tests) --------
    def health(self) -> dict:
        s = self._session
        adm = s._admission_controller()
        out: dict = {
            "status": "draining" if adm.shutting_down else "ok",
            "unix_s": time.time(),
            "admission": {"active": adm.active, "queued": adm.queued,
                          "shutting_down": adm.shutting_down},
        }
        try:
            from spark_rapids_tpu.memory.governor import (GOVERNOR_ENABLED,
                                                          get_governor)
            if GOVERNOR_ENABLED.get(s.conf.settings):
                gov = get_governor()
                out["governor"] = {
                    "reserved_bytes": gov.reserved_bytes(),
                    "pressure": gov.admission_pressure(),
                }
        # enginelint: disable=RL001 (health must degrade, not fail — the error string is the report)
        except Exception as e:
            out["governor"] = {"error": f"{type(e).__name__}: {e}"}
        cluster = getattr(s, "_cluster_handle", None)
        if cluster is not None:
            workers = []
            now = time.monotonic()
            for h in cluster.workers():
                workers.append({
                    "worker_id": h.worker_id, "pid": h.pid,
                    "alive": h.alive, "lost_reason": h.lost_reason,
                    "state": getattr(h, "state", None)
                    or ("alive" if h.alive else "lost"),
                    "heartbeat_age_s": (
                        None if not h.last_heartbeat
                        else round(now - h.last_heartbeat, 3)),
                })
            out["cluster"] = {"workers": workers}
            out["cluster"]["epoch"] = getattr(cluster, "epoch", 1)
            recovery = getattr(cluster, "recovery_info", None)
            if recovery is not None:
                # this driver was rebuilt from its write-ahead journal
                # (cluster/journal.py): surface what the recovery
                # re-attached, replaced, and salvaged
                out["cluster"]["recovery"] = recovery
            # only UNPLANNED loss degrades readiness: a draining or
            # retired worker is a planned scale-down, a quarantined one
            # still serves its map outputs
            if any(w["state"] == "lost" for w in workers) \
                    and out["status"] == "ok":
                out["status"] = "degraded"
        control = getattr(s, "_control", None)
        if control is not None:
            shed = dict(control.slo.shed)
            if shed:
                # a shed tenant is a PLANNED partial outage: the
                # engine is protecting everyone else's SLO, so
                # readiness degrades with the tenant NAMED rather
                # than flipping hard-down
                out["shed_tenants"] = sorted(shed)
                if out["status"] == "ok":
                    out["status"] = "degraded"
        return out

    def control(self) -> dict:
        """The /control body: learned knob values, per-tenant SLO
        table, and the last 32 decisions — or a stub when the control
        plane is off (the endpoint must answer either way so
        dashboards can probe for it)."""
        control = getattr(self._session, "_control", None)
        if control is None:
            return {"enabled": False}
        out = control.status()
        out["enabled"] = True
        return out

    # -- cost-attribution plane (obs/profile.py, raw-conf gated) -------
    def _profile_on(self) -> bool:
        raw = self._session.conf.settings.get(
            "spark.rapids.obs.profile.enabled")
        return raw is not None and str(raw).lower() in ("true", "1",
                                                        "yes")

    def _progress_index(self):
        """The HistoryIndex live progress reads its medians from: the
        control loop's (already fed in-process) when the controller is
        on, else a session-owned one refreshed from the history file.
        None when there is no history to compare against."""
        s = self._session
        control = getattr(s, "_control", None)
        idx = getattr(control, "_history_index", None) \
            if control is not None else None
        if idx is not None:
            return idx
        hist_dir = s.conf.settings.get("spark.rapids.obs.history.dir")
        if not hist_dir:
            return None
        from spark_rapids_tpu.obs.history import HISTORY_FILE, \
            HistoryIndex
        import os
        idx = getattr(s, "_progress_hist_index", None)
        if idx is None:
            idx = s._progress_hist_index = HistoryIndex()
        idx.refresh_from(os.path.join(hist_dir, HISTORY_FILE))
        return idx

    def profile(self) -> dict:
        """The /profile body: HBM occupancy timeline, per-fingerprint
        operator cost tables, live per-query device-seconds — or
        ``{"enabled": false}`` when profiling is off (the endpoint
        answers either way; the profile module is only imported when
        the conf is on)."""
        if not self._profile_on():
            return {"enabled": False}
        from spark_rapids_tpu.obs.profile import profile_view
        return profile_view(self._session)

    def tenants(self) -> dict:
        """The /tenants body: per-tenant and per-fingerprint usage
        plus the conservation cross-check — or ``{"enabled": false}``
        when profiling is off."""
        if not self._profile_on():
            return {"enabled": False}
        from spark_rapids_tpu.obs.metering import get_meter
        meter = get_meter()
        out = meter.snapshot()
        out["conservation"] = meter.conservation()
        out["enabled"] = True
        return out

    def queries(self) -> dict:
        s = self._session
        with s._lc_cond:
            live = dict(s._live)
        now = time.monotonic()
        prof_on = self._profile_on()
        idx = self._progress_index() if prof_on else None
        out = {}
        for qid, lc in live.items():
            started = lc._started_at
            row = {
                "state": lc.state,
                "tenant": lc.tenant,
                "wall_s": (None if started is None
                           else round(now - started, 3)),
            }
            if prof_on:
                from spark_rapids_tpu.obs.profile import live_progress
                row.update(live_progress(lc, idx))
            out[qid] = row
        return {"active": out, "count": len(out)}

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)

"""Failure diagnostics: bounded bundles emitted when a query dies.

After three robustness PRs the engine survives peer death, OOM storms,
and spill corruption — but when the budgets are finally exhausted
(``StageRecoveryExhausted``, ``SplitAndRetryOOM``) the operator gets a
bare traceback and must rerun the chaos to learn anything. This module
captures what the process already knows at the moment of failure into
one JSON artifact: the analyzed plan (EXPLAIN ANALYZE view with whatever
metrics accrued before death), the unified metrics snapshot, the last N
span events, the active fault-injection spec + its fired log, and the
buffer catalog's tier occupancy. Every field is bounded so a bundle is
kilobytes, not a heap dump.

Only imported from the failure path (and never on query success), so it
may import freely; ``maybe_emit_bundle`` itself must NEVER raise — a
broken diagnostic must not mask the real error.
"""
from __future__ import annotations

import json
import os
import time
import traceback

from ..conf import ConfEntry, register

DIAG_DIR = register(ConfEntry(
    "spark.rapids.obs.diagnostics.dir", "",
    "When set, a query failure emits a bounded diagnostic bundle "
    "(diag_<query_id>_<unix-ms>.json: annotated plan, metrics snapshot, "
    "last span events, fault config + fired log, catalog tier occupancy, "
    "plus the profiler's operator cost table / HBM tail and the current "
    "metering books when profiling is on) into this directory. Empty "
    "(default): no bundle, no overhead."))
DIAG_MAX_SPAN_EVENTS = register(ConfEntry(
    "spark.rapids.obs.diagnostics.maxSpanEvents", 256,
    "How many trailing span events a diagnostic bundle carries.",
    conv=int))

_MAX_MSG = 4096       # error message / traceback cap, chars
_MAX_FAULT_LOG = 64   # fired-fault audit entries carried


def _catalog_view(ctx) -> dict:
    # read the catalog out of the stage cache only if one was actually
    # built — a failure before first spill should not construct one now
    cache = getattr(ctx, "cache", None)
    cat = cache.get("catalog") if isinstance(cache, dict) else None
    if cat is None:
        return {}
    view = {}
    try:
        view["metrics"] = dict(cat.metrics)
    # enginelint: disable=RL001 (diag view is best-effort; section omitted on failure)
    except Exception:
        pass
    try:
        view["tier_occupancy"] = cat.tier_occupancy()
    # enginelint: disable=RL001 (diag view is best-effort; section omitted on failure)
    except Exception:
        pass
    try:
        # cross-query picture at death: who held HBM and whether this
        # query died mid-grant-wait (reserved_bytes > 0) — the first
        # question in an OOM-storm post-mortem
        gov = getattr(cat, "governor", None)
        if gov is not None:
            view["governor"] = {"queries": gov.query_stats(),
                                "reserved_bytes": gov.reserved_bytes()}
    # enginelint: disable=RL001 (diag view is best-effort; section omitted on failure)
    except Exception:
        pass
    return view


def _fault_view(ctx) -> dict:
    spec = None
    try:
        spec = ctx.conf.settings.get("spark.rapids.test.faults")
    # enginelint: disable=RL001 (conf read is best-effort for the bundle)
    except Exception:
        pass
    # fault registries hang off transports / readers parked in the stage
    # cache; any of them carries the same audit log shape
    fired = []
    cache = getattr(ctx, "cache", None)
    if isinstance(cache, dict):
        for v in list(cache.values()):
            reg = v if hasattr(v, "log") and hasattr(v, "check") \
                else getattr(v, "faults", None)
            if reg is None or not hasattr(reg, "log"):
                continue
            try:
                fired = [dict(e) if isinstance(e, dict) else str(e)
                         for e in list(reg.log)[-_MAX_FAULT_LOG:]]
            # enginelint: disable=RL001 (fault audit log is best-effort; section left empty)
            except Exception:
                fired = []
            if fired:
                break
    return {"spec": spec, "fired": fired}


def _lifecycle_view(ctx) -> dict:
    """Query lifecycle state at emission time (exec/lifecycle.py): a
    deadline-exceeded bundle shows DEADLINE_EXCEEDED with the timeout
    that tripped, a cancel shows CANCELLED, so the first line of
    triage — 'did it die or was it killed?' — is in the bundle."""
    try:
        lc = ctx.cache.get("lifecycle")
        if lc is None:
            return {}
        return {"state": lc.state,
                "timeout_s": lc.timeout,
                "deadline_remaining_s": lc.remaining(),
                "cancel_requested": lc.cancel_event.is_set()}
    # enginelint: disable=RL001 (lifecycle view is best-effort; section omitted)
    except Exception:
        return {}


def maybe_emit_bundle(ctx, plan, error, out_dir: str) -> str | None:
    """Write ``diag_<query_id>_<unix-ms>.json`` into ``out_dir``.

    Returns the path written, or None. Never raises.
    """
    try:
        os.makedirs(out_dir, exist_ok=True)
        query_id = getattr(ctx, "query_id", None) or "unknown"
        tracer = getattr(ctx, "tracer", None)
        try:
            max_ev = int(ctx.conf.get(DIAG_MAX_SPAN_EVENTS))
        # enginelint: disable=RL001 (bad conf value falls back to the default event cap)
        except Exception:
            max_ev = 256

        bundle: dict = {
            "kind": "spark_rapids_tpu.diagnostic_bundle",
            "version": 1,
            "query_id": query_id,
            "trace_id": getattr(tracer, "trace_id", None) or query_id,
            "emitted_unix_s": time.time(),
            "error": {
                "type": type(error).__name__,
                "message": str(error)[:_MAX_MSG],
                "traceback": "".join(traceback.format_exception(
                    type(error), error, error.__traceback__))[-_MAX_MSG:],
            },
        }

        try:
            from ..plan.overrides import explain_analyze
            bundle["plan_analyzed"] = explain_analyze(plan, ctx).splitlines() \
                if plan is not None else []
        # enginelint: disable=RL001 (plan render is best-effort; section left empty)
        except Exception:
            bundle["plan_analyzed"] = []

        try:
            from .registry import query_metrics_snapshot
            bundle["metrics"] = query_metrics_snapshot(ctx)
        # enginelint: disable=RL001 (metrics snapshot is best-effort; section left empty)
        except Exception:
            bundle["metrics"] = {}

        bundle["span_events"] = (tracer.events_snapshot(last=max_ev)
                                 if tracer is not None else [])
        try:
            # where the time and HBM actually went before death: the
            # profiler's operator cost table + HBM tail and the current
            # metering books.  Read off ctx.cache (never the lazy
            # property) so a disabled-profile failure does not import
            # the profiler modules here
            prof = ctx.cache.get("profiler") \
                if isinstance(getattr(ctx, "cache", None), dict) else None
            if prof is not None:
                bundle["profile"] = {
                    **prof.history_blob(),
                    "hbm_tail": prof.hbm_timeline(last=64),
                }
                from .metering import get_meter
                meter = get_meter()
                bundle["metering"] = {
                    "tenants": meter.snapshot()["tenants"],
                    "totals": meter.totals(),
                }
        # enginelint: disable=RL001 (profile/metering view is best-effort; section omitted)
        except Exception:
            pass
        bundle["faults"] = _fault_view(ctx)
        bundle["catalog"] = _catalog_view(ctx)
        bundle["lifecycle"] = _lifecycle_view(ctx)
        try:
            # recent query history: was this failure the first of a streak,
            # or query N of a tenant that has been failing all morning?
            hist_dir = ctx.conf.settings.get("spark.rapids.obs.history.dir")
            if hist_dir:
                from .history import read_history_tail
                bundle["history_tail"] = read_history_tail(hist_dir)
        # enginelint: disable=RL001 (history tail is best-effort; section omitted)
        except Exception:
            pass
        try:
            bundle["conf"] = {k: v for k, v in ctx.conf.settings.items()
                              if str(k).startswith("spark.")}
        # enginelint: disable=RL001 (conf snapshot is best-effort; section left empty)
        except Exception:
            bundle["conf"] = {}

        path = os.path.join(
            out_dir, f"diag_{query_id}_{int(time.time() * 1000)}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)
        return path
    # enginelint: disable=RL001 (bundle emission must never mask the original query error)
    except Exception:
        return None

"""Always-on operator/device cost attribution + HBM occupancy timeline.

The reference wires GpuMetricNames into every GpuExec and brackets the
hot paths in NVTX ranges so Nsight can say where a query's device time
went (PAPER.md §L5, GpuExec.scala:27-56); this engine's analog rides
the instrumentation that already exists — the per-(operator, partition)
summary the base PlanNode wrapper records at iterator exhaustion — so
profiling adds ONE bounded record per operator-partition, never
per-batch work (the <3% warm-overhead budget ci/premerge.sh enforces).

Three surfaces per query:

* **operator cost table** — active (device) seconds, wall, batches,
  rows per operator; fused stages and mesh regions additionally
  attribute their time across member ops via ``fused_ops`` /
  ``region_ops`` metadata, so a FusedStageExec no longer hides which
  member burned the time.
* **flamegraph** — collapsed-stack text (``query;container;member N``)
  loadable by any flamegraph renderer, plus Perfetto counter tracks
  (ph="C") merged into the query's existing trace_event timeline.
* **HBM occupancy timeline** — a ring-buffer sampler over the live
  BufferCatalogs (and the governor's per-query ledger when it is on):
  per-query device bytes and watermark position over time, integrated
  into HBM-byte-seconds for metering, served at ``/profile``.

Import discipline: ExecCtx gates on the RAW conf string, so with
``spark.rapids.obs.profile.enabled`` unset this module (and
``obs.metering``) is never imported — ci/premerge.sh asserts
sys.modules stays clean and the disabled path stays byte-identical.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
import weakref

from spark_rapids_tpu.conf import ConfEntry, register, _bool
from spark_rapids_tpu.obs.metering import get_meter
from spark_rapids_tpu.obs.registry import get_registry

__all__ = ["PROFILE_ENABLED", "PROFILE_DIR", "QueryProfiler",
           "ProfileStore", "get_store", "live_progress", "profile_view",
           "drain_hbm_for_shipping", "ingest_worker_hbm"]

PROFILE_ENABLED = register(ConfEntry(
    "spark.rapids.obs.profile.enabled", False,
    "Cost-attribution plane: per-operator device/wall attribution "
    "(fused-stage and mesh-region members included), HBM occupancy "
    "timeline, and per-tenant metering (/profile, /tenants). Off by "
    "default: the disabled path never imports obs.profile/obs.metering "
    "and adds no per-batch work (premerge gates overhead < 3%).",
    conv=_bool))
PROFILE_DIR = register(ConfEntry(
    "spark.rapids.obs.profile.dir", "",
    "When set, every profiled query exports profile_<query_id>.json "
    "(operator cost table + HBM timeline, schema ci/obs_schema.json) "
    "and flamegraph_<query_id>.txt (collapsed-stack text) into this "
    "directory at ExecCtx close. Empty (default): in-memory only "
    "(still served at /profile and embedded in diag bundles)."))
PROFILE_HBM_INTERVAL_MS = register(ConfEntry(
    "spark.rapids.obs.profile.hbm.intervalMs", 50,
    "HBM occupancy sampling period for the ring-buffer timeline; one "
    "process-wide daemon thread samples every live profiled query's "
    "catalog (and the governor ledger when it is on).",
    conv=int))
PROFILE_HBM_MAX_SAMPLES = register(ConfEntry(
    "spark.rapids.obs.profile.hbm.maxSamples", 2048,
    "Ring-buffer bound on retained HBM occupancy samples (per query "
    "and process-wide): older samples rotate out; the byte-seconds "
    "integral keeps accumulating regardless.",
    conv=int))
PROFILE_MAX_OPS = register(ConfEntry(
    "spark.rapids.obs.profile.maxOps", 256,
    "Bound on distinct operator rows per query cost table; overflow "
    "folds into an \"(other)\" row so a pathological plan cannot grow "
    "the profiler without limit.",
    conv=int))


# ---------------------------------------------------------------------------
# HBM occupancy sampler (process-wide)
# ---------------------------------------------------------------------------

class _HbmSampler:
    """One daemon thread sampling every live :class:`QueryProfiler`'s
    catalog occupancy.  Starts on the first profiler registration and
    exits when the last one unregisters — a process that never profiles
    never spawns it.  Each tick also integrates the PROCESS total into
    the meter's independent hbm-byte-seconds ledger (the conservation
    counterpart of the per-query integrals)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._profilers: dict[int, "weakref.ref"] = {}
        self._thread: "threading.Thread | None" = None
        self._interval = 0.05
        self._samples: collections.deque = collections.deque(maxlen=2048)
        self._remote: dict[str, collections.deque] = {}
        self._seq = 0
        self._ship_seq = 0
        self._last_t: "float | None" = None
        self.total_byte_seconds = 0.0

    def register(self, prof: "QueryProfiler") -> None:
        with self._lock:
            first = not self._profilers
            self._profilers[id(prof)] = weakref.ref(prof)
            self._interval = prof.hbm_interval_s if first \
                else min(self._interval, prof.hbm_interval_s)
            if first and not self._samples and \
                    self._samples.maxlen != prof.hbm_max_samples:
                self._samples = collections.deque(
                    maxlen=prof.hbm_max_samples)
            if self._thread is None:
                self._last_t = None
                self._thread = threading.Thread(
                    target=self._loop, name="obs-hbm-sampler", daemon=True)
                self._thread.start()

    def unregister(self, prof: "QueryProfiler") -> None:
        with self._lock:
            self._profilers.pop(id(prof), None)

    def _loop(self) -> None:
        while True:
            time.sleep(self._interval)
            with self._lock:
                refs = list(self._profilers.values())
                if not refs:
                    self._thread = None
                    return
            self._tick(refs)

    def _tick(self, refs) -> None:
        now = time.time()
        dt = 0.0 if self._last_t is None else max(0.0, now - self._last_t)
        self._last_t = now
        per_query: dict[str, int] = {}
        total = 0
        for r in refs:
            p = r()
            if p is None:
                continue
            b = p._sample_hbm(now, dt)
            per_query[p.query_id] = b
            total += b
        if dt:
            get_meter().add_total("hbm_byte_seconds", total * dt)
            self.total_byte_seconds += total * dt
        sample = {"unix_s": round(now, 4), "device_bytes": total,
                  "per_query": per_query}
        # governor view only when the governor is actually running —
        # never import-as-side-effect from the sampler thread
        import sys
        gov_mod = sys.modules.get("spark_rapids_tpu.memory.governor")
        if gov_mod is not None:
            try:
                gov = gov_mod.get_governor()
                sample["governor"] = gov.occupancy_sample()
            # enginelint: disable=RL001 (sampler must outlive any governor hiccup; a failed tick just drops the governor lane)
            except Exception:
                pass
        with self._lock:
            self._seq += 1
            sample["seq"] = self._seq
            self._samples.append(sample)

    # -- read side -----------------------------------------------------
    def snapshot(self, last: "int | None" = None) -> list[dict]:
        with self._lock:
            out = list(self._samples)
        return out if last is None else out[-last:]

    def drain_for_shipping(self) -> list[dict]:
        """Samples not yet shipped (worker heartbeat path); each is
        shipped exactly once, like drained spans."""
        with self._lock:
            out = [s for s in self._samples if s["seq"] > self._ship_seq]
            if out:
                self._ship_seq = out[-1]["seq"]
        return out

    def ingest_remote(self, worker_id: str, samples: list[dict]) -> None:
        with self._lock:
            dq = self._remote.setdefault(
                str(worker_id), collections.deque(maxlen=512))
            dq.extend(samples)

    def remote_snapshot(self, last: int = 32) -> dict:
        with self._lock:
            return {w: list(dq)[-last:] for w, dq in self._remote.items()}


_sampler = _HbmSampler()


def drain_hbm_for_shipping() -> list[dict]:
    return _sampler.drain_for_shipping()


def ingest_worker_hbm(worker_id: str, samples: list[dict]) -> None:
    _sampler.ingest_remote(worker_id, samples)


# ---------------------------------------------------------------------------
# Per-query profiler
# ---------------------------------------------------------------------------

class QueryProfiler:
    """Operator cost table + HBM ring buffer for ONE query execution.

    ``record_op`` is called once per (operator, partition) at iterator
    exhaustion by the base PlanNode wrapper — the amortized cost is a
    dict update, not per-batch work.  Containers exposing ``fused_ops``
    / ``region_ops`` split their time equally across members as
    attributed child rows (key ``Container/Member``), keeping the
    container row as the authoritative total."""

    def __init__(self, query_id: str, conf, ctx=None):
        self.query_id = query_id
        self.profile_dir = conf.get(PROFILE_DIR)
        self.max_ops = max(8, conf.get(PROFILE_MAX_OPS))
        self.hbm_interval_s = max(0.001,
                                  conf.get(PROFILE_HBM_INTERVAL_MS) / 1e3)
        self.hbm_max_samples = max(16, conf.get(PROFILE_HBM_MAX_SAMPLES))
        self._ctx = (lambda: None) if ctx is None else weakref.ref(ctx)
        self._lock = threading.Lock()
        self._ops: dict[str, dict] = {}
        self._hbm: collections.deque = collections.deque(
            maxlen=self.hbm_max_samples)
        self._hbm_byte_s = 0.0
        self._hbm_peak = 0
        self._spill_bytes = 0.0
        self._meter = get_meter()
        self._finalized = False
        self._t0 = time.time()
        _sampler.register(self)

    # -- write side (exec hot path) ------------------------------------
    def record_op(self, node, label: str, active_s: float, wall_s: float,
                  batches: int, rows: int, partition: int) -> None:
        """One (operator, partition) exhausted: fold its totals in and
        attribute container time to member ops."""
        members = getattr(node, "fused_ops", None)
        if members is None:
            members = getattr(node, "region_ops", None)
        mem: list[str] = []
        if members:
            try:
                mem = [type(m).__name__ for m in members]
            # enginelint: disable=RL001 (profiling is best-effort attribution; a node with odd metadata still gets its container row)
            except Exception:
                mem = []
        with self._lock:
            self._acc(label, None, active_s, wall_s, batches, rows)
            if mem:
                share, wshare = active_s / len(mem), wall_s / len(mem)
                for ml in mem:
                    self._acc(f"{label}/{ml}", label, share, wshare, 0, 0)
        # the INDEPENDENT process-totals path (conservation contract:
        # tenant charges are derived from this profiler's table instead)
        self._meter.add_total("device_seconds", active_s)
        get_registry().inc("profile.device_seconds", active_s)

    def _acc(self, key: str, parent: "str | None", dev: float,
             wall: float, batches: int, rows: int) -> None:
        e = self._ops.get(key)
        if e is None:
            if len(self._ops) >= self.max_ops:
                key, parent = "(other)", None
                e = self._ops.get(key)
            if e is None:
                e = self._ops[key] = {
                    "op": key.rsplit("/", 1)[-1], "parent": parent,
                    "device_s": 0.0, "wall_s": 0.0,
                    "batches": 0, "rows": 0, "calls": 0}
        e["device_s"] += dev
        e["wall_s"] += wall
        e["batches"] += int(batches)
        e["rows"] += int(rows)
        e["calls"] += 1

    def _sample_hbm(self, now: float, dt: float) -> int:
        """One sampler tick: this query's current device bytes (its
        catalog's ledger; 0 before the catalog exists).  Never CREATES
        the catalog — profiling a host-only query must not allocate
        device machinery."""
        ctx = self._ctx()
        cat = None if ctx is None else ctx.cache.get("catalog")
        used = int(getattr(cat, "device_used", 0) or 0)
        with self._lock:
            self._hbm.append((round(now, 4), used))
            self._hbm_byte_s += used * dt
            if used > self._hbm_peak:
                self._hbm_peak = used
        return used

    # -- read side -----------------------------------------------------
    def operators(self) -> dict:
        with self._lock:
            return {k: dict(e) for k, e in self._ops.items()}

    def device_seconds(self) -> float:
        """Top-level active seconds (member rows are attribution views
        of their container, never counted twice)."""
        with self._lock:
            return sum(e["device_s"] for e in self._ops.values()
                       if e["parent"] is None)

    def hbm_byte_seconds(self) -> float:
        with self._lock:
            return self._hbm_byte_s

    def usage(self) -> dict:
        """This query's charge-side usage (the byte metrics derived
        from registry deltas are added by the session, which owns the
        before-snapshot)."""
        with self._lock:
            dev = sum(e["device_s"] for e in self._ops.values()
                      if e["parent"] is None)
            return {"device_seconds": dev,
                    "hbm_byte_seconds": self._hbm_byte_s,
                    "spill_bytes": self._spill_bytes,
                    "queries": 1}

    def flamegraph(self) -> str:
        """Collapsed-stack text (one ``frame;frame value`` line per
        stack, value = device µs).  Container frames with attributed
        members contribute through their member lines, so totals do not
        double count."""
        ops = self.operators()
        parents = {e["parent"] for e in ops.values() if e["parent"]}
        lines = []
        for key, e in sorted(ops.items()):
            us = int(round(e["device_s"] * 1e6))
            if e["parent"]:
                lines.append(f"{self.query_id};{e['parent']};{e['op']} "
                             f"{us}")
            elif key not in parents:
                lines.append(f"{self.query_id};{e['op']} {us}")
        return "\n".join(lines) + ("\n" if lines else "")

    def hbm_timeline(self, last: "int | None" = None) -> list:
        with self._lock:
            out = [[t, b] for t, b in self._hbm]
        return out if last is None else out[-last:]

    def artifact(self) -> dict:
        """The schema-checked profile document (ci/obs_schema.json
        kind="profile"; scripts/validate_obs.py accepts it)."""
        ops = {}
        for k, e in self.operators().items():
            ops[k] = {"op": e["op"], "parent": e["parent"],
                      "device_s": round(e["device_s"], 6),
                      "wall_s": round(e["wall_s"], 6),
                      "batches": e["batches"], "rows": e["rows"],
                      "calls": e["calls"]}
        with self._lock:
            hbm = {"samples": len(self._hbm),
                   "byte_seconds": round(self._hbm_byte_s, 3),
                   "peak_bytes": self._hbm_peak,
                   "timeline": [[t, b] for t, b in list(self._hbm)[-256:]]}
        return {"kind": "profile", "version": 1,
                "query_id": self.query_id,
                "unix_s": round(self._t0, 3),
                "operators": ops, "hbm": hbm,
                "flamegraph": self.flamegraph()}

    def history_blob(self) -> dict:
        """Compact per-query table for the history entry (no timeline —
        the jsonl must stay one lean line per query)."""
        ops = {k: {"op": e["op"], "parent": e["parent"],
                   "device_s": round(e["device_s"], 6),
                   "wall_s": round(e["wall_s"], 6),
                   "batches": e["batches"], "rows": e["rows"]}
               for k, e in self.operators().items()}
        return {"operators": ops,
                "device_seconds": round(self.device_seconds(), 6),
                "hbm_byte_seconds": round(self.hbm_byte_seconds(), 3)}

    # -- lifecycle -----------------------------------------------------
    def finalize(self, ctx) -> None:
        """End-of-execution hook (ExecCtx.close, BEFORE the catalog is
        popped and BEFORE trace export): capture the catalog's spill
        totals, merge counter tracks into the query trace, and export
        the artifact files.  Idempotent."""
        with self._lock:
            if self._finalized:
                return
            self._finalized = True
        _sampler.unregister(self)
        cat = ctx.cache.get("catalog")
        if cat is not None:
            m = getattr(cat, "metrics", None) or {}
            self._spill_bytes = float(
                m.get("bytes_spilled_to_host", 0)
                + m.get("bytes_spilled_to_disk", 0))
            if self._spill_bytes:
                self._meter.add_total("spill_bytes", self._spill_bytes)
        tracer = ctx.cache.get("tracer")
        if tracer is not None:
            for t_wall, b in self.hbm_timeline():
                tracer.counter("hbm.device_bytes", wall_t=t_wall, bytes=b)
            top = {e["op"]: round(e["device_s"], 6)
                   for e in self.operators().values()
                   if e["parent"] is None}
            if top:
                tracer.counter("operator.device_seconds", **top)
        d = self.profile_dir
        if d:
            # enginelint: disable=RL001 (artifact export is best-effort teardown; the query already finished)
            try:
                os.makedirs(d, exist_ok=True)
                path = os.path.join(d, f"profile_{self.query_id}.json")
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(self.artifact(), f)
                os.replace(tmp, path)
                with open(os.path.join(
                        d, f"flamegraph_{self.query_id}.txt"), "w") as f:
                    f.write(self.flamegraph())
            # enginelint: disable=RL001 (artifact export is best-effort; a full disk must not fail the query)
            except Exception:
                pass


# ---------------------------------------------------------------------------
# Per-fingerprint aggregation (the /profile "where does this PLAN spend")
# ---------------------------------------------------------------------------

class ProfileStore:
    """LRU-bounded per-fingerprint merge of operator cost tables, so
    /profile answers "where does q18 spend" across runs without
    re-reading the history file."""

    def __init__(self, max_fingerprints: int = 128, max_ops: int = 64):
        self.max_fingerprints = max_fingerprints
        self.max_ops = max_ops
        self._lock = threading.Lock()
        self._fps: "collections.OrderedDict" = collections.OrderedDict()

    def note(self, fingerprint: str, operators: dict,
             wall_s: "float | None" = None) -> None:
        if not fingerprint or not operators:
            return
        with self._lock:
            agg = self._fps.get(fingerprint)
            if agg is None:
                agg = self._fps[fingerprint] = {"runs": 0, "wall_s": 0.0,
                                                "operators": {}}
            agg["runs"] += 1
            if isinstance(wall_s, (int, float)):
                agg["wall_s"] += float(wall_s)
            for k, e in operators.items():
                o = agg["operators"].get(k)
                if o is None:
                    if len(agg["operators"]) >= self.max_ops:
                        continue
                    o = agg["operators"][k] = {
                        "op": e.get("op", k), "parent": e.get("parent"),
                        "device_s": 0.0, "wall_s": 0.0, "rows": 0}
                o["device_s"] += float(e.get("device_s", 0.0))
                o["wall_s"] += float(e.get("wall_s", 0.0))
                o["rows"] += int(e.get("rows", 0))
            self._fps.move_to_end(fingerprint)
            while len(self._fps) > self.max_fingerprints:
                self._fps.popitem(last=False)

    def snapshot(self) -> dict:
        with self._lock:
            return {fp: {"runs": a["runs"],
                         "wall_s": round(a["wall_s"], 4),
                         "operators": {
                             k: {kk: (round(vv, 6)
                                      if isinstance(vv, float) else vv)
                                 for kk, vv in o.items()}
                             for k, o in a["operators"].items()}}
                    for fp, a in self._fps.items()}


_store: "ProfileStore | None" = None
_store_lock = threading.Lock()


def get_store() -> ProfileStore:
    global _store
    with _store_lock:
        if _store is None:
            _store = ProfileStore()
        return _store


# ---------------------------------------------------------------------------
# Live progress / HTTP view helpers
# ---------------------------------------------------------------------------

def live_progress(lc, index) -> dict:
    """Progress fields for one in-flight query: rows processed so far,
    percent complete and ETA against the fingerprint's historical
    medians (HistoryIndex).  Partial knowledge degrades gracefully —
    rows without history, history without rows, or neither."""
    out: dict = {}
    rows = None
    ctx = getattr(lc, "ctx", None)
    if ctx is not None:
        try:
            rows = int(sum(m.values.get("numOutputRows", 0.0)
                           for m in list(ctx.metrics.values())))
        # enginelint: disable=RL001 (a snapshot racing operator registration just skips this poll)
        except Exception:
            rows = None
    if rows is not None:
        out["rows_processed"] = rows
    fp = getattr(lc, "plan_fingerprint", None)
    stats = index.lookup(fp) if (index is not None and fp) else None
    if not stats:
        return out
    med_rows = stats.get("median_rows")
    med_wall = stats.get("median_wall_s")
    started = getattr(lc, "_started_at", None)
    elapsed = None if started is None else time.monotonic() - started
    pct = None
    if med_rows and rows:
        pct = min(0.99, rows / med_rows)
    elif med_wall and elapsed is not None:
        pct = min(0.99, elapsed / med_wall)
    if pct is not None:
        out["percent_complete"] = round(100.0 * pct, 1)
        if elapsed is not None and pct > 0:
            out["eta_s"] = round(max(0.0, elapsed * (1.0 - pct) / pct), 3)
    if med_wall is not None:
        out["median_wall_s"] = round(med_wall, 4)
    return out


def profile_view(session) -> dict:
    """The /profile HTTP body: process HBM timeline (+ per-worker lanes
    shipped over heartbeats), per-fingerprint cost tables, and a brief
    per-live-query line."""
    out: dict = {
        "enabled": True,
        "hbm": {"byte_seconds": round(_sampler.total_byte_seconds, 3),
                "samples": _sampler.snapshot(last=120),
                "workers": _sampler.remote_snapshot()},
        "fingerprints": get_store().snapshot(),
    }
    live: dict = {}
    with session._lc_cond:
        lcs = dict(session._live)
    for qid, lc in lcs.items():
        ctx = getattr(lc, "ctx", None)
        prof = None if ctx is None else ctx.cache.get("profiler")
        if prof is None:
            continue
        tl = prof.hbm_timeline(last=1)
        live[qid] = {"device_seconds": round(prof.device_seconds(), 6),
                     "hbm_bytes": tl[-1][1] if tl else 0,
                     "hbm_byte_seconds": round(prof.hbm_byte_seconds(),
                                               3)}
    out["live"] = live
    return out

"""Request-scoped span tracing with Perfetto/Chrome ``trace_event`` export.

Dapper-style (Sigelman et al., 2010): one ``query_id``/``trace_id`` pair
is minted per execution and every span/event carries it, so a reduce-side
fetch, its retries, and any lineage recompute — possibly on another
process, propagated through the TCP fetch request — all land under the
originating query's trace.  The reference plugin leans on NVTX ranges +
the Spark SQL UI for the same story (GpuExec withResources/NvtxWithMetrics);
this headless engine exports the Chrome ``trace_event`` JSON array format
(ph="X" complete events, ph="i" instants, µs timestamps) which both
Perfetto and chrome://tracing load directly, alongside the existing xprof
hook (`spark.rapids.tpu.profile.dir`).

This module is only imported when `spark.rapids.obs.trace.enabled` is set
(ExecCtx checks the raw conf string first) or when a diagnostic bundle is
being emitted — the disabled path never touches it (ci/premerge.sh gate).
"""
from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
import uuid
from collections import deque

from ..conf import ConfEntry, register, _bool

TRACE_ENABLED = register(ConfEntry(
    "spark.rapids.obs.trace.enabled", False,
    "Open a span per query/stage/partition/operator and record them as "
    "Chrome trace_event dicts with a propagated query_id/trace_id "
    "(carried across the TCP shuffle wire). Off by default: the disabled "
    "path never imports the tracer and adds no per-batch work.",
    conv=_bool))
TRACE_DIR = register(ConfEntry(
    "spark.rapids.obs.trace.dir", "",
    "When set, ExecCtx.close() exports the query's trace as "
    "trace_<query_id>.json (Perfetto/Chrome trace_event JSON) into this "
    "directory. Empty (default): spans are kept in memory only (still "
    "available to diagnostics bundles and EXPLAIN ANALYZE)."))
TRACE_MAX_EVENTS = register(ConfEntry(
    "spark.rapids.obs.trace.maxEvents", 10000,
    "Bounded span-event buffer per query: oldest events are dropped past "
    "this count so a long query cannot grow the tracer without limit.",
    conv=int))


def new_query_id() -> str:
    """16-hex-char query id; doubles as the default trace id."""
    return uuid.uuid4().hex[:16]


class _Span:
    """One open span; append-only until closed. Not a context manager
    itself — ``Tracer.span`` wraps open/close with parent bookkeeping."""

    __slots__ = ("name", "cat", "span_id", "parent_id", "t0", "args")

    def __init__(self, name, cat, span_id, parent_id, args):
        self.name = name
        self.cat = cat
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = time.perf_counter()
        self.args = args

    def annotate(self, **kv):
        self.args.update(kv)


class Tracer:
    """Per-query tracer: bounded event buffer + thread-local span stacks.

    Spans nest per-thread (each worker thread sees its own parent chain),
    but generator-driven operators can suspend mid-span and close out of
    order — the stack pop is therefore by identity, not strictly LIFO.
    All methods are safe to call from multiple threads.
    """

    def __init__(self, query_id: str | None = None,
                 trace_id: str | None = None, max_events: int = 10000):
        self.query_id = query_id or new_query_id()
        self.trace_id = trace_id or self.query_id
        self._events: deque = deque(maxlen=max(1, int(max_events)))
        self._dropped = 0
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._tls = threading.local()
        # trace_event ts fields are µs relative to a common origin
        self._origin = time.perf_counter()
        self._wall_origin = time.time()
        self.pid = os.getpid()
        # pid -> ph="M" process_name metadata, kept OUTSIDE the bounded
        # deque so lane names survive event-buffer rotation; prepended
        # at export (Perfetto reads metadata in any position, but names
        # must not be evictable)
        self._lanes: dict[int, dict] = {}
        # terminal lifecycle state (CANCELLED / DEADLINE_EXCEEDED / ...)
        # stamped by the query root when the run ends abnormally; carried
        # in the export header so a trace says WHY it ends early
        self.query_state: str | None = None

    # -- internals ---------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _ts_us(self, t: float) -> float:
        return (t - self._origin) * 1e6

    def _base_args(self, span_id, parent_id) -> dict:
        return {"query_id": self.query_id, "trace_id": self.trace_id,
                "span_id": span_id, "parent_id": parent_id}

    def _push(self, ev: dict):
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)

    # -- span API ----------------------------------------------------------

    def current_span_id(self) -> int | None:
        st = self._stack()
        return st[-1].span_id if st else None

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "query", *,
             parent_id: int | None = None, **args):
        """Open a span; yields the span object for ``annotate(**kv)``.

        ``parent_id`` overrides the thread-local parent — used when the
        logical parent lives on another thread (worker pools) or another
        process (the TCP server re-parents onto the propagated span id).
        """
        st = self._stack()
        if parent_id is None:
            parent_id = st[-1].span_id if st else None
        sp = _Span(name, cat, next(self._ids), parent_id, dict(args))
        st.append(sp)
        try:
            yield sp
        finally:
            # identity removal: suspended generators may close spans out
            # of LIFO order on this thread
            try:
                st.remove(sp)
            except ValueError:
                pass
            t1 = time.perf_counter()
            ev = {"name": name, "cat": cat, "ph": "X",
                  "ts": self._ts_us(sp.t0), "dur": (t1 - sp.t0) * 1e6,
                  "pid": self.pid, "tid": threading.get_ident(),
                  "args": {**self._base_args(sp.span_id, sp.parent_id),
                           **sp.args}}
            self._push(ev)

    def event(self, name: str, cat: str = "query", *,
              parent_id: int | None = None, **args):
        """Record an instant event under the current (or given) span."""
        if parent_id is None:
            parent_id = self.current_span_id()
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": self._ts_us(time.perf_counter()),
              "pid": self.pid, "tid": threading.get_ident(),
              "args": {**self._base_args(next(self._ids), parent_id),
                       **args}}
        self._push(ev)

    def complete(self, name: str, cat: str, t0: float, t1: float, *,
                 parent_id: int | None = None, **args):
        """Record an already-timed span (perf_counter endpoints)."""
        if parent_id is None:
            parent_id = self.current_span_id()
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": self._ts_us(t0), "dur": (t1 - t0) * 1e6,
              "pid": self.pid, "tid": threading.get_ident(),
              "args": {**self._base_args(next(self._ids), parent_id),
                       **args}}
        self._push(ev)

    def counter(self, name: str, *, t: "float | None" = None,
                wall_t: "float | None" = None, **values):
        """Record a Perfetto counter sample (ph="C"): ``values`` are
        the numeric series plotted as a stacked counter track.  ``t``
        is a perf_counter timestamp; ``wall_t`` an absolute
        ``time.time()`` one (the HBM sampler's clock) rebased onto this
        tracer's origin; neither = now.  The cost-attribution plane
        (obs/profile.py) merges HBM occupancy and per-operator
        device-seconds lanes into the query trace through this."""
        if wall_t is not None:
            ts = (wall_t - self._wall_origin) * 1e6
        else:
            ts = self._ts_us(time.perf_counter() if t is None else t)
        ev = {"name": name, "cat": "counter", "ph": "C", "ts": ts,
              "pid": self.pid, "tid": 0,
              "args": {**self._base_args(next(self._ids), None),
                       **values}}
        self._push(ev)

    def set_query_state(self, state: str) -> None:
        """Record the query's terminal lifecycle state (exec/lifecycle)."""
        self.query_state = state

    # -- cluster aggregation ----------------------------------------------

    def ensure_lane(self, pid: int, name: str) -> None:
        """Name a process lane (driver, each worker) with a ph="M"
        process_name metadata record — ONE Perfetto timeline then shows
        every process's spans on its own labelled track."""
        with self._lock:
            if pid in self._lanes:
                return
            self._lanes[pid] = {
                "name": "process_name", "cat": "__metadata", "ph": "M",
                "pid": pid, "tid": 0, "ts": 0,
                "args": {**self._base_args(next(self._ids), None),
                         "name": name},
            }

    def drain_events(self) -> list[dict]:
        """Pop and return every buffered event (oldest first).  Used by
        cluster workers to ship spans incrementally on heartbeats and
        fragment completion — an event is shipped exactly once."""
        with self._lock:
            evs = list(self._events)
            self._events.clear()
        return evs

    def ingest_wall(self, events: list[dict]) -> None:
        """Merge events whose ``ts`` is ABSOLUTE wall-clock µs (see
        :func:`stamp_for_shipping`) into this tracer's buffer, rebased
        onto its own origin so driver and worker spans share one
        timeline.  Clock skew between processes on one host is bounded
        by NTP-free time.time() drift — microseconds over a query."""
        base = self._wall_origin * 1e6
        for ev in events:
            ev = dict(ev)
            ev["ts"] = ev.get("ts", 0.0) - base
            self._push(ev)

    # -- export ------------------------------------------------------------

    def events_snapshot(self, last: int | None = None) -> list[dict]:
        with self._lock:
            evs = list(self._lanes.values()) + list(self._events)
        if last is not None and last >= 0:
            evs = evs[-last:]
        return evs

    def export(self, path: str) -> str:
        """Write Perfetto/Chrome trace JSON; returns the path written."""
        doc = {
            "traceEvents": self.events_snapshot(),
            "displayTimeUnit": "ms",
            "otherData": {
                "query_id": self.query_id,
                "trace_id": self.trace_id,
                "wall_clock_origin_unix_s": self._wall_origin,
                "events_dropped": self._dropped,
            },
        }
        if self.query_state is not None:
            doc["otherData"]["query_state"] = self.query_state
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def trace_header(self) -> dict:
        """Propagation header carried in TCP fetch requests: enough for
        the serving side to attribute its work to this query's trace."""
        hdr = {"query_id": self.query_id, "trace_id": self.trace_id}
        sid = self.current_span_id()
        if sid is not None:
            hdr["span_id"] = sid
        return hdr


def stamp_for_shipping(events: list[dict], wall_origin: float,
                       pid: int) -> list[dict]:
    """Prepare drained events for cross-process shipping: rewrite each
    ``ts`` from tracer-origin-relative µs to ABSOLUTE wall-clock µs
    (``wall_origin`` is the shipping tracer's ``_wall_origin``) and
    stamp the shipping process's pid, so the receiving driver can rebase
    onto ITS origin (:meth:`Tracer.ingest_wall`) and keep per-worker
    lanes distinct."""
    base = wall_origin * 1e6
    out = []
    for ev in events:
        ev = dict(ev)
        ev["ts"] = ev.get("ts", 0.0) + base
        ev["pid"] = pid
        out.append(ev)
    return out

"""Host (CPU oracle) batch kernels: sort, group-by, filter, concat, slice.

The reference uses CPU Spark itself as the differential-test oracle
(tests/SparkQueryCompareTestSuite.scala:153-167,
integration_tests asserts.py:290 ``assert_gpu_and_cpu_are_equal_collect``).
This framework is standalone, so the CPU engine lives here: numpy-vectorized
implementations with exactly Spark's ordering/equality semantics (null
ordering, NaN largest + NaN==NaN for keys, -0.0==0.0).  These also serve as
the CPU baseline that `bench.py` compares the TPU path against.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.host.batch import HostBatch, HostColumn
from spark_rapids_tpu.ops.segmented import AggSpec
from spark_rapids_tpu.ops.sort import SortOrder

__all__ = [
    "host_sort_permutation", "host_sort", "host_filter", "host_concat",
    "host_slice", "host_group_by", "host_take",
    "host_join", "host_join_output",
]


def _f64_sortable_bits(x: np.ndarray) -> np.ndarray:
    """IEEE754 -> uint64 total order (NaN above +inf, -0.0 == +0.0)."""
    x = x.astype(np.float64)
    x = np.where(x == 0.0, 0.0, x)                  # -0.0 -> +0.0
    x = np.where(np.isnan(x), np.float64("nan"), x)  # canonical NaN
    bits = x.view(np.uint64).copy()
    neg = bits >> np.uint64(63) != 0
    bits = np.where(neg, ~bits, bits | np.uint64(1) << np.uint64(63))
    # canonical NaN (0x7ff8...) encodes above +inf already via the flip
    return bits


def _key_codes(col: HostColumn, ascending: bool,
               nulls_first: bool) -> list[np.ndarray]:
    """Encode a column as sortable integer key arrays (most-significant
    first).  Null indicator precedes the value key."""
    v = col.validity
    null_key = np.where(v, np.uint8(1 if nulls_first else 0),
                        np.uint8(0 if nulls_first else 1))
    dt = col.dtype
    if isinstance(dt, T.StringType):
        s = np.array(["" if x is None else x for x in col.data], dtype=str)
        _, codes = np.unique(s, return_inverse=True)
        codes = codes.astype(np.int64)
        val = codes if ascending else -codes
    elif dt.fractional:
        bits = _f64_sortable_bits(col.data)
        val = bits if ascending else ~bits
    else:
        u = col.data.astype(np.int64).view(np.uint64) ^ (np.uint64(1) << np.uint64(63))
        val = u if ascending else ~u
    val = np.where(v, val, np.zeros((), val.dtype))
    return [null_key, val]


def host_sort_permutation(batch: HostBatch,
                          orders: Sequence[SortOrder]) -> np.ndarray:
    """Stable permutation sorting the batch by ``orders``."""
    keys: list[np.ndarray] = []
    for o in orders:
        keys.extend(_key_codes(batch.columns[o.child_index], o.ascending,
                               o.resolved_nulls_first))
    if not keys:
        return np.arange(batch.num_rows)
    # np.lexsort: LAST key is primary -> reverse
    return np.lexsort(list(reversed(keys)))


def host_sort(batch: HostBatch, orders: Sequence[SortOrder]) -> HostBatch:
    perm = host_sort_permutation(batch, orders)
    return HostBatch([c.take(perm) for c in batch.columns], batch.schema)


def host_take(batch: HostBatch, indices: np.ndarray) -> HostBatch:
    return HostBatch([c.take(indices) for c in batch.columns], batch.schema)


def host_filter(batch: HostBatch, mask: np.ndarray) -> HostBatch:
    return HostBatch([c.filter(mask) for c in batch.columns], batch.schema)


def host_slice(batch: HostBatch, start: int, end: int) -> HostBatch:
    idx = np.arange(max(start, 0), min(end, batch.num_rows))
    return host_take(batch, idx)


def host_concat(batches: Sequence[HostBatch]) -> HostBatch:
    assert batches, "empty concat"
    schema = batches[0].schema
    cols = []
    for i, f in enumerate(schema):
        data = np.concatenate([b.columns[i].data for b in batches])
        validity = np.concatenate([b.columns[i].validity for b in batches])
        cols.append(HostColumn(data, validity, f.data_type))
    return HostBatch(cols, schema)


# ---------------------------------------------------------------------------
# group-by (oracle analog of ops.segmented.sorted_group_by)
# ---------------------------------------------------------------------------

def _group_codes(col: HostColumn) -> list[np.ndarray]:
    """Key arrays (null indicator + value code) where equal values (Spark
    key equality: null==null, NaN==NaN, -0.0==0.0) get equal codes, ordered
    ascending with nulls first.  The separate null indicator avoids any
    value/null sentinel collision."""
    v = col.validity
    dt = col.dtype
    if isinstance(dt, T.StringType):
        s = np.array(["" if x is None else x for x in col.data], dtype=str)
        _, codes = np.unique(s, return_inverse=True)
        codes = codes.astype(np.int64)
    elif dt.fractional:
        codes = _f64_sortable_bits(col.data).view(np.int64)
    else:
        codes = col.data.astype(np.int64)
    return [v.astype(np.uint8), np.where(v, codes, np.int64(0))]


def _agg_reduce(spec: AggSpec, col: HostColumn | None, seg_starts: np.ndarray,
                seg_lens: np.ndarray, perm: np.ndarray,
                in_type: T.DataType) -> HostColumn:
    """Compute one aggregate per segment of the permuted batch."""
    ngroups = len(seg_starts)
    res_type = spec.result_type(in_type)
    if spec.op == "count_star":
        data = seg_lens.astype(np.int64)
        return HostColumn(data, np.ones(ngroups, np.bool_), T.LongType())
    assert col is not None
    pv = col.validity[perm]
    out_valid = np.zeros(ngroups, np.bool_)
    if isinstance(res_type, T.StringType):
        out = np.empty(ngroups, dtype=object)
    else:
        out = np.zeros(ngroups, dtype=res_type.np_dtype)
    pd = col.data[perm]
    for g in range(ngroups):
        sl = slice(seg_starts[g], seg_starts[g] + seg_lens[g])
        seg_d, seg_v = pd[sl], pv[sl]
        vals = seg_d[seg_v]
        if spec.op == "count":
            out[g] = len(vals)
            out_valid[g] = True
            continue
        if spec.op in ("first", "last"):
            # first/last including nulls (ignoreNulls=False)
            if seg_lens[g] > 0:
                i = 0 if spec.op == "first" else seg_lens[g] - 1
                if seg_v[i]:
                    out[g] = seg_d[i]
                    out_valid[g] = True
            continue
        if len(vals) == 0:
            continue
        if spec.op == "sum":
            if res_type.integral:
                out[g] = np.int64(np.sum(vals.astype(np.int64), dtype=np.int64))
            else:
                out[g] = np.sum(vals.astype(np.float64))
            out_valid[g] = True
        elif spec.op == "min":
            out[g] = _nan_aware_min(vals, in_type)
            out_valid[g] = True
        elif spec.op == "max":
            out[g] = _nan_aware_max(vals, in_type)
            out_valid[g] = True
        elif spec.op == "avg":
            out[g] = np.sum(vals.astype(np.float64)) / len(vals)
            out_valid[g] = True
        elif spec.op == "first_non_null":
            out[g] = vals[0]
            out_valid[g] = True
        elif spec.op == "last_non_null":
            out[g] = vals[-1]
            out_valid[g] = True
        elif spec.op == "percentile":
            # same algorithm as the device kernel (sort + linear
            # interpolation at q*(n-1)) so differential tests compare
            # bit-for-bit, not vs np.percentile's internals
            v = np.sort(vals.astype(np.float64))
            pos = (len(v) - 1) * spec.param
            lo, hi = int(np.floor(pos)), int(np.ceil(pos))
            out[g] = v[lo] + (v[hi] - v[lo]) * (pos - lo)
            out_valid[g] = True
        else:
            raise NotImplementedError(spec.op)
    return HostColumn(out, out_valid, res_type)


def _nan_aware_min(vals, dt: T.DataType):
    if isinstance(dt, T.StringType):
        return min(vals)
    if dt.fractional:
        # Spark: NaN is largest -> min ignores NaN unless all NaN
        nn = vals[~np.isnan(vals.astype(np.float64))]
        return np.min(nn) if len(nn) else vals[0]
    return np.min(vals)


def _nan_aware_max(vals, dt: T.DataType):
    if isinstance(dt, T.StringType):
        return max(vals)
    if dt.fractional:
        # Spark: NaN is the LARGEST value, so any NaN wins outright
        # (fuzz-found: argmax over inf-masked values picked a real +inf
        # when both +inf and NaN were present)
        f = vals.astype(np.float64)
        if np.isnan(f).any():
            return np.asarray(np.nan, dtype=vals.dtype)[()]
        return np.max(vals)
    return np.max(vals)


def host_group_by(batch: HostBatch, key_indices: Sequence[int],
                  aggs: Sequence[AggSpec]) -> HostBatch:
    """Group ``batch`` by keys computing ``aggs``; output = keys then aggs,
    groups in ascending key order (matches device sorted_group_by)."""
    n = batch.num_rows
    if key_indices:
        codes: list[np.ndarray] = []
        for k in key_indices:
            codes.extend(_group_codes(batch.columns[k]))
        perm = np.lexsort(list(reversed(codes)))
        pc = [c[perm] for c in codes]
        if n == 0:
            boundaries = np.zeros(0, np.bool_)
        else:
            differ = np.zeros(n, np.bool_)
            differ[0] = True
            for c in pc:
                differ[1:] |= c[1:] != c[:-1]
            boundaries = differ
        seg_starts = np.nonzero(boundaries)[0]
        seg_lens = np.diff(np.append(seg_starts, n))
    else:
        perm = np.arange(n)
        seg_starts = np.zeros(1, np.int64)
        seg_lens = np.array([n], np.int64)

    out_cols: list[HostColumn] = []
    out_fields: list[T.StructField] = []
    for k in key_indices:
        col = batch.columns[k]
        out_cols.append(col.take(perm[seg_starts]))
        out_fields.append(batch.schema.fields[k])
    for spec in aggs:
        col = batch.columns[spec.child_index] if spec.op != "count_star" else None
        in_t = col.dtype if col is not None else T.LongType()
        out_cols.append(_agg_reduce(spec, col, seg_starts, seg_lens, perm, in_t))
        arg = "1" if spec.op == "count_star" else batch.schema.names[spec.child_index]
        name = f"count({arg})" if spec.op == "count_star" else f"{spec.op}({arg})"
        out_fields.append(T.StructField(name, spec.result_type(in_t)))
    return HostBatch(out_cols, T.Schema(out_fields))


# ---------------------------------------------------------------------------
# joins (CPU oracle for ops/join.py; Spark key semantics: null keys never
# match, NaN==NaN, -0.0==0.0)
# ---------------------------------------------------------------------------

def _join_key(cols: list[HostColumn], i: int):
    """Row i's key tuple, or None when any key column is null."""
    out = []
    for c in cols:
        if not c.validity[i]:
            return None
        v = c.data[i]
        if isinstance(c.dtype, (T.FloatType, T.DoubleType)):
            f = float(v)
            if f != f:
                v = "NaN"          # NaN == NaN for join keys
            elif f == 0.0:
                v = 0.0            # -0.0 == 0.0
            else:
                v = f
        elif isinstance(v, np.generic):
            v = v.item()
        out.append(v)
    return tuple(out)


def host_join(lb: HostBatch, rb: HostBatch, lkeys: Sequence[int],
              rkeys: Sequence[int], join_type: str):
    """Returns (li, ri, l_take, r_take) int64/bool arrays (see
    ops/join.py join_indices for the contract)."""
    nl, nr = lb.num_rows, rb.num_rows
    li, ri, lt, rt = [], [], [], []
    if join_type == "cross":
        for i in range(nl):
            for j in range(nr):
                li.append(i); ri.append(j); lt.append(True); rt.append(True)
    else:
        lcols = [lb.columns[k] for k in lkeys]
        rcols = [rb.columns[k] for k in rkeys]
        index: dict = {}
        for j in range(nr):
            k = _join_key(rcols, j)
            if k is not None:
                index.setdefault(k, []).append(j)
        matched_r = np.zeros(nr, np.bool_)
        for i in range(nl):
            k = _join_key(lcols, i)
            matches = index.get(k, []) if k is not None else []
            if join_type == "semi":
                if matches:
                    li.append(i); ri.append(0); lt.append(True); rt.append(False)
            elif join_type == "anti":
                if not matches:
                    li.append(i); ri.append(0); lt.append(True); rt.append(False)
            elif matches:
                for j in matches:
                    matched_r[j] = True
                    li.append(i); ri.append(j); lt.append(True); rt.append(True)
            elif join_type in ("left", "full"):
                li.append(i); ri.append(0); lt.append(True); rt.append(False)
        if join_type == "full":
            for j in range(nr):
                if not matched_r[j]:
                    li.append(0); ri.append(j); lt.append(False); rt.append(True)
    return (np.asarray(li, np.int64), np.asarray(ri, np.int64),
            np.asarray(lt, np.bool_), np.asarray(rt, np.bool_))


def host_join_output(lb: HostBatch, rb: HostBatch, li, ri, lt, rt,
                     schema, include_right: bool) -> HostBatch:
    cols = []
    for c in lb.columns:
        cols.append(_take_masked(c, li, lt))
    if include_right:
        for c in rb.columns:
            cols.append(_take_masked(c, ri, rt))
    return HostBatch(cols, schema)


def _take_masked(c: HostColumn, idx: np.ndarray, take: np.ndarray) -> HostColumn:
    n = len(idx)
    if len(c.data) == 0:
        data = np.zeros(n, dtype=c.data.dtype) if c.data.dtype != object \
            else np.full(n, None, dtype=object)
        return HostColumn(data, np.zeros(n, np.bool_), c.dtype)
    data = c.data[np.clip(idx, 0, len(c.data) - 1)]
    validity = c.validity[np.clip(idx, 0, len(c.data) - 1)] & take
    if c.data.dtype == object:
        data = np.where(validity, data, None)
    else:
        data = np.where(validity, data, np.zeros((), c.data.dtype))
    return HostColumn(data, validity, c.dtype)

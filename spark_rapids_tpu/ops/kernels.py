"""Core batch kernels: compaction (filter), gather, concat, slice.

Reference seams: ``Table.filter`` (GpuFilterExec,
basicPhysicalOperators.scala), ``Table.concatenate`` (ConcatAndConsumeAll,
GpuCoalesceBatches.scala:40), batch slicing (limit.scala).

TPU-first: filter does NOT change the array shape.  It computes a stable
permutation that front-packs kept rows (argsort of the drop-flag; jax sorts
are stable) and updates the traced ``num_rows`` scalar — everything stays
inside one compiled program, no host sync on the data-dependent row count.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.columnar.column import DeviceColumn

__all__ = ["compact", "take", "concat_batches", "slice_batch",
           "slice_rows", "gather_columns", "shrink_capacity",
           "pad_capacity", "device_scalar"]


@__import__("functools").lru_cache(maxsize=65536)
def device_scalar(value, dtype_str: str = "int32") -> jax.Array:
    """Device-resident scalar cached by value.

    A tiny host->device transfer costs tens of milliseconds of pure
    round-trip latency on a tunneled PJRT backend, and the same small
    values (partition ids, limits, zero offsets) recur on every batch —
    profiled at ~4s/iteration of TPC-DS q6 before caching.  The analog
    of the reference pinning small Scalars on the GPU across kernel
    launches (GpuScalar caching, GpuExpressionsUtils.scala)."""
    return jnp.asarray(value, jnp.dtype(dtype_str))


def _gather_column(col: DeviceColumn, perm: jax.Array,
                   out_mask: jax.Array) -> DeviceColumn:
    """Gather rows of ``col`` by ``perm`` then canonicalize padding by
    ``out_mask`` (bool[capacity], True = real row)."""
    validity = col.validity[perm] & out_mask
    if col.is_var_width:
        data = jnp.where(validity[:, None], col.data[perm], 0)
        lengths = jnp.where(validity, col.lengths[perm], 0)
        return DeviceColumn(data, validity, col.dtype, lengths)
    data = jnp.where(validity, col.data[perm], jnp.zeros((), col.data.dtype))
    return DeviceColumn(data, validity, col.dtype)


def gather_columns(cols: Sequence[DeviceColumn], perm: jax.Array,
                   new_count: jax.Array) -> list[DeviceColumn]:
    cap = perm.shape[0]
    out_mask = jnp.arange(cap, dtype=jnp.int32) < new_count
    return [_gather_column(c, perm, out_mask) for c in cols]


def compact(batch: ColumnBatch, keep: jax.Array) -> ColumnBatch:
    """Filter: keep rows where ``keep`` (bool[capacity]) is True.

    Order-preserving front-pack via exclusive-scan + scatter: kept row i
    lands at cumsum(keep)[i]-1, dropped rows scatter out of bounds and
    are discarded (mode='drop').  O(n) — the previous stable-argsort
    formulation cost a full O(n log n) multi-pass sort per filter, which
    dominated multi-branch scan-filter-agg plans (TPC-DS q28: 12
    filtered branches).  Padding and rows beyond ``num_rows`` are
    always dropped; scatter into zero-initialized outputs reproduces
    the zeroed-padding invariant directly.
    """
    keep = keep & batch.row_mask()
    cap = batch.capacity
    dest = jnp.cumsum(keep.astype(jnp.int32)) - 1
    idx = jnp.where(keep, dest, cap)  # cap = out of bounds -> dropped
    new_count = jnp.sum(keep, dtype=jnp.int32)
    cols = []
    for c in batch.columns:
        validity = jnp.zeros(cap, jnp.bool_).at[idx].set(
            c.validity, mode="drop")
        data = jnp.zeros_like(c.data).at[idx].set(
            jnp.where((keep & c.validity)[(...,) + (None,) *
                                          (c.data.ndim - 1)],
                      c.data, jnp.zeros((), c.data.dtype)),
            mode="drop")
        if c.is_var_width:
            lengths = jnp.zeros(cap, jnp.int32).at[idx].set(
                jnp.where(keep & c.validity, c.lengths, 0), mode="drop")
            cols.append(DeviceColumn(data, validity, c.dtype, lengths))
        else:
            cols.append(DeviceColumn(data, validity, c.dtype))
    return ColumnBatch(cols, new_count, batch.schema)


def take(batch: ColumnBatch, indices: jax.Array,
         out_count: jax.Array) -> ColumnBatch:
    """Gather rows at ``indices`` (int32[out_capacity]); entries at position
    >= out_count are padding."""
    cols = gather_columns(batch.columns, indices, out_count)
    return ColumnBatch(cols, out_count, batch.schema)


def slice_batch(batch: ColumnBatch, limit: jax.Array) -> ColumnBatch:
    """Keep the first ``limit`` rows (GpuLocalLimit, limit.scala)."""
    if isinstance(limit, int):
        limit = device_scalar(limit)  # cached: no per-call H2D round trip
    new_count = jnp.minimum(batch.num_rows, jnp.asarray(limit, jnp.int32))
    identity = jnp.arange(batch.capacity, dtype=jnp.int32)
    cols = gather_columns(batch.columns, identity, new_count)
    return ColumnBatch(cols, new_count, batch.schema)


def slice_rows(batch: ColumnBatch, lo: int, hi: int) -> ColumnBatch:
    """Row range ``[lo, hi)`` of a front-packed batch as its own batch.

    The caller must know (host-side) that ``hi <= num_rows``, so every
    row in the range is real.  Slices run eagerly: each (lo, hi, shape)
    triple is unique to its split point, so a jit here would compile a
    fresh executable per slice (the opposite of the canonical-bucket
    discipline the jitted shrink/pad kernels exist for)."""
    cols = []
    for c in batch.columns:
        if c.is_var_width:
            cols.append(DeviceColumn(c.data[lo:hi], c.validity[lo:hi],
                                     c.dtype, c.lengths[lo:hi]))
        else:
            cols.append(DeviceColumn(c.data[lo:hi], c.validity[lo:hi],
                                     c.dtype))
    n = hi - lo
    return ColumnBatch(cols, jnp.asarray(n, jnp.int32), batch.schema,
                       known_rows=n)


def shrink_capacity(batch: ColumnBatch, cap: int) -> ColumnBatch:
    """Static-slice a front-packed batch down to ``cap`` rows of storage.

    The caller must know (host-side) that ``num_rows <= cap``; rows are
    already front-packed so a plain prefix slice keeps them all.  Used to
    hold a running aggregation buffer at a fixed canonical capacity
    instead of walking compilation buckets upward.  Jitted per (cap,
    batch-shape) so the eager path costs one dispatch, not one per column.
    """
    if batch.capacity <= cap:
        return batch
    return _shared("shrink", _shrink_jit)(batch, cap)


_SHARED_JITS: dict = {}


def _shared(name: str, fn):
    """Compile-accounted wrapper for a capacity-changing kernel.

    These two kernels compile NEW executables mid-query (every distinct
    capacity is a fresh signature, and spill/retry storms churn
    capacities across drain threads), so they go through the shared-jit
    wrapper, which serializes CPU compiles process-wide.  kernels sits
    below exec/, hence the wrapper is bound lazily on first dispatch
    instead of imported at module load."""
    w = _SHARED_JITS.get(name)
    if w is None:
        from spark_rapids_tpu.exec.compile_cache import instrument
        w = _SHARED_JITS.setdefault(name, instrument(fn))
    return w


@partial(jax.jit, static_argnames=("cap",))
def _shrink_jit(batch: ColumnBatch, cap: int) -> ColumnBatch:
    cols = []
    for c in batch.columns:
        if c.is_var_width:
            cols.append(DeviceColumn(c.data[:cap], c.validity[:cap],
                                     c.dtype, c.lengths[:cap]))
        else:
            cols.append(DeviceColumn(c.data[:cap], c.validity[:cap], c.dtype))
    return ColumnBatch(cols, batch.num_rows, batch.schema)


def pad_capacity(batch: ColumnBatch, cap: int) -> ColumnBatch:
    """Grow a batch's storage to ``cap`` rows with trailing padding
    (cheap realloc; keeps compilation buckets canonical)."""
    if cap <= batch.capacity:
        return batch
    return _shared("pad", _pad_jit)(batch, cap)


@partial(jax.jit, static_argnames=("cap",))
def _pad_jit(batch: ColumnBatch, cap: int) -> ColumnBatch:
    pad = cap - batch.capacity
    cols = []
    for c in batch.columns:
        validity = jnp.concatenate([c.validity, jnp.zeros(pad, jnp.bool_)])
        if c.is_var_width:
            data = jnp.concatenate(
                [c.data, jnp.zeros((pad, c.max_len), c.data.dtype)])
            lengths = jnp.concatenate([c.lengths, jnp.zeros(pad, jnp.int32)])
            cols.append(DeviceColumn(data, validity, c.dtype, lengths))
        else:
            data = jnp.concatenate([c.data, jnp.zeros(pad, c.data.dtype)])
            cols.append(DeviceColumn(data, validity, c.dtype))
    return ColumnBatch(cols, batch.num_rows, batch.schema)


def concat_batches(batches: Sequence[ColumnBatch],
                   out_capacity: int | None = None) -> ColumnBatch:
    """Concatenate batches (GpuCoalesceBatches / Table.concatenate).

    Shapes are static: the output capacity is the pow2 bucket of the summed
    input capacities unless given.  Rows are front-packed via compaction of
    the concatenated row masks.
    """
    assert batches, "concat of zero batches"
    schema = batches[0].schema
    # align devices: inputs committed to different mesh devices (e.g. a
    # mesh join's per-device probe outputs consumed by a non-mesh
    # operator) cannot feed one jitted concat; move strays to the first
    # batch's device (no-op when aligned, impossible-and-unneeded when
    # already tracing inside a jit — tracers carry no placement)
    if batches[0].columns and not isinstance(
            batches[0].columns[0].data, jax.core.Tracer):
        devs = {repr(d) for b in batches if b.columns
                for d in [next(iter(b.columns[0].data.devices()))]
                if getattr(b.columns[0].data, "committed", False)}
        if len(devs) > 1:
            target = next(iter(batches[0].columns[0].data.devices()))
            batches = [jax.device_put(b, target) for b in batches]
    cap = out_capacity or round_capacity(sum(b.capacity for b in batches))
    ncols = batches[0].num_columns
    # per-column concat with per-batch real-row masks
    masks = jnp.concatenate([b.row_mask() for b in batches])
    total = sum(b.capacity for b in batches)
    pad = cap - total
    if pad < 0:
        raise ValueError("out_capacity smaller than concatenated capacities")
    if pad:
        masks = jnp.concatenate([masks, jnp.zeros(pad, jnp.bool_)])
    perm = jnp.argsort(~masks, stable=True)
    new_count = jnp.sum(masks, dtype=jnp.int32)
    out_mask = jnp.arange(cap, dtype=jnp.int32) < new_count
    cols = []
    for ci in range(ncols):
        parts = [b.columns[ci] for b in batches]
        dtype = parts[0].dtype
        if parts[0].is_var_width:
            w = max(p.max_len for p in parts)
            datas = [jnp.pad(p.data, ((0, 0), (0, w - p.max_len))) for p in parts]
            data = jnp.concatenate(datas)
            lengths = jnp.concatenate([p.lengths for p in parts])
            validity = jnp.concatenate([p.validity for p in parts])
            if pad:
                data = jnp.concatenate([data,
                                        jnp.zeros((pad, w), data.dtype)])
                lengths = jnp.concatenate([lengths, jnp.zeros(pad, jnp.int32)])
                validity = jnp.concatenate([validity, jnp.zeros(pad, jnp.bool_)])
            validity = validity[perm] & out_mask
            cols.append(DeviceColumn(jnp.where(validity[:, None], data[perm], 0),
                                     validity, dtype,
                                     jnp.where(validity, lengths[perm], 0)))
        else:
            data = jnp.concatenate([p.data for p in parts])
            validity = jnp.concatenate([p.validity for p in parts])
            if pad:
                data = jnp.concatenate([data, jnp.zeros(pad, data.dtype)])
                validity = jnp.concatenate([validity, jnp.zeros(pad, jnp.bool_)])
            validity = validity[perm] & out_mask
            cols.append(DeviceColumn(
                jnp.where(validity, data[perm], jnp.zeros((), data.dtype)),
                validity, dtype))
    return ColumnBatch(cols, new_count, schema)

"""Lexicographic sort with Spark null ordering (Table.orderBy analog).

Reference: GpuSortExec.scala:51 / SortUtils.scala build cuDF orderBy args
(ascending/descending, null ordering).  TPU-first design: one stable
multi-operand ``lax.sort`` handles any mix of key types, directions and null
orders.  Per key column the operands are:

* a leading null-indicator byte (0/1 by nulls-first/last),
* for floats: a NaN-indicator byte (Spark: NaN is the largest value; for
  descending keys NaN must come first) followed by the value itself with
  -0.0 normalized to +0.0 and NaN zeroed (ref NormalizeFloatingNumbers);
  descending negates the value,
* for integers/date/timestamp/bool: the value; descending uses bitwise NOT
  (monotonic inversion with no overflow),
* for strings: the padded byte matrix chunked into big-endian uint32 words
  (zero padding makes prefixes sort first); descending inverts each word.

A most-significant pad flag forces batch padding rows to sort last.

Note: no 64-bit bitcasts anywhere — TPU v5e XLA does not implement
bitcast-convert on 64-bit element types (verified empirically); s64/f64
arithmetic and comparisons are supported (emulated).

OOM retry contract (memory/retry.py): ``sort_batch`` is a TOTAL order
over its input and no pairwise sorted-merge kernel exists here, so
exec/sortexec.py runs it under ``with_retry_no_split`` (reference
GpuSortExec's withRetryNoSplit, GpuSortExec.scala) — on HBM exhaustion
the scope spills and re-attempts the whole batch but never splits it:
independently sorted halves would interleave and break the order.
Operators whose outputs compose row-wise (project/filter) or through an
associative merge (aggregate update, window state) use the splitting
scope instead.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.ops.kernels import gather_columns

__all__ = ["SortOrder", "sort_batch", "sort_permutation", "encode_key_operands",
           "normalize_floats"]


@dataclass(frozen=True)
class SortOrder:
    """One sort key: column index + direction + null ordering."""
    child_index: int
    ascending: bool = True
    nulls_first: bool | None = None  # None = Spark default (first iff asc)

    @property
    def resolved_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return self.ascending  # Spark: asc->nulls first, desc->nulls last
        return self.nulls_first


def normalize_floats(x: jax.Array) -> jax.Array:
    """-0.0 -> +0.0 and NaN -> canonical NaN (ref NormalizeFloatingNumbers)."""
    zero = jnp.zeros((), x.dtype)
    x = jnp.where(x == zero, zero, x)
    return jnp.where(jnp.isnan(x), jnp.full((), jnp.nan, x.dtype), x)


def string_key_words(col: DeviceColumn) -> list[jax.Array]:
    """Padded byte matrix -> list of big-endian uint32 word operands."""
    w = col.max_len
    nwords = (w + 3) // 4
    padded = col.data if w % 4 == 0 else \
        jnp.pad(col.data, ((0, 0), (0, 4 * nwords - w)))
    b = padded.reshape(col.capacity, nwords, 4).astype(jnp.uint32)
    words = (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]
    return [words[:, i] for i in range(nwords)]


def encode_key_operands(col: DeviceColumn, ascending: bool = True) -> list[jax.Array]:
    """Encode a column's values into sort operands (see module docstring)."""
    dt = col.dtype
    if isinstance(dt, T.StringType):
        # lengths break ties between strings differing only by trailing NULs
        words = string_key_words(col) + [col.lengths]
        return words if ascending else [~wd for wd in words]
    if isinstance(dt, T.BooleanType):
        v = col.data.astype(jnp.int32)
        return [v] if ascending else [~v]
    if dt.fractional:
        x = normalize_floats(col.data)
        isnan = jnp.isnan(x)
        # NaN largest: asc -> NaN flag sorts last; desc -> first
        nan_key = jnp.where(isnan, jnp.uint8(1 if ascending else 0),
                            jnp.uint8(0 if ascending else 1))
        v = jnp.where(isnan, jnp.zeros((), x.dtype), x)
        return [nan_key, v if ascending else -v]
    # integral / date / timestamp
    return [col.data] if ascending else [~col.data]


def sort_permutation(batch: ColumnBatch, orders: list[SortOrder],
                     real: jax.Array | None = None) -> jax.Array:
    """Return the permutation (int32[capacity]) that sorts the batch.

    ``real`` overrides the front-packed ``row_mask()`` real-row
    indicator — a mesh broadcast sort (exec/mesh_region.py) all-gathers
    P shard segments whose rows are packed per SEGMENT, not globally,
    so the caller supplies the segment-aware mask and the sort's
    padding-last flag simultaneously front-packs and orders."""
    cap = batch.capacity
    if real is None:
        real = batch.row_mask()
    operands: list[jax.Array] = [(~real).astype(jnp.uint8)]  # padding last
    for o in orders:
        col = batch.columns[o.child_index]
        null_ind = jnp.where(col.validity,
                             jnp.uint8(1 if o.resolved_nulls_first else 0),
                             jnp.uint8(0 if o.resolved_nulls_first else 1))
        operands.append(null_ind)
        operands.extend(encode_key_operands(col, o.ascending))
    iota = jnp.arange(cap, dtype=jnp.int32)
    nk = len(operands)
    sorted_ops = lax.sort(operands + [iota], num_keys=nk, is_stable=True)
    return sorted_ops[-1]


def sort_batch(batch: ColumnBatch, orders: list[SortOrder]) -> ColumnBatch:
    perm = sort_permutation(batch, orders)
    cols = gather_columns(batch.columns, perm, batch.num_rows)
    return ColumnBatch(cols, batch.num_rows, batch.schema)

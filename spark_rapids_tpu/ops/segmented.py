"""Sort-based group-by aggregation (cuDF groupBy().aggregate analog).

Reference: GpuHashAggregateExec computes cuDF hash-group-by per batch then
merges (aggregate.scala:348-560).  XLA has no device hash tables, so the
TPU-idiomatic design (SURVEY §7 "hard parts") is *sort-based*: sort rows by
the grouping keys, mark segment boundaries, and reduce with XLA segment ops
— fully static shapes, group count as a traced scalar.

Null keys form their own group (Spark semantics); key equality treats
null == null.  Padding rows are forced into one trailing segment whose
output slot is canonicalized away.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.ops.sort import SortOrder, sort_batch, normalize_floats

__all__ = ["AggSpec", "sorted_group_by"]

# supported aggregate ops (reference AggregateFunctions.scala:531 CudfAggregate)
_AGG_OPS = ("sum", "count", "count_star", "min", "max", "avg", "first", "last",
            "first_non_null", "last_non_null", "percentile")


@dataclass(frozen=True)
class AggSpec:
    op: str          # one of _AGG_OPS
    child_index: int  # input column (ignored for count_star)
    param: float | None = None  # percentile fraction q in [0, 1]

    def result_type(self, input_type: T.DataType) -> T.DataType:
        if self.op in ("count", "count_star"):
            return T.LongType()
        if self.op == "sum":
            if input_type.integral:
                return T.LongType()
            return T.DoubleType()
        if self.op in ("avg", "percentile"):
            return T.DoubleType()
        return input_type


def _cols_differ(col: DeviceColumn) -> jax.Array:
    """bool[capacity]: row i's key differs from row i-1's (null==null)."""
    v = col.validity
    v_prev = jnp.roll(v, 1)
    if col.is_string:
        d_prev = jnp.roll(col.data, 1, axis=0)
        data_diff = jnp.any(col.data != d_prev, axis=1) | \
            (col.lengths != jnp.roll(col.lengths, 1))
    elif col.dtype.fractional:
        # group keys: NaN == NaN, -0.0 == 0.0 (Spark normalized semantics)
        d = normalize_floats(col.data)
        d_prev = jnp.roll(d, 1)
        data_diff = (d != d_prev) & ~(jnp.isnan(d) & jnp.isnan(d_prev))
    else:
        data_diff = col.data != jnp.roll(col.data, 1)
    return (v != v_prev) | (v & v_prev & data_diff)


def sorted_group_by(batch: ColumnBatch, key_indices: list[int],
                    aggs: list[AggSpec],
                    presorted: bool = False) -> ColumnBatch:
    """Group ``batch`` by key columns, computing ``aggs``.

    Output schema: key columns (original names/types) then one column per
    agg. Output capacity == input capacity; num_rows == number of groups.
    Grand aggregates (no keys) produce exactly one row, even on empty input
    (reference "reduction default-values path", aggregate.scala:514+).

    ``presorted``: the caller guarantees rows equal on the key columns
    are already contiguous (PlanNode.output_ordering) — segment
    detection only needs contiguity, so the O(n log n) sort is skipped
    (the reference's sort-aggregate-over-sorted-input fast path).
    """
    cap = batch.capacity
    # percentile is order-holistic: rows must ALSO sort by the value
    # column within each key group (nulls last, so each segment's valid
    # run starts at the segment start) — Spark computes the same via
    # per-group sorted buffers (ObjectHashAggregate Percentile)
    pct_cols = sorted({s.child_index for s in aggs if s.op == "percentile"})
    if len(pct_cols) > 1:
        raise NotImplementedError(
            "percentile aggregates over multiple distinct columns in one "
            "group-by are not supported (one value-sort per group-by)")
    if key_indices or pct_cols:
        if presorted and not pct_cols:
            sb = batch
        else:
            orders = [SortOrder(i, True, True) for i in key_indices]
            orders += [SortOrder(i, True, False) for i in pct_cols]
            sb = sort_batch(batch, orders)
    if key_indices:
        real = sb.row_mask()
        idx = jnp.arange(cap, dtype=jnp.int32)
        differ = jnp.zeros(cap, jnp.bool_)
        for ki in key_indices:
            differ = differ | _cols_differ(sb.columns[ki])
        flag = (idx == 0) | (differ & real) | (idx == sb.num_rows)
        # rows past the first padding row never set a new flag
        flag = flag & (idx <= sb.num_rows)
        seg_id = jnp.cumsum(flag.astype(jnp.int32)) - 1
        num_groups = jnp.where(sb.num_rows > 0,
                               seg_id[jnp.maximum(sb.num_rows - 1, 0)] + 1, 0)
    else:
        if not pct_cols:
            sb = batch  # grand aggregate without percentile: no sort
        real = sb.row_mask()
        seg_id = jnp.zeros(cap, jnp.int32)
        num_groups = jnp.asarray(1, jnp.int32)  # grand aggregate: one row
        flag = jnp.arange(cap, dtype=jnp.int32) == 0

    out_mask = jnp.arange(cap, dtype=jnp.int32) < num_groups
    out_cols: list[DeviceColumn] = []
    out_fields: list[T.StructField] = []

    # --- key columns: value at each segment start -------------------------
    for ki in key_indices:
        col = sb.columns[ki]
        pos = jnp.where(flag & real, seg_id, cap)  # scatter target (drop pad)
        validity = jnp.zeros(cap, jnp.bool_).at[pos].set(col.validity, mode="drop")
        validity = validity & out_mask
        if col.is_var_width:
            data = jnp.zeros((cap, col.max_len),
                             col.data.dtype).at[pos].set(col.data, mode="drop")
            lengths = jnp.zeros(cap, jnp.int32).at[pos].set(col.lengths, mode="drop")
            out_cols.append(DeviceColumn(jnp.where(validity[:, None], data, 0),
                                         validity, col.dtype,
                                         jnp.where(validity, lengths, 0)))
        else:
            data = jnp.zeros(cap, col.data.dtype).at[pos].set(col.data, mode="drop")
            out_cols.append(DeviceColumn(
                jnp.where(validity, data, jnp.zeros((), data.dtype)),
                validity, col.dtype))
        out_fields.append(batch.schema.fields[ki])

    # --- aggregates -------------------------------------------------------
    seg_real_cnt = _seg_sum(real.astype(jnp.int64), seg_id, cap)
    for spec in aggs:
        col = sb.columns[spec.child_index] if spec.op != "count_star" else None
        res_col, res_type = _compute_agg(spec, col, seg_id, real, cap,
                                         out_mask, seg_real_cnt)
        out_cols.append(res_col)
        in_t = col.dtype if col is not None else T.LongType()
        arg = "1" if spec.op == "count_star" else batch.schema.names[spec.child_index]
        name = f"count({arg})" if spec.op == "count_star" else f"{spec.op}({arg})"
        out_fields.append(T.StructField(name, spec.result_type(in_t)))

    return ColumnBatch(out_cols, num_groups, T.Schema(out_fields))


def _seg_sum(x, seg_id, cap):
    return jax.ops.segment_sum(x, seg_id, num_segments=cap)


def _compute_agg(spec: AggSpec, col: DeviceColumn | None, seg_id, real, cap,
                 out_mask, seg_real_cnt):
    op = spec.op
    if op == "count_star":
        validity = out_mask
        return DeviceColumn(jnp.where(validity, seg_real_cnt, 0), validity,
                            T.LongType()), T.LongType()

    contributes = col.validity & real
    cnt_valid = _seg_sum(contributes.astype(jnp.int64), seg_id, cap)

    if op == "count":
        validity = out_mask
        return DeviceColumn(jnp.where(validity, cnt_valid, 0), validity,
                            T.LongType()), T.LongType()

    if op in ("sum", "avg"):
        acc_dt = jnp.int64 if (col.dtype.integral and op == "sum") else jnp.float64
        contrib = jnp.where(contributes, col.data.astype(acc_dt),
                            jnp.zeros((), acc_dt))
        s = _seg_sum(contrib, seg_id, cap)
        if op == "avg":
            data = s.astype(jnp.float64) / jnp.maximum(cnt_valid, 1).astype(jnp.float64)
            rtype = T.DoubleType()
        elif col.dtype.integral:
            data, rtype = s, T.LongType()
        else:
            data, rtype = s.astype(jnp.float64), T.DoubleType()
        validity = (cnt_valid > 0) & out_mask
        return DeviceColumn(jnp.where(validity, data, jnp.zeros((), data.dtype)),
                            validity, rtype), rtype

    if op in ("min", "max"):
        if col.dtype.fractional:
            # Spark: NaN is the largest value; no 64-bit bitcasts on TPU, so
            # mask NaNs to +/-inf identities and patch the all/any-NaN cases.
            x = normalize_floats(col.data)
            isnan = jnp.isnan(x)
            nan_cnt = _seg_sum((contributes & isnan).astype(jnp.int32), seg_id, cap)
            nonnan_cnt = _seg_sum((contributes & ~isnan).astype(jnp.int32), seg_id, cap)
            if op == "min":
                masked = jnp.where(contributes & ~isnan, x,
                                   jnp.full((), jnp.inf, x.dtype))
                r = jax.ops.segment_min(masked, seg_id, num_segments=cap)
                # min is NaN only when every contributing value is NaN
                data = jnp.where((nonnan_cnt == 0) & (nan_cnt > 0),
                                 jnp.full((), jnp.nan, x.dtype), r)
            else:
                masked = jnp.where(contributes & ~isnan, x,
                                   jnp.full((), -jnp.inf, x.dtype))
                r = jax.ops.segment_max(masked, seg_id, num_segments=cap)
                # max is NaN when any contributing value is NaN
                data = jnp.where(nan_cnt > 0, jnp.full((), jnp.nan, x.dtype), r)
        elif isinstance(col.dtype, T.StringType):
            # lexicographic min/max by a per-segment sort: order rows by
            # (segment, non-contributing-last, string key words) and take
            # each segment's first row (reference: cudf groupby min/max
            # string aggregations)
            from jax import lax
            from spark_rapids_tpu.ops.sort import encode_key_operands
            words = encode_key_operands(col, ascending=(op == "min"))
            flag = (~contributes).astype(jnp.uint8)
            iota = jnp.arange(cap, dtype=jnp.int32)
            sorted_ops = lax.sort([seg_id, flag, *words, iota],
                                  num_keys=2 + len(words),
                                  is_stable=True)
            s_seg, s_flag, order = sorted_ops[0], sorted_ops[1], sorted_ops[-1]
            firsts = jnp.concatenate(
                [jnp.ones(1, jnp.bool_), s_seg[1:] != s_seg[:-1]])
            take = firsts & (s_flag == 0)
            target = jnp.where(take, s_seg, cap)
            src = col.data[order]
            data = jnp.zeros((cap, col.max_len), jnp.uint8
                             ).at[target].set(src, mode="drop")
            lens = jnp.zeros(cap, jnp.int32
                             ).at[target].set(col.lengths[order], mode="drop")
            validity = (cnt_valid > 0) & out_mask
            return DeviceColumn(jnp.where(validity[:, None], data, 0),
                                validity, col.dtype,
                                jnp.where(validity, lens, 0)), col.dtype
        else:
            info = jnp.iinfo(col.data.dtype) if col.data.dtype != jnp.bool_ else None
            if col.data.dtype == jnp.bool_:
                d = col.data.astype(jnp.int32)
                ident = 1 if op == "min" else 0
                masked = jnp.where(contributes, d, ident)
                r = (jax.ops.segment_min if op == "min" else jax.ops.segment_max)(
                    masked, seg_id, num_segments=cap)
                data = r.astype(jnp.bool_)
            else:
                ident = info.max if op == "min" else info.min
                masked = jnp.where(contributes, col.data, ident)
                data = (jax.ops.segment_min if op == "min" else jax.ops.segment_max)(
                    masked, seg_id, num_segments=cap)
        validity = (cnt_valid > 0) & out_mask
        zero = jnp.zeros((), data.dtype)
        return DeviceColumn(jnp.where(validity, data, zero), validity,
                            col.dtype), col.dtype

    if op == "percentile":
        # rows arrive sorted (keys, value asc, value-nulls last), so each
        # segment's valid values occupy [seg_start, seg_start + cnt_valid);
        # linear interpolation at q*(n-1), Spark Percentile semantics
        q = spec.param
        assert q is not None, "percentile AggSpec needs param=q"
        idx = jnp.arange(cap, dtype=jnp.int32)
        starts = jax.ops.segment_min(jnp.where(real, idx, cap), seg_id,
                                     num_segments=cap)
        pos = (cnt_valid - 1).astype(jnp.float64) * q
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.ceil(pos).astype(jnp.int32)
        frac = pos - lo
        base = jnp.clip(starts, 0, cap - 1)
        x = col.data.astype(jnp.float64)
        vlo = x[jnp.clip(base + lo, 0, cap - 1)]
        vhi = x[jnp.clip(base + hi, 0, cap - 1)]
        data = vlo + (vhi - vlo) * frac
        validity = (cnt_valid > 0) & out_mask
        return DeviceColumn(jnp.where(validity, data, 0.0), validity,
                            T.DoubleType()), T.DoubleType()

    if op in ("first", "last", "first_non_null", "last_non_null"):
        # index of first/last row per segment; *_non_null picks among valid
        # rows only (Spark first/last ignoreNulls=true), plain variants use
        # row position regardless of validity (ignoreNulls=false default)
        ignore_nulls = op.endswith("non_null")
        eligible = contributes if ignore_nulls else real
        idx = jnp.arange(cap, dtype=jnp.int32)
        if op.startswith("first"):
            masked_idx = jnp.where(eligible, idx, cap)
            pick = jax.ops.segment_min(masked_idx, seg_id, num_segments=cap)
        else:
            masked_idx = jnp.where(eligible, idx, -1)
            pick = jax.ops.segment_max(masked_idx, seg_id, num_segments=cap)
        pick = jnp.clip(pick, 0, cap - 1)
        has_eligible = cnt_valid > 0 if ignore_nulls else seg_real_cnt > 0
        validity = col.validity[pick] & out_mask & has_eligible
        if col.is_var_width:
            data = jnp.where(validity[:, None], col.data[pick], 0)
            return DeviceColumn(data, validity, col.dtype,
                                jnp.where(validity, col.lengths[pick], 0)), col.dtype
        data = jnp.where(validity, col.data[pick], jnp.zeros((), col.data.dtype))
        return DeviceColumn(data, validity, col.dtype), col.dtype

    raise NotImplementedError(f"aggregate op {op}")

"""Window function device kernels.

Reference: GpuWindowExec + GpuWindowExpression (GpuWindowExec.scala:92,
GpuWindowExpression.scala:169-830) lower to cuDF rolling-window
aggregations.  TPU design: after one sort by (partition keys, order
keys), every window shape becomes static-shape index arithmetic:

* partition extents ``seg_start/seg_end`` via boundary-flag cummax,
* running (UNBOUNDED PRECEDING..CURRENT ROW) and whole-partition frames
  via prefix sums / segment reductions,
* bounded ROWS frames via **sparse tables** (log2(cap) levels of
  power-of-two-span min/max, XLA-friendly static depth) for min/max and
  clamped prefix-sum differences for sum/count/avg,
* RANGE frames differ from ROWS only in using peer-group edges
  (first/last row with equal order keys) as the effective row,
* row_number/rank/dense_rank/lead/lag from the same segment arrays.

All results are computed in sorted order; the exec emits the sorted
batch (Spark does not define window output order).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.ops.segmented import _cols_differ
from spark_rapids_tpu.ops.sort import SortOrder, normalize_floats, sort_batch

__all__ = ["WindowFrame", "UNBOUNDED", "CURRENT_ROW", "SegmentInfo",
           "sorted_segments", "running_or_bounded_agg", "row_number", "rank",
           "dense_rank", "lead_lag"]

UNBOUNDED = None
CURRENT_ROW = 0


@dataclass(frozen=True)
class WindowFrame:
    """ROWS/RANGE frame: bounds are None (unbounded) or int row offsets
    (negative = preceding).  RANGE only supports UNBOUNDED/CURRENT_ROW
    bounds (Spark's value-RANGE with literal offsets is a planner
    rejection, as in the reference tagging)."""
    mode: str = "range"            # "rows" | "range"
    lower: int | None = UNBOUNDED  # None=unbounded preceding, k<=0 offset
    upper: int | None = CURRENT_ROW  # None=unbounded following, k>=0

    def __post_init__(self):
        if self.mode == "range":
            assert self.lower in (UNBOUNDED, CURRENT_ROW)
            assert self.upper in (UNBOUNDED, CURRENT_ROW)


@dataclass
class SegmentInfo:
    """Per-row partition/peer extents over the sorted batch."""
    seg_start: jax.Array    # int32[cap] first row index of row's partition
    seg_end: jax.Array      # int32[cap] last row index (inclusive)
    peer_start: jax.Array   # first row of the order-key peer group
    peer_end: jax.Array     # last row of the peer group
    seg_id: jax.Array       # int32[cap]
    order_change: jax.Array  # bool[cap] order key differs from prev in seg
    real: jax.Array         # bool[cap]


def sorted_segments(sb: ColumnBatch, part_idx: Sequence[int],
                    order_idx: Sequence[int]) -> SegmentInfo:
    cap = sb.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    real = sb.row_mask()
    part_flag = jnp.zeros(cap, jnp.bool_)
    for ki in part_idx:
        part_flag = part_flag | _cols_differ(sb.columns[ki])
    part_flag = (idx == 0) | (part_flag & real) | (idx == sb.num_rows)
    part_flag = part_flag & (idx <= sb.num_rows)
    seg_id = jnp.cumsum(part_flag.astype(jnp.int32)) - 1
    seg_start = lax.cummax(jnp.where(part_flag, idx, 0))
    # seg_end: reverse cummax of next-boundary - 1
    nxt = jnp.where(part_flag, idx, cap)
    rev_next = jnp.flip(lax.cummin(jnp.flip(
        jnp.concatenate([nxt[1:], jnp.asarray([cap], jnp.int32)]))))
    seg_end = jnp.minimum(rev_next - 1, jnp.maximum(sb.num_rows - 1, 0))

    order_change = jnp.zeros(cap, jnp.bool_)
    for ki in order_idx:
        order_change = order_change | _cols_differ(sb.columns[ki])
    peer_flag = part_flag | (order_change & real)
    peer_start = lax.cummax(jnp.where(peer_flag, idx, 0))
    pnxt = jnp.where(peer_flag, idx, cap)
    rev_pnext = jnp.flip(lax.cummin(jnp.flip(
        jnp.concatenate([pnxt[1:], jnp.asarray([cap], jnp.int32)]))))
    peer_end = jnp.minimum(rev_pnext - 1, jnp.maximum(sb.num_rows - 1, 0))
    return SegmentInfo(seg_start, seg_end, peer_start, peer_end, seg_id,
                       order_change & real, real)


# ---------------------------------------------------------------------------
# frame edges
# ---------------------------------------------------------------------------

def _frame_edges(seg: SegmentInfo, frame: WindowFrame):
    """(lo, hi) inclusive row-index bounds per row."""
    cap = seg.seg_start.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    if frame.mode == "rows":
        lo = seg.seg_start if frame.lower is UNBOUNDED else \
            jnp.maximum(idx + frame.lower, seg.seg_start)
        hi = seg.seg_end if frame.upper is UNBOUNDED else \
            jnp.minimum(idx + frame.upper, seg.seg_end)
    else:  # range: CURRENT_ROW means the whole peer group
        lo = seg.seg_start if frame.lower is UNBOUNDED else seg.peer_start
        hi = seg.seg_end if frame.upper is UNBOUNDED else seg.peer_end
    return lo, hi


# ---------------------------------------------------------------------------
# sparse-table range min/max (static log depth)
# ---------------------------------------------------------------------------

def _sparse_table(x: jax.Array, op) -> list[jax.Array]:
    """st[k][i] = op over x[i : i+2^k), clamped at the end."""
    cap = x.shape[0]
    levels = [x]
    k = 1
    while (1 << k) <= cap:
        prev = levels[-1]
        half = 1 << (k - 1)
        shifted = jnp.concatenate([prev[half:], prev[-1:].repeat(half)])
        levels.append(op(prev, shifted))
        k += 1
    return levels


def _range_query(levels: list[jax.Array], lo, hi, op, identity):
    """Per-row op over x[lo..hi] via two overlapping power-of-two spans."""
    length = hi - lo + 1
    valid = length > 0
    length = jnp.maximum(length, 1)
    # floor(log2(length)) via pure integer comparisons (no f64 log on TPU)
    k = jnp.zeros(length.shape, jnp.int32)
    for kk in range(1, len(levels)):
        k = k + (length >= (1 << kk)).astype(jnp.int32)
    cap = levels[0].shape[0]
    res = jnp.full(levels[0].shape, identity, levels[0].dtype)
    for kk in range(len(levels)):
        span = 1 << kk
        a = levels[kk][jnp.clip(lo, 0, cap - 1)]
        b = levels[kk][jnp.clip(hi - span + 1, 0, cap - 1)]
        cand = op(a, b)
        res = jnp.where(k == kk, cand, res)
    return jnp.where(valid, res, identity)


# ---------------------------------------------------------------------------
# aggregates over frames
# ---------------------------------------------------------------------------

def running_or_bounded_agg(op: str, col: DeviceColumn, seg: SegmentInfo,
                           frame: WindowFrame):
    """sum|count|avg|min|max over the frame. Returns (data, validity,
    result_type)."""
    cap = col.capacity
    contributes = col.validity & seg.real
    lo, hi = _frame_edges(seg, frame)

    if op in ("sum", "count", "avg"):
        if op == "count":
            x = contributes.astype(jnp.int64)
            acc_dt = jnp.int64
        else:
            acc_dt = jnp.int64 if col.dtype.integral else jnp.float64
            x = jnp.where(contributes, col.data.astype(acc_dt),
                          jnp.zeros((), acc_dt))
        # empty frames (lo > hi, e.g. ROWS 2 FOLLOWING..5 FOLLOWING at the
        # partition tail) must yield 0, not a negative cross-partition diff
        hi1 = jnp.maximum(hi + 1, lo)
        ps = jnp.concatenate([jnp.zeros(1, acc_dt), jnp.cumsum(x)])
        total = ps[jnp.clip(hi1, 0, cap)] - ps[jnp.clip(lo, 0, cap)]
        cnt_x = contributes.astype(jnp.int64)
        pc = jnp.concatenate([jnp.zeros(1, jnp.int64), jnp.cumsum(cnt_x)])
        cnt = pc[jnp.clip(hi1, 0, cap)] - pc[jnp.clip(lo, 0, cap)]
        if op == "count":
            return cnt, seg.real, T.LongType()
        if op == "avg":
            data = total.astype(jnp.float64) / jnp.maximum(cnt, 1)
            return data, seg.real & (cnt > 0), T.DoubleType()
        if col.dtype.integral:
            return total, seg.real & (cnt > 0), T.LongType()
        return total.astype(jnp.float64), seg.real & (cnt > 0), \
            T.DoubleType()

    if op in ("min", "max"):
        if col.dtype.fractional:
            x = normalize_floats(col.data)
            # NaN largest: min ignores NaN unless all-NaN; max returns NaN
            # if any NaN (Spark float ordering)
            isnan = jnp.isnan(x)
            base = jnp.where(contributes & ~isnan, x,
                             jnp.full((), jnp.inf if op == "min" else -jnp.inf,
                                      x.dtype))
            ident = jnp.inf if op == "min" else -jnp.inf
            fop = jnp.minimum if op == "min" else jnp.maximum
            levels = _sparse_table(base, fop)
            res = _range_query(levels, lo, hi, fop, ident)
            hi1 = jnp.maximum(hi + 1, lo)
            nan_x = (contributes & isnan).astype(jnp.int64)
            pn = jnp.concatenate([jnp.zeros(1, jnp.int64), jnp.cumsum(nan_x)])
            nan_cnt = pn[jnp.clip(hi1, 0, cap)] - pn[jnp.clip(lo, 0, cap)]
            cnt_x = contributes.astype(jnp.int64)
            pc = jnp.concatenate([jnp.zeros(1, jnp.int64), jnp.cumsum(cnt_x)])
            cnt = pc[jnp.clip(hi1, 0, cap)] - pc[jnp.clip(lo, 0, cap)]
            if op == "min":
                data = jnp.where((cnt > 0) & (cnt == nan_cnt),
                                 jnp.full((), jnp.nan, x.dtype), res)
            else:
                data = jnp.where(nan_cnt > 0, jnp.full((), jnp.nan, x.dtype),
                                 res)
            return data, seg.real & (cnt > 0), col.dtype
        if col.is_var_width:
            raise NotImplementedError(
                "windowed min/max over strings/arrays")
        d = col.data.astype(jnp.int64) if col.data.dtype == jnp.bool_ \
            else col.data
        info = jnp.iinfo(d.dtype)
        ident = info.max if op == "min" else info.min
        base = jnp.where(contributes, d, ident)
        fop = jnp.minimum if op == "min" else jnp.maximum
        levels = _sparse_table(base, fop)
        res = _range_query(levels, lo, hi, fop, ident)
        hi1 = jnp.maximum(hi + 1, lo)
        cnt_x = contributes.astype(jnp.int64)
        pc = jnp.concatenate([jnp.zeros(1, jnp.int64), jnp.cumsum(cnt_x)])
        cnt = pc[jnp.clip(hi1, 0, cap)] - pc[jnp.clip(lo, 0, cap)]
        if col.data.dtype == jnp.bool_:
            res = res.astype(jnp.bool_)
        return res, seg.real & (cnt > 0), col.dtype

    raise ValueError(f"window agg op {op}")


# ---------------------------------------------------------------------------
# ranking / offset functions
# ---------------------------------------------------------------------------

def row_number(seg: SegmentInfo) -> jax.Array:
    idx = jnp.arange(seg.seg_start.shape[0], dtype=jnp.int32)
    return idx - seg.seg_start + 1


def rank(seg: SegmentInfo) -> jax.Array:
    return seg.peer_start - seg.seg_start + 1


def dense_rank(seg: SegmentInfo) -> jax.Array:
    cap = seg.seg_start.shape[0]
    changes = jnp.cumsum(seg.order_change.astype(jnp.int32))
    return changes - changes[seg.seg_start] + 1


def lead_lag(col: DeviceColumn, seg: SegmentInfo, offset: int,
             default_data=None, default_valid=None, default_len=None):
    """lead(offset>0) / lag(offset<0) within the partition.

    ``default_*``: optional out-of-frame fill — scalar-broadcast array
    for fixed-width columns; for strings a [cap, w] byte matrix plus
    ``default_len`` (round-1 advisor finding: strings previously raised
    inside the jitted program)."""
    cap = col.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    src = idx + offset
    in_seg = (src >= seg.seg_start) & (src <= seg.seg_end) & seg.real
    srcc = jnp.clip(src, 0, cap - 1)
    validity = jnp.where(in_seg, col.validity[srcc], False)
    if col.is_var_width:
        cdata = col.data
        if default_data is not None and \
                default_data.shape[1] > cdata.shape[1]:
            cdata = jnp.pad(
                cdata, ((0, 0), (0, default_data.shape[1] - cdata.shape[1])))
        data = jnp.where(validity[:, None], cdata[srcc], 0)
        lengths = jnp.where(validity, col.lengths[srcc], 0)
        if default_data is not None:
            if data.shape[1] < default_data.shape[1]:
                data = jnp.pad(
                    data, ((0, 0), (0, default_data.shape[1] - data.shape[1])))
            use_def = ~in_seg & seg.real & default_valid
            data = jnp.where(use_def[:, None], default_data, data)
            lengths = jnp.where(use_def, default_len, lengths)
            validity = validity | use_def
        return data, validity, lengths
    data = jnp.where(validity, col.data[srcc], jnp.zeros((), col.data.dtype))
    if default_data is not None:
        use_def = ~in_seg & seg.real & default_valid
        data = jnp.where(use_def, default_data, data)
        validity = validity | use_def
    return data, validity, None

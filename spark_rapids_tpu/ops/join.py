"""Equi-join device kernels: sort-merge composed from XLA primitives.

The reference joins through cuDF hash-join kernels
(shims/spark300/.../GpuHashJoin.scala:300-326 doJoinLeftRight:
innerJoin/leftJoin/leftSemiJoin/leftAntiJoin/fullJoin).  XLA has no
device hash table, but `lax.sort` is excellent on TPU, so this kernel is
sort-based (SURVEY.md §7 "hard parts"):

1. **key ids**: concatenate both sides' key columns, one stable
   multi-operand sort, segment boundaries -> dense int32 rank per row,
   comparable across sides (Spark key semantics: NaN==NaN, -0.0==0.0,
   null keys never match).
2. **probe**: sort right ids; per left row `searchsorted` gives the
   contiguous match range [start, end).
3. **count** (phase 1): per-left-row output counts by join type; total
   is materialized to host ONCE at the batch boundary to pick a static
   pow2 output capacity (XLA static-shape discipline, columnar/batch.py).
4. **gather** (phase 2): output slot j -> (left row, right row) via
   cumsum + searchsorted; full-outer appends unmatched right rows by
   scatter.  Gathers build the output columns.

Right outer join is the exec layer's job (swap sides, reorder columns,
exec/joins.py), matching the reference's build-side flip.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.ops.segmented import _cols_differ
from spark_rapids_tpu.ops.sort import encode_key_operands

__all__ = ["join_probe", "join_total", "join_indices_from_probe",
           "gather_join_output", "JOIN_TYPES"]

JOIN_TYPES = ("inner", "left", "semi", "anti", "full", "cross")

_I32MAX = jnp.iinfo(jnp.int32).max


def _combined_key_column(lc: DeviceColumn, rc: DeviceColumn) -> DeviceColumn:
    """Concatenate one key column from both sides (string widths padded
    to the max of the two)."""
    assert type(lc.dtype) is type(rc.dtype), (lc.dtype, rc.dtype)
    validity = jnp.concatenate([lc.validity, rc.validity])
    if lc.is_var_width:
        w = max(lc.max_len, rc.max_len)
        ld = jnp.pad(lc.data, ((0, 0), (0, w - lc.max_len)))
        rd = jnp.pad(rc.data, ((0, 0), (0, w - rc.max_len)))
        return DeviceColumn(jnp.concatenate([ld, rd]), validity, lc.dtype,
                            jnp.concatenate([lc.lengths, rc.lengths]))
    return DeviceColumn(jnp.concatenate([lc.data, rc.data]), validity,
                        lc.dtype)


def _key_ids(lbatch: ColumnBatch, rbatch: ColumnBatch,
             lkeys: Sequence[int], rkeys: Sequence[int]):
    """Dense cross-side key ranks.

    Returns (lid[CL], rid[CR]): int32 rank of each row's key tuple;
    rows that are padding or have any null key get _I32MAX on the left
    and _I32MAX-1 on the right so they never match anything.
    """
    cl, cr = lbatch.capacity, rbatch.capacity
    cc = cl + cr
    cols = [_combined_key_column(lbatch.columns[a], rbatch.columns[b])
            for a, b in zip(lkeys, rkeys)]
    valid = jnp.concatenate([lbatch.row_mask(), rbatch.row_mask()])
    for c in cols:
        valid = valid & c.validity

    operands: list[jax.Array] = [(~valid).astype(jnp.uint8)]  # invalid last
    for c in cols:
        operands.extend(encode_key_operands(c, True))
    iota = jnp.arange(cc, dtype=jnp.int32)
    sorted_ops = lax.sort(operands + [iota], num_keys=len(operands),
                          is_stable=True)
    order = sorted_ops[-1]

    differ = jnp.zeros(cc, jnp.bool_)
    for c in cols:
        sc = DeviceColumn(c.data[order], c.validity[order], c.dtype,
                          None if c.lengths is None else c.lengths[order])
        differ = differ | _cols_differ(sc)
    pos = jnp.arange(cc, dtype=jnp.int32)
    seg = jnp.cumsum(((pos > 0) & differ).astype(jnp.int32))
    ids = jnp.zeros(cc, jnp.int32).at[order].set(seg)
    lid = jnp.where(valid[:cl], ids[:cl], _I32MAX)
    rid = jnp.where(valid[cl:], ids[cl:], _I32MAX - 1)
    return lid, rid


def _probe(lbatch: ColumnBatch, rbatch: ColumnBatch,
           lkeys: Sequence[int], rkeys: Sequence[int], join_type: str):
    """Per-left-row match ranges + per-row output counts."""
    cl, cr = lbatch.capacity, rbatch.capacity
    real_l = lbatch.row_mask()
    num_r = rbatch.num_rows
    if join_type == "cross":
        start = jnp.zeros(cl, jnp.int32)
        cnt = jnp.where(real_l, num_r, 0).astype(jnp.int32)
        rsort_perm = jnp.arange(cr, dtype=jnp.int32)
        out_cnt = cnt
        return start, cnt, rsort_perm, out_cnt, None
    lid, rid = _key_ids(lbatch, rbatch, lkeys, rkeys)
    sorted_rid, rsort_perm = lax.sort(
        [rid, jnp.arange(cr, dtype=jnp.int32)], num_keys=1, is_stable=True)
    start = jnp.searchsorted(sorted_rid, lid, side="left").astype(jnp.int32)
    end = jnp.searchsorted(sorted_rid, lid, side="right").astype(jnp.int32)
    cnt = jnp.where(lid == _I32MAX, 0, end - start)
    out_cnt = _out_cnt(cnt, real_l, join_type)
    unmatched_r = None
    if join_type == "full":
        sorted_lid = lax.sort([lid], num_keys=1)[0]
        s = jnp.searchsorted(sorted_lid, rid, side="left")
        e = jnp.searchsorted(sorted_lid, rid, side="right")
        unmatched_r = rbatch.row_mask() & (e == s)
    return start, cnt, rsort_perm, out_cnt, unmatched_r


def build_prepare_fast(rbatch: ColumnBatch, rkey: int):
    """Sort the build side ONCE by its (single, integral) key.

    Returns ``(sorted_key, perm, nv)``: the build keys sorted ascending
    with the ``nv`` valid entries first and every invalid/padding slot
    rewritten to the dtype max so the array stays globally sorted (probe
    ranges are clipped to ``nv``, which keeps genuine max-valued keys —
    they live at positions < nv).  This is the streaming-join analog of
    the reference's build-side hash table (GpuHashJoin build side,
    GpuHashJoin.scala:193-249): built once, probed per stream batch with
    no per-batch sort.
    """
    col = rbatch.columns[rkey]
    valid = col.validity & rbatch.row_mask()
    cr = rbatch.capacity
    iota = jnp.arange(cr, dtype=jnp.int32)
    flag = (~valid).astype(jnp.uint8)
    _, skey, perm = lax.sort([flag, col.data, iota], num_keys=2,
                             is_stable=True)
    nv = jnp.sum(valid, dtype=jnp.int32)
    maxv = jnp.iinfo(col.data.dtype).max
    skey = jnp.where(iota < nv, skey, maxv)
    return skey, perm, nv


def probe_fast(lbatch: ColumnBatch, lkey: int, sorted_key, perm, nv,
               join_type: str):
    """Per-stream-batch probe against a prepared build side: two
    searchsorted passes, zero sorts.  Same contract as the heavy phase of
    :func:`join_probe` (without full-outer bookkeeping — streaming full
    outer tracks matched build rows in the gather phase instead)."""
    col = lbatch.columns[lkey]
    lvalid = col.validity & lbatch.row_mask()
    start = jnp.searchsorted(sorted_key, col.data, side="left").astype(jnp.int32)
    end = jnp.searchsorted(sorted_key, col.data, side="right").astype(jnp.int32)
    end = jnp.minimum(end, nv)
    start = jnp.minimum(start, end)
    cnt = jnp.where(lvalid, end - start, 0)
    out_cnt = _out_cnt(cnt, lbatch.row_mask(), join_type)
    total = jnp.sum(out_cnt, dtype=jnp.int64)
    return (start, cnt, perm, out_cnt, None), total


def _out_cnt(cnt, real_l, join_type):
    if join_type == "inner":
        return cnt
    if join_type in ("left", "full"):
        return jnp.where(real_l, jnp.maximum(cnt, 1), 0)
    if join_type == "semi":
        return jnp.where(real_l & (cnt > 0), 1, 0).astype(jnp.int32)
    if join_type == "anti":
        return jnp.where(real_l & (cnt == 0), 1, 0).astype(jnp.int32)
    raise ValueError(f"join_type {join_type}")


def matched_build_rows(ri, r_take, cr: int) -> jax.Array:
    """bool[cr]: build rows referenced by matched output slots (streaming
    full-outer bookkeeping, accumulated across stream batches)."""
    slots = jnp.where(r_take, ri, cr)
    return jnp.zeros(cr, jnp.bool_).at[slots].set(True, mode="drop")


def join_probe(lbatch: ColumnBatch, rbatch: ColumnBatch,
               lkeys: Sequence[int], rkeys: Sequence[int],
               join_type: str):
    """Phase 1 (the heavy phase: contains every sort).

    Returns ``(probe_arrays, total)`` where ``probe_arrays`` feeds
    :func:`join_indices_from_probe` and ``total`` is the output row count
    (device scalar).  Splitting probe from gather means the sorts run ONCE
    per join, with only the cheap gather re-specialized per output
    capacity (the reference's two cuDF phases, gather-map + gather,
    GpuHashJoin.scala:300-326, have the same split).
    """
    start, cnt, rsort_perm, out_cnt, unmatched_r = _probe(
        lbatch, rbatch, lkeys, rkeys, join_type)
    total = jnp.sum(out_cnt, dtype=jnp.int64)
    if unmatched_r is not None:
        total = total + jnp.sum(unmatched_r, dtype=jnp.int64)
    return (start, cnt, rsort_perm, out_cnt, unmatched_r), total


def join_total(lbatch: ColumnBatch, rbatch: ColumnBatch,
               lkeys: Sequence[int], rkeys: Sequence[int],
               join_type: str) -> jax.Array:
    """Total output rows (device scalar); prefer :func:`join_probe`."""
    return join_probe(lbatch, rbatch, lkeys, rkeys, join_type)[1]


def join_indices_from_probe(cl: int, probe_arrays, join_type: str,
                            out_cap: int):
    """Phase 2: gather plan into a static ``out_cap`` output from
    precomputed probe arrays (no sorts here).

    Returns (li, ri, l_take, r_take, total):
      li/ri: int32[out_cap] source row per output slot (clamped in range),
      l_take/r_take: bool[out_cap] — False means that side is all-null for
      the slot (outer non-matches) or the slot is padding.
    """
    start, cnt, rsort_perm, out_cnt, unmatched_r = probe_arrays
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32),
         jnp.cumsum(out_cnt)[:-1].astype(jnp.int32)])
    total_left = jnp.sum(out_cnt, dtype=jnp.int32)

    j = jnp.arange(out_cap, dtype=jnp.int32)
    in_left = j < total_left
    # left row for slot j: last offset <= j. offsets is non-decreasing.
    li = (jnp.searchsorted(offsets, j, side="right") - 1).astype(jnp.int32)
    li = jnp.clip(li, 0, cl - 1)
    k = j - offsets[li]
    matched = in_left & (k < cnt[li])
    pos = jnp.clip(start[li] + k, 0, rsort_perm.shape[0] - 1)
    ri = rsort_perm[pos]
    l_take = in_left
    r_take = matched
    total = total_left
    if join_type in ("semi", "anti"):
        r_take = jnp.zeros_like(r_take)
    if unmatched_r is not None:  # full outer: append unmatched right rows
        u_off = total_left + jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(unmatched_r)[:-1].astype(jnp.int32)])
        slots = jnp.where(unmatched_r, u_off, out_cap)
        ridx = jnp.arange(rsort_perm.shape[0], dtype=jnp.int32)
        ri2 = jnp.zeros(out_cap, jnp.int32).at[slots].set(ridx, mode="drop")
        take2 = jnp.zeros(out_cap, jnp.bool_).at[slots].set(
            True, mode="drop")
        ri = jnp.where(take2, ri2, ri)
        r_take = r_take | take2
        total = total + jnp.sum(unmatched_r, dtype=jnp.int32)
    return li, ri, l_take, r_take, total


def gather_join_output(lbatch: ColumnBatch, rbatch: ColumnBatch,
                       li, ri, l_take, r_take, total,
                       schema: T.Schema, include_right: bool) -> ColumnBatch:
    """Build the output batch from a join_indices plan."""
    out_cols: list[DeviceColumn] = []
    for c in lbatch.columns:
        out_cols.append(_take_side(c, li, l_take))
    if include_right:
        for c in rbatch.columns:
            out_cols.append(_take_side(c, ri, r_take))
    return ColumnBatch(out_cols, total.astype(jnp.int32), schema)


def _take_side(c: DeviceColumn, idx, take) -> DeviceColumn:
    validity = c.validity[idx] & take
    if c.is_var_width:
        data = jnp.where(validity[:, None], c.data[idx], 0)
        return DeviceColumn(data, validity, c.dtype,
                            jnp.where(validity, c.lengths[idx], 0))
    data = jnp.where(validity, c.data[idx], jnp.zeros((), c.data.dtype))
    return DeviceColumn(data, validity, c.dtype)

"""Columnar kernel layer: the TPU analog of libcudf's Table operations.

The reference calls into libcudf via JNI at a well-defined seam
(``Table.filter``, ``Table.orderBy``, ``Table.groupBy().aggregate``,
``Table.contiguousSplit``, ``Table.concatenate`` — see SURVEY §2.9).  This
package supplies the same seam as jit-compilable functions over
:class:`~spark_rapids_tpu.columnar.ColumnBatch`, lowered to XLA (with Pallas
for irregular kernels), designed around static shapes + validity masks.
"""
from spark_rapids_tpu.ops.kernels import compact, take, concat_batches, slice_batch
from spark_rapids_tpu.ops.sort import sort_batch, SortOrder
from spark_rapids_tpu.ops.segmented import sorted_group_by

__all__ = [
    "compact", "take", "concat_batches", "slice_batch",
    "sort_batch", "SortOrder", "sorted_group_by",
]

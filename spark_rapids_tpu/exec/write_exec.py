"""Physical write sink: CreateDataWriteExec + the write job driver.

Reference: GpuDataWritingCommandExec / GpuFileFormatWriter — the plan
root that runs a side-effecting directory write and returns no rows.
This is the engine's first side-effecting operator, so exactly-once is
owned here: every task attempt stages privately, the
WriteCommitCoordinator (io/writer.py) arbitrates first-writer-wins per
task, and the job either commits atomically or aborts leaving only
garbage-collectable staging dirs.  With a cluster attached the tasks
run as write fragments on workers (cluster/exec.py
dispatch_write_fragments) under the same coordinator; otherwise the
driver runs them in-process with the same attempt/commit protocol.
"""
from __future__ import annotations

import os
import uuid

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.faults import FaultRegistry, InjectedFault
from spark_rapids_tpu.io.writer import (WRITE_CLUSTER_ENABLED,
                                        WRITE_STAGING_GC, WRITE_STAGING_TTL,
                                        WRITE_TASK_MAX_ATTEMPTS,
                                        WriteCommitCoordinator,
                                        WriteCommitError, WriteStats,
                                        gc_staging, stats_from_manifest,
                                        write_task_attempt)

__all__ = ["CreateDataWriteExec", "run_write_job"]


class CreateDataWriteExec(PlanNode):
    """Plan-root sink that writes its child to ``path`` and yields no
    batches.  ``collect()`` on a write returns no rows; the job's
    :class:`WriteStats` land on :attr:`stats` after execution."""

    def __init__(self, child: PlanNode, path: str, fmt: str = "parquet",
                 partition_by=None, options=None):
        super().__init__([child])
        self.path = path
        self.fmt = fmt
        self.partition_by = list(partition_by or [])
        self.options = dict(options or {})
        self.stats: WriteStats | None = None

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        # the whole job runs as one driver-side "partition": task
        # fan-out happens inside run_write_job (cluster dispatch or the
        # in-process loop), not through the collect pipeline
        return 1

    def partition_iter(self, ctx: ExecCtx, pid: int):
        self.stats = run_write_job(self, ctx)
        yield from ()


def run_write_job(node: CreateDataWriteExec, ctx: ExecCtx) -> WriteStats:
    """Execute a write job end-to-end: GC stale staging, run every task
    to a committed manifest (cluster or in-process), commit atomically,
    invalidate caches that scanned the replaced files.  Any failure or
    cancellation aborts the job — staging is dropped and nothing
    becomes visible."""
    child = node.children[0]
    conf = ctx.conf
    faults = ctx.cached(("fault_registry",),
                        lambda: FaultRegistry.from_conf(conf))
    os.makedirs(node.path, exist_ok=True)
    job_id = uuid.uuid4().hex[:8]
    if conf.get(WRITE_STAGING_GC):
        gc_staging(node.path, conf.get(WRITE_STAGING_TTL), keep_job=job_id)
    coord = WriteCommitCoordinator(node.path, node.fmt, job_id,
                                   faults=faults, conf=conf)
    tasks = list(range(child.num_partitions(ctx)))
    committed = False
    try:
        clustered = False
        cluster = ctx.cache.get("cluster")
        journal = getattr(cluster, "journal", None)
        if journal is not None:
            # write decisions are driver state a crash cannot recompute:
            # journal the job open so recovery can roll an interrupted
            # commit forward (or an uncommitted job back to staging)
            coord.journal = journal
            journal.append("write_start", job=job_id,
                           path=coord.path, fmt=node.fmt)
        if cluster is not None and conf.get(WRITE_CLUSTER_ENABLED):
            from spark_rapids_tpu.cluster.exec import \
                dispatch_write_fragments
            clustered = dispatch_write_fragments(cluster, ctx, coord, node,
                                                 tasks)
        if not clustered:
            _run_local_tasks(node, ctx, coord, tasks, faults)
        missing = coord.missing(tasks)
        if missing:
            raise WriteCommitError(
                f"write job {job_id}: no committed attempt for tasks "
                f"{missing}")
        manifest = coord.commit_job(schema=None if node.partition_by
                                    else child.output_schema.to_arrow(),
                                    options=node.options)
        committed = True
    finally:
        if not committed:
            coord.abort_job()
    from spark_rapids_tpu.exec.result_cache import invalidate_output_paths
    invalidate_output_paths(node.path)
    return stats_from_manifest(manifest)


def _run_local_tasks(node: CreateDataWriteExec, ctx: ExecCtx,
                     coord: WriteCommitCoordinator, tasks, faults) -> None:
    """In-process task loop: each task gets up to ``maxAttempts``
    attempts; a failed attempt (mid-write death, dropped commit
    message) leaves its staging dir for GC and retries under a fresh
    attempt id."""
    max_attempts = max(1, int(ctx.conf.get(WRITE_TASK_MAX_ATTEMPTS)))
    for task in tasks:
        for _ in range(max_attempts):
            ctx.check_cancel()
            attempt = coord.next_attempt(task)
            try:
                m = write_task_attempt(
                    node.children[0], ctx, task,
                    coord.attempt_dir(task, attempt), node.fmt,
                    node.partition_by, node.options, job_id=coord.job_id,
                    attempt=attempt, faults=faults)
            except (InjectedFault, OSError):
                # task attempt died mid-write (injected crash or real
                # I/O failure): its partial staging dir stays behind
                # for GC; retry under the next attempt id
                from spark_rapids_tpu.obs.registry import get_registry
                get_registry().inc("write.task_attempt_failures")
                continue
            if coord.register(m):
                break
        if not coord.has_winner(task):
            raise WriteCommitError(
                f"write task {task} failed after {max_attempts} attempts")

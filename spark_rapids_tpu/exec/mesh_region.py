"""Mesh regions: whole pipelines as ONE per-device program, plus the
mesh-distributed sort.

A mesh *island* (exec/mesh_exec.py) runs one collective operator per
``shard_map`` program: the planner shards the operator's input, runs the
program, and splits the output back into per-device batches.  Between
two islands every batch used to take a host/device-0 round trip — the
exact gather the pod-scale plan shape must avoid.

A mesh *region* extends the island downward: the contiguous elementwise
pipeline feeding a collective operator (filter / project / fused stage —
the same absorbable set as whole-stage fusion, exec/fused.py) is spliced
INTO the per-device program, so batches are sharded once at the region's
leaves, flow shard-resident through the member pipeline and the
collective, and cross the device boundary only at the region's output —
one compiled executable per (pipeline, collective, mesh shape).

:class:`MeshSortExec` completes the operator set: a global sort (or
TopN) as a broadcast sort inside ``shard_map`` — all-gather the shard
rows over ICI, sort the gathered batch per device, and keep each
device's contiguous slice of the total order (reference: GpuSortExec's
total-order contract; the reference reaches distributed order via a
range exchange + per-partition sort, here the gather IS the exchange).
Device order equals global order, so a downstream limit or collect
reads partitions in order with zero cross-device traffic; with
``limit=n`` only device 0 keeps the first n rows (TopN), which a
``GlobalLimitExec`` above passes through untouched.
"""
from __future__ import annotations

import time
from typing import Iterator, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.exec.fused import (FusedStageExec, stage_body,
                                         stage_key_parts)
from spark_rapids_tpu.exec.mesh_exec import (MeshAggregateExec,
                                             MeshExchangeExec,
                                             _MeshOutputMixin,
                                             _check_slice_fault,
                                             _note_a2a_bytes,
                                             _note_slice_recovery,
                                             _reraise_unless_slice_lost,
                                             mesh_for, place_shards)
from spark_rapids_tpu.exec.sortexec import SortExec
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.ops.kernels import gather_columns
from spark_rapids_tpu.ops.sort import sort_permutation
from spark_rapids_tpu.parallel.mesh import (local_view, restack,
                                            shard_batches, shard_map,
                                            split_shards)

__all__ = ["MeshSortExec", "MeshRegionExec"]


class MeshSortExec(_MeshOutputMixin, PlanNode):
    """Global sort / TopN over the mesh as one broadcast-sort program.

    Per-device body: all-gather every shard's rows and counts, build the
    segment-aware real-row mask (gathered segments are packed per shard,
    not globally), run ONE stable multi-operand sort whose leading
    padding-last flag simultaneously front-packs and orders, then keep
    this device's slice of the total order — device i holds rows
    [i*base + min(i, rem), ...), so partition order IS global order.
    With ``limit`` device 0 keeps the first ``limit`` rows and every
    other shard is empty.

    Broadcast cost: every device holds all P*cap gathered rows during
    the sort.  That is the TopN/ORDER-BY-tail shape TPC-H exercises
    (q2/q3/q10: small post-aggregation row sets); a terabyte-scale sort
    wants the range-exchange plan the in-process path already has.
    """

    def __init__(self, orders: Sequence, child: PlanNode, mesh_size: int,
                 limit: int | None = None, axis_name: str = "data"):
        from spark_rapids_tpu.exec.sortexec import resolve_orders
        super().__init__([child])
        self._orders = resolve_orders(orders, child.output_schema)
        self.mesh_size = mesh_size
        self.limit = limit
        self.axis_name = axis_name
        self._jitted = {}

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    @property
    def output_ordering(self):
        return [self.output_schema.names[o.child_index]
                for o in self._orders]

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self.mesh_size if ctx.is_device else 1

    # -- fallback ------------------------------------------------------
    def _single_exec(self) -> SortExec:
        # built lazily so tree-rewrite passes that replace the child are
        # picked up; the limit (if any) is enforced by the
        # GlobalLimitExec the planner keeps above this node
        return SortExec(list(self._orders), self.children[0],
                        global_sort=True)

    # -- distributed program -------------------------------------------
    def _local_step(self):
        """Per-device body (local view in, local view out) — the unit a
        MeshRegionExec splices into its shard_map program."""
        p = self.mesh_size
        axis = self.axis_name
        orders = self._orders
        limit = self.limit
        schema = self.children[0].output_schema

        def step(b: ColumnBatch) -> ColumnBatch:
            cap = b.capacity
            counts = jax.lax.all_gather(b.num_rows, axis)  # int32[P]
            cols = []
            for c in b.columns:
                data = jax.lax.all_gather(c.data, axis, tiled=True)
                val = jax.lax.all_gather(c.validity, axis, tiled=True)
                if c.is_string:
                    ln = jax.lax.all_gather(c.lengths, axis, tiled=True)
                    cols.append(DeviceColumn(data, val, c.dtype, ln))
                else:
                    cols.append(DeviceColumn(data, val, c.dtype))
            gcap = p * cap
            idx = jnp.arange(gcap, dtype=jnp.int32)
            # segment-aware real mask: rows are packed per gathered
            # shard segment, not globally
            real = (idx % cap) < counts[idx // cap]
            total = jnp.sum(counts, dtype=jnp.int32)
            gb = ColumnBatch(cols, total, schema)
            perm = sort_permutation(gb, orders, real=real)
            i = jax.lax.axis_index(axis)
            if limit is None:
                # contiguous slice of the total order per device; each
                # count is <= cap because total <= p*cap
                base = total // p
                rem = total % p
                start = i * base + jnp.minimum(i, rem)
                cnt = base + (i < rem).astype(jnp.int32)
                out_cap = cap
            else:
                out_cap = round_capacity(max(1, min(limit, gcap)))
                start = jnp.int32(0)
                cnt = jnp.where(i == 0,
                                jnp.minimum(jnp.int32(limit), total),
                                jnp.int32(0))
            pick = jnp.clip(start + jnp.arange(out_cap, dtype=jnp.int32),
                            0, gcap - 1)
            out_cols = gather_columns(gb.columns, perm[pick], cnt)
            return ColumnBatch(out_cols, cnt, schema)

        return step

    def _step_key_parts(self) -> tuple:
        return ("mesh_sort", tuple(self._orders),
                self.children[0].output_schema, self.limit, self.mesh_size)

    def _program(self, mesh):
        memo = id(mesh)
        if memo in self._jitted:
            return self._jitted[memo]
        from jax.sharding import PartitionSpec as P

        from spark_rapids_tpu.exec import compile_cache as cc
        axis = self.axis_name
        step = self._local_step()
        key = cc.fragment_key(*self._step_key_parts(),
                              cc.mesh_key_part(mesh, axis))

        def build():
            def prog(stacked: ColumnBatch) -> ColumnBatch:
                return restack(step(local_view(stacked)))
            return cc.instrument(jax.jit(shard_map(
                prog, mesh=mesh, in_specs=P(axis), out_specs=P(axis))))

        fn = cc.get_or_build(key, build)
        self._jitted[memo] = fn
        return fn

    def _outputs_cache_key(self, ctx: ExecCtx) -> tuple:
        return ("meshsort", id(self), ctx.backend)

    def _outputs(self, ctx: ExecCtx):
        return ctx.cached(self._outputs_cache_key(ctx),
                          lambda: self._compute_outputs(ctx))

    def _fallback_outputs(self, ctx: ExecCtx):
        """Single-device recompute from lineage: the in-process global
        sort over the same child — also the degenerate path when the
        mesh never existed or the child produced nothing."""
        out = [list(self._single_exec().partition_iter(ctx, 0))]
        out += [[] for _ in range(self.mesh_size - 1)]
        return out

    def _compute_outputs(self, ctx: ExecCtx):
        from spark_rapids_tpu.exec.core import drain_partitions
        batches = list(drain_partitions(ctx, self.children[0]))
        mesh = mesh_for(ctx, self.mesh_size, self.axis_name)
        t0 = None
        if mesh is not None and batches:
            try:
                _check_slice_fault(ctx, "meshsort", mesh)
                shards = place_shards(batches, self.mesh_size)
                stacked = shard_batches(shards, mesh, self.axis_name)
                _note_a2a_bytes(stacked)
                result = self._program(mesh)(stacked)
                return [[b] for b in split_shards(result)]
            except Exception as err:
                _reraise_unless_slice_lost(err)
                t0 = time.perf_counter()
        out = self._fallback_outputs(ctx)
        if t0 is not None:
            _note_slice_recovery(ctx, time.perf_counter() - t0)
        return out

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        if not ctx.is_device:
            yield from self._single_exec().partition_iter(ctx, pid)
            return
        yield from self._aligned(iter(self._outputs(ctx)[pid]))

    def node_desc(self) -> str:
        lim = f", limit={self.limit}" if self.limit is not None else ""
        return f"MeshSortExec[mesh={self.mesh_size}, {self._orders}{lim}]"


class MeshRegionExec(_MeshOutputMixin, PlanNode):
    """A contiguous elementwise pipeline + its terminal collective
    operator, compiled into ONE per-device ``shard_map`` program.

    ``members`` is innermost-first (members[0] consumes the region
    input); ``terminal`` is a MeshAggregateExec, MeshExchangeExec, or
    MeshSortExec whose child is members[-1].  Like FusedStageExec, every
    member and the terminal keep their ORIGINAL child links, so schema /
    ordering delegation and — critically — lineage-based recovery walk
    the unfused chain: on a lost mesh slice the terminal's own
    single-device fallback re-executes the members as ordinary
    per-batch operators.

    Execution primes the terminal's per-execution output cache and then
    delegates ``partition_iter`` to the terminal, so its partition
    serving (exchange partition slicing, alignment, shrink) is reused
    unchanged.
    """

    combines_batches = True

    def __init__(self, terminal: PlanNode, members: Sequence[PlanNode]):
        assert members, "a region needs at least one absorbed member"
        super().__init__([members[0].children[0]])
        self._terminal = terminal
        self._members = tuple(members)
        # elementary filter/project ops, fused stages unpacked: the
        # region body and key compose per elementary op
        flat = []
        for m in self._members:
            if isinstance(m, FusedStageExec):
                flat.extend(m.fused_ops)
            else:
                flat.append(m)
        self._flat = tuple(flat)
        self.mesh_size = terminal.mesh_size
        self.axis_name = terminal.axis_name
        self._jitted = {}
        # the member chain is the terminal's recovery lineage: after a
        # lost slice the fallback replays it per batch, so a fused
        # member must not have donated (deleted) its input buffers
        for m in self._members:
            if isinstance(m, FusedStageExec):
                m.donate_ok = False

    @property
    def output_schema(self) -> T.Schema:
        return self._terminal.output_schema

    @property
    def output_ordering(self):
        return self._terminal.output_ordering

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self._terminal.num_partitions(ctx)

    @property
    def region_ops(self) -> tuple:
        return self._flat + (self._terminal,)

    # -- program -------------------------------------------------------
    def _is_exchange(self) -> bool:
        return isinstance(self._terminal, MeshExchangeExec)

    def _program(self, mesh, send_capacity: int | None = None):
        memo = (id(mesh), send_capacity)
        if memo in self._jitted:
            return self._jitted[memo]
        from jax.sharding import PartitionSpec as P

        from spark_rapids_tpu.exec import compile_cache as cc
        axis = self.axis_name
        body = stage_body(self._flat)
        if self._is_exchange():
            tstep = self._terminal._local_step(send_capacity)
            tparts = self._terminal._step_key_parts(send_capacity)
        else:
            tstep = self._terminal._local_step()
            tparts = self._terminal._step_key_parts()
        key = cc.fragment_key("mesh_region", stage_key_parts(self._flat),
                              *tparts, self.children[0].output_schema,
                              cc.mesh_key_part(mesh, axis))

        def build():
            if self._is_exchange():
                def prog(stacked: ColumnBatch):
                    out, overflow = tstep(body(local_view(stacked)))
                    return restack(out), restack(overflow)
                out_specs = (P(axis), P(axis))
            else:
                def prog(stacked: ColumnBatch) -> ColumnBatch:
                    return restack(tstep(body(local_view(stacked))))
                out_specs = P(axis)
            return cc.instrument(jax.jit(shard_map(
                prog, mesh=mesh, in_specs=P(axis), out_specs=out_specs)))

        fn = cc.get_or_build(key, build)
        self._jitted[memo] = fn
        return fn

    def _run_exchange(self, ctx: ExecCtx, mesh, stacked):
        # mirror of MeshExchangeExec._run_exchange over the REGION
        # program: a bounded send buffer that overflowed under key skew
        # retries once at worst-case capacity (counted, never truncated)
        import numpy as np

        from spark_rapids_tpu.conf import MESH_SEND_CAPACITY
        send_cap = ctx.conf.get(MESH_SEND_CAPACITY) or None
        result, flags = self._program(mesh, send_cap)(stacked)
        if send_cap is not None and bool(
                # enginelint: disable=RL003 (overflow-flag check; one scalar sync gates the recompile fallback)
                np.asarray(jax.device_get(flags)).any()):
            get_registry().inc("mesh_send_overflows")
            result, _ = self._program(mesh, None)(stacked)
        return result

    # -- execution -----------------------------------------------------
    def _ensure(self, ctx: ExecCtx) -> None:
        ctx.cached(("mesh_region", id(self), ctx.backend),
                   lambda: self._execute(ctx))

    def _execute(self, ctx: ExecCtx) -> bool:
        tkey = self._terminal._outputs_cache_key(ctx)
        from spark_rapids_tpu.exec.core import drain_partitions
        batches = list(drain_partitions(ctx, self.children[0]))
        mesh = mesh_for(ctx, self.mesh_size, self.axis_name)
        t0 = None
        if mesh is not None and batches:
            try:
                _check_slice_fault(ctx, "meshregion", mesh)
                shards = place_shards(batches, self.mesh_size)
                stacked = shard_batches(shards, mesh, self.axis_name)
                _note_a2a_bytes(stacked)
                if self._is_exchange():
                    result = self._run_exchange(ctx, mesh, stacked)
                    ctx.cache[tkey] = ("mesh", split_shards(result))
                else:
                    result = self._program(mesh)(stacked)
                    ctx.cache[tkey] = [[b] for b in split_shards(result)]
                return True
            except Exception as err:
                _reraise_unless_slice_lost(err)
                t0 = time.perf_counter()
        # lost slice / no mesh / empty input: the terminal's own
        # fallback recomputes through the intact member chain
        ctx.cache[tkey] = self._terminal._fallback_outputs(ctx)
        if t0 is not None:
            _note_slice_recovery(ctx, time.perf_counter() - t0)
        return True

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        if not ctx.is_device:
            # host backend: the terminal's host path walks the original
            # member chain as ordinary per-batch operators
            yield from self._terminal.partition_iter(ctx, pid)
            return
        self._ensure(ctx)
        yield from self._aligned(self._terminal.partition_iter(ctx, pid))

    def node_desc(self) -> str:
        inner = " -> ".join([op.node_desc() for op in self._members]
                            + [self._terminal.node_desc()])
        return (f"MeshRegionExec[mesh={self.mesh_size}, "
                f"{len(self._flat) + 1} ops: {inner}]")

"""Mesh regions: whole pipelines as ONE per-device program, plus the
mesh-distributed sort.

A mesh *island* (exec/mesh_exec.py) runs one collective operator per
``shard_map`` program: the planner shards the operator's input, runs the
program, and splits the output back into per-device batches.  Between
two islands every batch used to take a host/device-0 round trip — the
exact gather the pod-scale plan shape must avoid.

A mesh *region* extends the island downward: the contiguous elementwise
pipeline feeding a collective operator (filter / project / fused stage —
the same absorbable set as whole-stage fusion, exec/fused.py) is spliced
INTO the per-device program, so batches are sharded once at the region's
leaves, flow shard-resident through the member pipeline and the
collective, and cross the device boundary only at the region's output —
one compiled executable per (pipeline, collective, mesh shape).

:class:`MeshSortExec` completes the operator set: a global sort (or
TopN) as a broadcast sort inside ``shard_map`` — all-gather the shard
rows over ICI, sort the gathered batch per device, and keep each
device's contiguous slice of the total order (reference: GpuSortExec's
total-order contract; the reference reaches distributed order via a
range exchange + per-partition sort, here the gather IS the exchange).
Device order equals global order, so a downstream limit or collect
reads partitions in order with zero cross-device traffic; with
``limit=n`` only device 0 keeps the first n rows (TopN), which a
``GlobalLimitExec`` above passes through untouched.
"""
from __future__ import annotations

import time
from typing import Iterator, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.exec.fused import (FusedStageExec, stage_body,
                                         stage_key_parts)
from spark_rapids_tpu.exec.mesh_exec import (MeshAggregateExec,
                                             MeshExchangeExec,
                                             MeshJoinExec,
                                             _MeshOutputMixin,
                                             _check_slice_fault,
                                             _note_a2a_bytes,
                                             _note_slice_recovery,
                                             _reraise_unless_slice_lost,
                                             all_gather_batch,
                                             concat_or_empty, drain_cached,
                                             mesh_for, place_shards)
from spark_rapids_tpu.exec.sortexec import SortExec
from spark_rapids_tpu.exec.window import WindowExec, _window_body
from spark_rapids_tpu.expr.core import eval_device
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.ops import kernels as dk
from spark_rapids_tpu.ops.kernels import gather_columns
from spark_rapids_tpu.ops.sort import sort_permutation
from spark_rapids_tpu.parallel.mesh import (local_view, restack,
                                            shard_batches, shard_map,
                                            split_shards)
from spark_rapids_tpu.parallel.mesh_shuffle import (exchange_local,
                                                    partition_ids_for_keys)

__all__ = ["MeshSortExec", "MeshWindowExec", "MeshRegionExec"]


class MeshSortExec(_MeshOutputMixin, PlanNode):
    """Global sort / TopN over the mesh as one broadcast-sort program.

    Per-device body: all-gather every shard's rows and counts, build the
    segment-aware real-row mask (gathered segments are packed per shard,
    not globally), run ONE stable multi-operand sort whose leading
    padding-last flag simultaneously front-packs and orders, then keep
    this device's slice of the total order — device i holds rows
    [i*base + min(i, rem), ...), so partition order IS global order.
    With ``limit`` device 0 keeps the first ``limit`` rows and every
    other shard is empty.

    Broadcast cost: every device holds all P*cap gathered rows during
    the sort.  That is the TopN/ORDER-BY-tail shape TPC-H exercises
    (q2/q3/q10: small post-aggregation row sets); a terabyte-scale sort
    wants the range-exchange plan the in-process path already has.
    """

    def __init__(self, orders: Sequence, child: PlanNode, mesh_size: int,
                 limit: int | None = None, axis_name: str = "data"):
        from spark_rapids_tpu.exec.sortexec import resolve_orders
        super().__init__([child])
        self._orders = resolve_orders(orders, child.output_schema)
        self.mesh_size = mesh_size
        self.limit = limit
        self.axis_name = axis_name
        self._jitted = {}

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    @property
    def output_ordering(self):
        return [self.output_schema.names[o.child_index]
                for o in self._orders]

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self.mesh_size if ctx.is_device else 1

    # -- fallback ------------------------------------------------------
    def _single_exec(self) -> SortExec:
        # built lazily so tree-rewrite passes that replace the child are
        # picked up; the limit (if any) is enforced by the
        # GlobalLimitExec the planner keeps above this node
        return SortExec(list(self._orders), self.children[0],
                        global_sort=True)

    # -- distributed program -------------------------------------------
    def _local_step(self):
        """Per-device body (local view in, local view out) — the unit a
        MeshRegionExec splices into its shard_map program."""
        p = self.mesh_size
        axis = self.axis_name
        orders = self._orders
        limit = self.limit
        schema = self.children[0].output_schema

        def step(b: ColumnBatch) -> ColumnBatch:
            cap = b.capacity
            counts = jax.lax.all_gather(b.num_rows, axis)  # int32[P]
            cols = []
            for c in b.columns:
                data = jax.lax.all_gather(c.data, axis, tiled=True)
                val = jax.lax.all_gather(c.validity, axis, tiled=True)
                if c.is_string:
                    ln = jax.lax.all_gather(c.lengths, axis, tiled=True)
                    cols.append(DeviceColumn(data, val, c.dtype, ln))
                else:
                    cols.append(DeviceColumn(data, val, c.dtype))
            gcap = p * cap
            idx = jnp.arange(gcap, dtype=jnp.int32)
            # segment-aware real mask: rows are packed per gathered
            # shard segment, not globally
            real = (idx % cap) < counts[idx // cap]
            total = jnp.sum(counts, dtype=jnp.int32)
            gb = ColumnBatch(cols, total, schema)
            perm = sort_permutation(gb, orders, real=real)
            i = jax.lax.axis_index(axis)
            if limit is None:
                # contiguous slice of the total order per device; each
                # count is <= cap because total <= p*cap
                base = total // p
                rem = total % p
                start = i * base + jnp.minimum(i, rem)
                cnt = base + (i < rem).astype(jnp.int32)
                out_cap = cap
            else:
                out_cap = round_capacity(max(1, min(limit, gcap)))
                start = jnp.int32(0)
                cnt = jnp.where(i == 0,
                                jnp.minimum(jnp.int32(limit), total),
                                jnp.int32(0))
            pick = jnp.clip(start + jnp.arange(out_cap, dtype=jnp.int32),
                            0, gcap - 1)
            out_cols = gather_columns(gb.columns, perm[pick], cnt)
            return ColumnBatch(out_cols, cnt, schema)

        return step

    def _step_key_parts(self) -> tuple:
        return ("mesh_sort", tuple(self._orders),
                self.children[0].output_schema, self.limit, self.mesh_size)

    def _program(self, mesh):
        memo = id(mesh)
        if memo in self._jitted:
            return self._jitted[memo]
        from jax.sharding import PartitionSpec as P

        from spark_rapids_tpu.exec import compile_cache as cc
        axis = self.axis_name
        step = self._local_step()
        key = cc.fragment_key(*self._step_key_parts(),
                              cc.mesh_key_part(mesh, axis))

        def build():
            def prog(stacked: ColumnBatch) -> ColumnBatch:
                return restack(step(local_view(stacked)))
            return cc.instrument(jax.jit(shard_map(
                prog, mesh=mesh, in_specs=P(axis), out_specs=P(axis))))

        fn = cc.get_or_build(key, build)
        self._jitted[memo] = fn
        return fn

    def _outputs_cache_key(self, ctx: ExecCtx) -> tuple:
        return ("meshsort", id(self), ctx.backend)

    def _outputs(self, ctx: ExecCtx):
        return ctx.cached(self._outputs_cache_key(ctx),
                          lambda: self._compute_outputs(ctx))

    def _fallback_outputs(self, ctx: ExecCtx):
        """Single-device recompute from lineage: the in-process global
        sort over the same child — also the degenerate path when the
        mesh never existed or the child produced nothing."""
        out = [list(self._single_exec().partition_iter(ctx, 0))]
        out += [[] for _ in range(self.mesh_size - 1)]
        return out

    def _compute_outputs(self, ctx: ExecCtx):
        from spark_rapids_tpu.exec.core import drain_partitions
        batches = list(drain_partitions(ctx, self.children[0]))
        mesh = mesh_for(ctx, self.mesh_size, self.axis_name)
        t0 = None
        if mesh is not None and batches:
            try:
                _check_slice_fault(ctx, "meshsort", mesh)
                shards = place_shards(batches, self.mesh_size)
                stacked = shard_batches(shards, mesh, self.axis_name)
                _note_a2a_bytes(stacked)
                result = self._program(mesh)(stacked)
                return [[b] for b in split_shards(result)]
            except Exception as err:
                _reraise_unless_slice_lost(err)
                t0 = time.perf_counter()
        out = self._fallback_outputs(ctx)
        if t0 is not None:
            _note_slice_recovery(ctx, time.perf_counter() - t0)
        return out

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        if not ctx.is_device:
            yield from self._single_exec().partition_iter(ctx, pid)
            return
        yield from self._aligned(iter(self._outputs(ctx)[pid]))

    def node_desc(self) -> str:
        lim = f", limit={self.limit}" if self.limit is not None else ""
        return f"MeshSortExec[mesh={self.mesh_size}, {self._orders}{lim}]"


class MeshWindowExec(_MeshOutputMixin, WindowExec):
    """Window functions distributed over the mesh, by spec shape:

    - **partitioned** (PARTITION BY present): rows hash-exchange on the
      partition keys in-program — Spark-bit-exact murmur3, the same ids
      a planner-inserted exchange would compute — so whole peer groups
      land on one device, then every device runs the columnar window
      kernel (``_window_body``) over its shard.  The reference shape is
      GpuWindowExec downstream of a hash partitioning on the window
      keys; here the exchange and the kernel are ONE program.
    - **global ordered** (no PARTITION BY, ORDER BY present): the frame
      spans the whole input, so every device all-gathers the rows,
      evaluates the global window, and keeps its contiguous slice of
      the ordered output — the MeshSortExec total-order machinery (the
      window body already sorts by the order keys).

    Unpartitioned AND unordered windows keep the in-process path (the
    bounded-memory `_stream_global` two-pass stream beats gathering).
    """

    def __init__(self, window_exprs: Sequence, child: PlanNode,
                 mesh_size: int, axis_name: str = "data"):
        WindowExec.__init__(self, window_exprs, child,
                            keys_partitioned=False)
        self.mesh_size = mesh_size
        self.axis_name = axis_name
        self._jitted = {}

    @property
    def output_batching(self):
        # mesh output is one batch per device shard, not one per
        # partition group — never advertise the single-batch guarantee
        return None

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self.mesh_size if ctx.is_device else 1

    # -- distributed program -------------------------------------------
    def _window_local(self, b: ColumnBatch) -> ColumnBatch:
        aug, orders, part_idx, order_idx, input_idx, nbase = \
            self._window_args(b)
        return _window_body(aug, orders, part_idx, order_idx, input_idx,
                            tuple(self._wexprs), nbase, self._schema)

    def _local_step(self):
        """Per-device body (local view in, local view out) — the unit a
        MeshRegionExec splices into its shard_map program."""
        p = self.mesh_size
        axis = self.axis_name
        part_b = self._part_b

        if part_b:
            def step(b: ColumnBatch) -> ColumnBatch:
                # route on the evaluated partition keys; the keys are
                # recomputed from the shipped raw columns after the
                # exchange (_window_args), so only the input schema
                # travels — no augmented columns on the wire
                cols = list(b.columns)
                fields = list(b.schema.fields)
                kidx = []
                for i, e in enumerate(part_b):
                    cols.append(eval_device(e, b))
                    fields.append(T.StructField(f"_wk{i}", e.dtype, True))
                    kidx.append(len(cols) - 1)
                aug = ColumnBatch(cols, b.num_rows, T.Schema(fields))
                pid = partition_ids_for_keys(aug, kidx, p)
                ex = exchange_local(b, pid, p, axis)
                return self._window_local(ex)
            return step

        def step(b: ColumnBatch) -> ColumnBatch:
            # global frame: gather, evaluate everywhere, keep this
            # device's contiguous slice of the ordered output
            cap = b.capacity
            gb = all_gather_batch(b, p, axis)
            out = self._window_local(gb)
            total = out.num_rows
            i = jax.lax.axis_index(axis)
            base = total // p
            rem = total % p
            start = i * base + jnp.minimum(i, rem)
            cnt = base + (i < rem).astype(jnp.int32)
            pick = jnp.clip(start + jnp.arange(cap, dtype=jnp.int32),
                            0, p * cap - 1)
            out_cols = gather_columns(out.columns, pick, cnt)
            return ColumnBatch(out_cols, cnt, self._schema)
        return step

    def _step_key_parts(self) -> tuple:
        return ("mesh_window", tuple(self._wexprs), tuple(self._part_b),
                tuple((e, asc, nf) for e, asc, nf in self._order_b),
                tuple(self._fn_inputs),
                self.children[0].output_schema, self._schema,
                self.mesh_size)

    def _program(self, mesh):
        memo = id(mesh)
        if memo in self._jitted:
            return self._jitted[memo]
        from jax.sharding import PartitionSpec as P

        from spark_rapids_tpu.exec import compile_cache as cc
        axis = self.axis_name
        step = self._local_step()
        key = cc.fragment_key(*self._step_key_parts(),
                              cc.mesh_key_part(mesh, axis))

        def build():
            def prog(stacked: ColumnBatch) -> ColumnBatch:
                return restack(step(local_view(stacked)))
            return cc.instrument(jax.jit(shard_map(
                prog, mesh=mesh, in_specs=P(axis), out_specs=P(axis))))

        fn = cc.get_or_build(key, build)
        self._jitted[memo] = fn
        return fn

    def _outputs_cache_key(self, ctx: ExecCtx) -> tuple:
        return ("meshwin", id(self), ctx.backend)

    def _outputs(self, ctx: ExecCtx):
        return ctx.cached(self._outputs_cache_key(ctx),
                          lambda: self._compute_outputs(ctx))

    def _fallback_outputs(self, ctx: ExecCtx):
        """Single-device recompute from lineage: the in-process window
        over the same child — also the degenerate path when the mesh
        never existed or the child produced nothing."""
        out = [list(WindowExec.partition_iter(self, ctx, 0))]
        out += [[] for _ in range(self.mesh_size - 1)]
        return out

    def _compute_outputs(self, ctx: ExecCtx):
        from spark_rapids_tpu.exec.core import drain_partitions
        batches = list(drain_partitions(ctx, self.children[0]))
        mesh = mesh_for(ctx, self.mesh_size, self.axis_name)
        t0 = None
        if mesh is not None and batches:
            try:
                _check_slice_fault(ctx, "meshwindow", mesh)
                shards = place_shards(batches, self.mesh_size)
                stacked = shard_batches(shards, mesh, self.axis_name)
                _note_a2a_bytes(stacked)
                result = self._program(mesh)(stacked)
                return [[b] for b in split_shards(result)]
            except Exception as err:
                _reraise_unless_slice_lost(err)
                t0 = time.perf_counter()
        out = self._fallback_outputs(ctx)
        if t0 is not None:
            _note_slice_recovery(ctx, time.perf_counter() - t0)
        return out

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        if not ctx.is_device:
            yield from WindowExec.partition_iter(self, ctx, pid)
            return
        yield from self._aligned(iter(self._outputs(ctx)[pid]))

    def node_desc(self) -> str:
        mode = "partitioned" if self._part_b else "global"
        return (f"MeshWindowExec[mesh={self.mesh_size}, {mode}, "
                f"{self._names}]")


class MeshRegionExec(_MeshOutputMixin, PlanNode):
    """A contiguous pipeline + its terminal collective operator,
    compiled into ONE per-device ``shard_map`` program.

    ``members`` is innermost-first (members[0] consumes the region
    input); ``terminal`` is a MeshAggregateExec, MeshExchangeExec,
    MeshSortExec, or MeshWindowExec whose child is members[-1].
    Members are elementwise ops (filter / project / fused stage) plus
    the collective interiors: a :class:`MeshJoinExec` (its build-side
    broadcast runs as an in-program all_gather in replicated mode, both
    key exchanges as in-program all-to-alls in partitioned mode) and a
    :class:`MeshWindowExec` (in-program hash exchange or gather+slice),
    so a region can hold scan→filter→join→project→agg as one program
    per mesh shape.  Like FusedStageExec, every member and the terminal
    keep their ORIGINAL child links, so schema / ordering delegation
    and — critically — lineage-based recovery walk the unfused chain:
    on a lost mesh slice the terminal's own single-device fallback
    re-executes the members as ordinary per-batch operators (a join
    member recomputes BOTH its sides from lineage).

    The region's children are the pipeline leaf plus one build-side
    subtree per absorbed join — those stay real plan edges: they are
    drained on the host side (the replicated/partitioned mode pick
    needs the materialized size) and their batches are stacked onto the
    mesh as extra program inputs.

    Execution primes the terminal's per-execution output cache and then
    delegates ``partition_iter`` to the terminal, so its partition
    serving (exchange partition slicing, alignment, shrink) is reused
    unchanged.  When the leaf is itself a mesh exchange — bare or a
    chained region's exchange terminal — the upstream output shards
    stay committed one-per-device and are stacked in place
    (``_chained_shards``): no gather, no host hop between regions.
    """

    combines_batches = True

    def __init__(self, terminal: PlanNode, members: Sequence[PlanNode]):
        assert members, "a region needs at least one absorbed member"
        self._terminal = terminal
        self._members = tuple(members)
        # elementary ops with fused stages unpacked, joins/windows kept
        # in place: the region body and key compose per element
        flat = []
        for m in self._members:
            if isinstance(m, FusedStageExec):
                flat.extend(m.fused_ops)
            else:
                flat.append(m)
        self._flat = tuple(flat)
        # segment the flat pipeline: maximal elementwise runs lower via
        # stage_body; each join/window is its own collective segment
        segs: list[tuple] = []
        run: list = []
        for op in flat:
            if isinstance(op, (MeshJoinExec, MeshWindowExec)):
                if run:
                    segs.append(("stage", tuple(run)))
                    run = []
                segs.append(("join" if isinstance(op, MeshJoinExec)
                             else "window", op))
            else:
                run.append(op)
        if run:
            segs.append(("stage", tuple(run)))
        self._segs = tuple(segs)
        self._joins = tuple(op for k, op in segs if k == "join")
        super().__init__([members[0].children[0]]
                         + [j.children[1] for j in self._joins])
        self.mesh_size = terminal.mesh_size
        self.axis_name = terminal.axis_name
        self._jitted = {}
        # the member chain is the terminal's recovery lineage: after a
        # lost slice the fallback replays it per batch, so a fused
        # member must not have donated (deleted) its input buffers
        for m in self._members:
            if isinstance(m, FusedStageExec):
                m.donate_ok = False

    @property
    def output_schema(self) -> T.Schema:
        return self._terminal.output_schema

    @property
    def output_ordering(self):
        return self._terminal.output_ordering

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self._terminal.num_partitions(ctx)

    @property
    def region_ops(self) -> tuple:
        return self._flat + (self._terminal,)

    # -- program -------------------------------------------------------
    def _is_exchange(self) -> bool:
        return isinstance(self._terminal, MeshExchangeExec)

    def _caps(self, leaf_cap: int, modes: tuple, send_cap: int | None,
              floors=None) -> tuple:
        """Symbolic per-device capacity walk over the segments, yielding
        the STATIC output capacity of each join (shard_map bodies cannot
        sync the probe total).  Elementwise stages and the global-window
        slice preserve capacity; a partitioned exchange's worst case is
        P*C; a join's output capacity starts as its post-exchange stream
        capacity and is floored by the measured total on a retry."""
        p = self.mesh_size
        cap = leaf_cap
        caps = []
        ji = 0
        for kind, seg in self._segs:
            if kind == "join":
                if modes[ji] == "partitioned":
                    c = cap if send_cap is None else min(send_cap, cap)
                    cap = p * c
                guess = round_capacity(max(cap, 8))
                if floors is not None and floors[ji]:
                    guess = max(guess, floors[ji])
                caps.append(guess)
                cap = guess
                ji += 1
            elif kind == "window" and seg._part_b:
                cap = p * cap
        return tuple(caps)

    def _body_key_parts(self, modes: tuple, caps: tuple,
                        send_capacity: int | None) -> tuple:
        parts = []
        ji = 0
        for kind, seg in self._segs:
            if kind == "stage":
                parts.append(("stage", stage_key_parts(seg)))
            elif kind == "join":
                parts.append(seg._region_step_key_parts(
                    modes[ji], caps[ji], send_capacity))
                ji += 1
            else:
                parts.append(seg._step_key_parts())
        return tuple(parts)

    def _program(self, mesh, send_capacity: int | None = None,
                 modes: tuple = (), caps: tuple = ()):
        memo = (id(mesh), send_capacity, modes, caps)
        if memo in self._jitted:
            return self._jitted[memo]
        from jax.sharding import PartitionSpec as P

        from spark_rapids_tpu.exec import compile_cache as cc
        axis = self.axis_name
        steps = []
        ji = 0
        for kind, seg in self._segs:
            if kind == "stage":
                steps.append(("stage", stage_body(seg)))
            elif kind == "join":
                steps.append(("join", seg._region_step(
                    modes[ji], caps[ji], send_capacity)))
                ji += 1
            else:
                steps.append(("window", seg._local_step()))
        is_ex = self._is_exchange()
        if is_ex:
            tstep = self._terminal._local_step(send_capacity)
            tparts = self._terminal._step_key_parts(send_capacity)
        else:
            tstep = self._terminal._local_step()
            tparts = self._terminal._step_key_parts()
        key = cc.fragment_key(
            "mesh_region", self._body_key_parts(modes, caps, send_capacity),
            *tparts, tuple(c.output_schema for c in self.children),
            cc.mesh_key_part(mesh, axis))
        n_builds = len(self._joins)
        n_flags = 2 * sum(m == "partitioned" for m in modes) \
            + (1 if is_ex else 0)
        n_aux = n_builds + n_flags

        def build():
            def prog(stacked, *builds):
                b = local_view(stacked)
                blocal = [local_view(x) for x in builds]
                totals, flags = [], []
                bi = 0
                for kind, step in steps:
                    if kind == "join":
                        b, (total, fl) = step(b, blocal[bi])
                        totals.append(total)
                        flags.extend(fl)
                        bi += 1
                    else:
                        b = step(b)
                if is_ex:
                    out, ovf = tstep(b)
                    flags.append(ovf)
                else:
                    out = tstep(b)
                aux = tuple(restack(t) for t in totals) \
                    + tuple(restack(f) for f in flags)
                return restack(out), aux
            in_specs = (P(axis),) * (1 + n_builds)
            out_specs = (P(axis), (P(axis),) * n_aux)
            return cc.instrument(jax.jit(shard_map(
                prog, mesh=mesh, in_specs=in_specs, out_specs=out_specs)))

        fn = cc.get_or_build(key, build)
        self._jitted[memo] = fn
        return fn

    def _launch(self, ctx: ExecCtx, mesh, stacked, builds, leaf_cap: int):
        """Run the region program, re-running on the two loud
        under-capacity signals (never truncating): a join whose probe
        total exceeded its static output capacity recompiles at the
        rounded-up measured size; an overflowed bounded send buffer
        falls back to worst-case capacity (the mesh analog of the OOM
        split-and-retry ladder).  All join totals and overflow flags
        are read back in ONE stacked device fetch per attempt."""
        import numpy as np

        from spark_rapids_tpu.conf import MESH_SEND_CAPACITY
        send_cap = ctx.conf.get(MESH_SEND_CAPACITY) or None
        modes = tuple("partitioned" if j._use_partitioned(ctx)
                      else "replicated" for j in self._joins)
        nj = len(self._joins)
        floors = [0] * nj
        result = None
        for _ in range(nj + 2):
            caps = self._caps(leaf_cap, modes, send_cap, floors)
            result, aux = self._program(mesh, send_cap, modes, caps)(
                stacked, *builds)
            if not aux or (nj == 0 and send_cap is None):
                return result
            vals = [np.asarray(v) for v in
                    # enginelint: disable=RL003 (join totals + overflow flags; one stacked sync gates the retry)
                    jax.device_get(aux)]
            retry = False
            for i in range(nj):
                total = int(vals[i].max())
                if total > caps[i]:
                    get_registry().inc("mesh_join_capacity_retries")
                    floors[i] = max(floors[i],
                                    round_capacity(max(total, 1)))
                    retry = True
            if send_cap is not None and any(v.any() for v in vals[nj:]):
                get_registry().inc("mesh_send_overflows")
                send_cap = None
                retry = True
            if not retry:
                return result
        return result

    # -- execution -----------------------------------------------------
    def _ensure(self, ctx: ExecCtx) -> None:
        ctx.cached(("mesh_region", id(self), ctx.backend),
                   lambda: self._execute(ctx))

    def _chained_shards(self, ctx: ExecCtx):
        """Region chaining: when the leaf IS a mesh exchange — bare, or
        an upstream region's exchange terminal — on the same mesh, its
        output shards are already committed one-per-device; consume
        them in place instead of slicing partitions out, shrinking,
        and re-sharding.  Returns None when the upstream degraded to
        host partitions (its fallback path) or the meshes differ — the
        caller then drains partitions normally."""
        leaf = self.children[0]
        if isinstance(leaf, MeshExchangeExec):
            up = leaf
        elif isinstance(leaf, MeshRegionExec) and leaf._is_exchange():
            leaf._ensure(ctx)
            up = leaf._terminal
        else:
            return None
        if up.mesh_size != self.mesh_size \
                or up.axis_name != self.axis_name:
            return None
        kind, out = up._outputs(ctx)
        if kind != "mesh":
            return None
        get_registry().inc("mesh_region_chains")
        return list(out)

    def _execute(self, ctx: ExecCtx) -> bool:
        tkey = self._terminal._outputs_cache_key(ctx)
        from spark_rapids_tpu.conf import MESH_REGION_CHAINING
        from spark_rapids_tpu.exec.core import drain_partitions
        mesh = mesh_for(ctx, self.mesh_size, self.axis_name)
        chained = None
        if mesh is not None and ctx.conf.get(MESH_REGION_CHAINING):
            chained = self._chained_shards(ctx)
        batches = chained if chained is not None \
            else list(drain_partitions(ctx, self.children[0]))
        t0 = None
        if mesh is not None and batches:
            try:
                _check_slice_fault(ctx, "meshregion", mesh)
                shards = chained if chained is not None \
                    else place_shards(batches, self.mesh_size)
                leaf_cap = shards[0].capacity
                stacked = shard_batches(shards, mesh, self.axis_name)
                if chained is None:
                    _note_a2a_bytes(stacked)
                builds = []
                for j in self._joins:
                    bl = drain_cached(ctx, j.children[1]) or \
                        [concat_or_empty([], j.children[1].output_schema)]
                    bshards = place_shards(bl, self.mesh_size)
                    bstacked = shard_batches(bshards, mesh, self.axis_name)
                    _note_a2a_bytes(bstacked)
                    builds.append(bstacked)
                result = self._launch(ctx, mesh, stacked, builds, leaf_cap)
                if self._is_exchange():
                    ctx.cache[tkey] = ("mesh", split_shards(result))
                else:
                    ctx.cache[tkey] = [[b] for b in split_shards(result)]
                return True
            except Exception as err:
                _reraise_unless_slice_lost(err)
                t0 = time.perf_counter()
        # lost slice / no mesh / empty input: the terminal's own
        # fallback recomputes through the intact member chain — a join
        # member's island path re-materializes BOTH its sides, so the
        # whole region lineage (build subtrees included) replays
        ctx.cache[tkey] = self._terminal._fallback_outputs(ctx)
        if t0 is not None:
            _note_slice_recovery(ctx, time.perf_counter() - t0)
        return True

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        if not ctx.is_device:
            # host backend: the terminal's host path walks the original
            # member chain as ordinary per-batch operators
            yield from self._terminal.partition_iter(ctx, pid)
            return
        self._ensure(ctx)
        yield from self._aligned(self._terminal.partition_iter(ctx, pid))

    def node_desc(self) -> str:
        inner = " -> ".join([op.node_desc() for op in self._members]
                            + [self._terminal.node_desc()])
        return (f"MeshRegionExec[mesh={self.mesh_size}, "
                f"{len(self._flat) + 1} ops: {inner}]")

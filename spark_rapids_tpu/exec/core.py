"""Physical plan node base + execution context.

TPU analog of the reference's ``GpuExec`` layer (reference
sql-plugin/src/main/scala/com/nvidia/spark/rapids/GpuExec.scala:65-137):
a columnar physical operator produces, per partition, an iterator of
batches.  Where the reference rides Spark's RDD machinery
(``doExecuteColumnar(): RDD[ColumnarBatch]``), this standalone engine
models the same contract directly: ``num_partitions`` + per-partition
batch iterators, with exchanges as stage barriers.

Every node runs on two backends:
* ``device`` — ColumnBatch (jax, jit-compiled kernels), the TPU path;
* ``host``   — HostBatch (numpy), the CPU oracle used for differential
  testing (reference SparkQueryCompareTestSuite.scala:153-167) and as the
  CPU baseline for benchmarks.

Metrics mirror GpuMetricNames (GpuExec.scala:27-56): numOutputRows,
numOutputBatches, totalTime per operator.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Iterator, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.host.batch import HostBatch

__all__ = [
    "ExecCtx", "PlanNode", "CoalesceGoal", "TargetSize", "RequireSingleBatch",
    "collect", "collect_host", "collect_device", "Metrics",
]


# ---------------------------------------------------------------------------
# Batching contracts (reference CoalesceGoal algebra,
# GpuCoalesceBatches.scala:94-130)
# ---------------------------------------------------------------------------

class CoalesceGoal:
    def max_with(self, other: "CoalesceGoal") -> "CoalesceGoal":
        if isinstance(self, RequireSingleBatchT) or \
                isinstance(other, RequireSingleBatchT):
            return RequireSingleBatch
        assert isinstance(self, TargetSize) and isinstance(other, TargetSize)
        return self if self.size >= other.size else other

    def satisfies(self, other: "CoalesceGoal") -> bool:
        if isinstance(other, RequireSingleBatchT):
            return isinstance(self, RequireSingleBatchT)
        return True


@dataclass(frozen=True)
class TargetSize(CoalesceGoal):
    size: int


class RequireSingleBatchT(CoalesceGoal):
    def __repr__(self):
        return "RequireSingleBatch"


RequireSingleBatch = RequireSingleBatchT()


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

class Metrics:
    """Per-operator metric map (reference GpuMetricNames)."""

    def __init__(self):
        self.values: dict[str, float] = {}

    def add(self, name: str, v: float):
        self.values[name] = self.values.get(name, 0.0) + v

    def __getitem__(self, name: str) -> float:
        return self.values.get(name, 0.0)


@dataclass
class ExecCtx:
    """Execution context: backend selection + conf + metrics sink."""

    backend: str = "device"          # "device" | "host"
    conf: TpuConf = field(default_factory=lambda: TpuConf({}))
    metrics: dict[str, Metrics] = field(default_factory=dict)
    # per-run stage cache: exchanges materialize their shuffle output here
    # once per execution (reference: shuffle files / ShuffleBufferCatalog)
    cache: dict = field(default_factory=dict)

    def metrics_for(self, node: "PlanNode") -> Metrics:
        key = f"{type(node).__name__}@{id(node):x}"
        if key not in self.metrics:
            self.metrics[key] = Metrics()
        return self.metrics[key]

    @property
    def is_device(self) -> bool:
        return self.backend == "device"


# ---------------------------------------------------------------------------
# Plan node
# ---------------------------------------------------------------------------

class PlanNode:
    """Base physical operator.

    Subclasses implement ``partition_iter`` producing batches for one
    partition on the active backend. ``output_schema`` is the operator's
    output schema; ``children`` its inputs.
    """

    def __init__(self, children: Sequence["PlanNode"]):
        self.children = tuple(children)

    # -- contract ----------------------------------------------------------
    @property
    def output_schema(self) -> T.Schema:
        raise NotImplementedError

    def num_partitions(self, ctx: ExecCtx) -> int:
        if self.children:
            return self.children[0].num_partitions(ctx)
        return 1

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        raise NotImplementedError

    # -- batching contracts (reference GpuExec.scala:71-86) ----------------
    @property
    def children_coalesce_goal(self) -> list[CoalesceGoal | None]:
        return [None] * len(self.children)

    @property
    def output_batching(self) -> CoalesceGoal | None:
        return None

    # -- execution helpers -------------------------------------------------
    def execute(self, ctx: ExecCtx) -> Iterator:
        """All partitions' batches, in partition order, with output
        metrics recorded for this (root) node."""
        for pid in range(self.num_partitions(ctx)):
            yield from self.timed_iter(ctx, self.partition_iter(ctx, pid))

    def timed_iter(self, ctx: ExecCtx, it: Iterator) -> Iterator:
        """Wrap an iterator with totalTime / output metrics."""
        m = ctx.metrics_for(self)
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            m.add("totalTime", time.perf_counter() - t0)
            m.add("numOutputBatches", 1)
            yield batch

    # -- plan introspection ------------------------------------------------
    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + self.node_desc() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def node_desc(self) -> str:
        return type(self).__name__


# ---------------------------------------------------------------------------
# Collect surface
# ---------------------------------------------------------------------------

def _rows_from_host(b: HostBatch) -> list[tuple]:
    cols = [c.to_list() for c in b.columns]
    return list(zip(*cols)) if cols else [()] * b.num_rows


def collect_host(plan: PlanNode, conf: TpuConf | None = None) -> list[tuple]:
    """Run on the CPU oracle; rows as python tuples."""
    ctx = ExecCtx(backend="host", conf=conf or TpuConf({}))
    out: list[tuple] = []
    for b in plan.execute(ctx):
        out.extend(_rows_from_host(b))
    return out


def collect_device(plan: PlanNode, conf: TpuConf | None = None) -> list[tuple]:
    """Run on the TPU path; rows as python tuples (D2H at the end only)."""
    ctx = ExecCtx(backend="device", conf=conf or TpuConf({}))
    out: list[tuple] = []
    for b in plan.execute(ctx):
        hb = device_to_host(b)
        out.extend(_rows_from_host(hb))
    return out


def collect(plan: PlanNode, backend: str = "device",
            conf: TpuConf | None = None) -> list[tuple]:
    if backend == "host":
        return collect_host(plan, conf)
    return collect_device(plan, conf)


def device_to_host(b: ColumnBatch) -> HostBatch:
    """D2H: ColumnBatch -> HostBatch (reference GpuColumnarToRowExec /
    GpuBringBackToHost transition)."""
    import jax
    import numpy as np
    from spark_rapids_tpu.host.batch import HostColumn
    n = b.host_num_rows()
    host = jax.device_get([(c.data, c.validity, c.lengths) for c in b.columns])
    cols = []
    for f, (data, validity, lengths) in zip(b.schema, host):
        v = np.asarray(validity[:n], dtype=np.bool_)
        if isinstance(f.data_type, T.StringType):
            bm = np.asarray(data[:n])
            ln = np.asarray(lengths[:n])
            py = np.empty(n, dtype=object)
            for i in range(n):
                py[i] = bytes(bm[i, :ln[i]]).decode("utf-8", "replace") \
                    if v[i] else None
            cols.append(HostColumn(py, v, f.data_type))
        else:
            cols.append(HostColumn(np.asarray(data[:n]), v, f.data_type))
    return HostBatch(cols, b.schema)


def host_to_device(b: HostBatch, capacity: int | None = None) -> ColumnBatch:
    """H2D: HostBatch -> ColumnBatch (reference HostColumnarToGpu)."""
    import jax.numpy as jnp
    import numpy as np
    from spark_rapids_tpu.columnar.batch import round_capacity
    from spark_rapids_tpu.columnar.column import (DeviceColumn,
                                                  round_string_width)
    n = b.num_rows
    cap = capacity or round_capacity(max(n, 1))
    cols = []
    for f, col in zip(b.schema, b.columns):
        if isinstance(f.data_type, T.StringType):
            enc = [(x.encode("utf-8") if x is not None else b"")
                   for x in col.data]
            maxw = max((len(e) for e in enc), default=1)
            w = round_string_width(max(maxw, 1))
            bm = np.zeros((n, w), dtype=np.uint8)
            lens = np.zeros(n, dtype=np.int32)
            for i, e in enumerate(enc):
                bm[i, :len(e)] = np.frombuffer(e, dtype=np.uint8)
                lens[i] = len(e)
            cols.append(DeviceColumn.strings_from_numpy(
                bm, lens, col.validity, cap))
        else:
            cols.append(DeviceColumn.from_numpy(
                col.data, col.validity, f.data_type, cap))
    return ColumnBatch(cols, jnp.asarray(n, dtype=jnp.int32), b.schema)

"""Physical plan node base + execution context.

TPU analog of the reference's ``GpuExec`` layer (reference
sql-plugin/src/main/scala/com/nvidia/spark/rapids/GpuExec.scala:65-137):
a columnar physical operator produces, per partition, an iterator of
batches.  Where the reference rides Spark's RDD machinery
(``doExecuteColumnar(): RDD[ColumnarBatch]``), this standalone engine
models the same contract directly: ``num_partitions`` + per-partition
batch iterators, with exchanges as stage barriers.

Every node runs on two backends:
* ``device`` — ColumnBatch (jax, jit-compiled kernels), the TPU path;
* ``host``   — HostBatch (numpy), the CPU oracle used for differential
  testing (reference SparkQueryCompareTestSuite.scala:153-167) and as the
  CPU baseline for benchmarks.

Metrics mirror GpuMetricNames (GpuExec.scala:27-56): numOutputRows,
numOutputBatches, totalTime per operator.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import ConfEntry, TpuConf, register
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.runtime import widen_thread_stacks

# worker threads created from here on (drain pools, shuffle servers) get
# deep stacks — XLA:CPU compiles overflow the 8 MiB default (runtime.py)
widen_thread_stacks()

__all__ = [
    "ExecCtx", "PlanNode", "CoalesceGoal", "TargetSize", "RequireSingleBatch",
    "collect", "collect_host", "collect_device", "Metrics",
    "drain_partitions", "drain_partitions_indexed",
]

CONCURRENT_TASKS = register(ConfEntry(
    "spark.rapids.sql.concurrentTpuTasks", 2,
    "Concurrent tasks allowed to occupy the chip (reference "
    "spark.rapids.sql.concurrentGpuTasks, RapidsConf.scala:351). "
    "Partitions execute on a worker pool bounded by this semaphore.",
    conv=int))

PROFILE_DIR = register(ConfEntry(
    "spark.rapids.tpu.profile.dir", "",
    "When set, collect() records an xprof/PJRT trace of the execution "
    "into this directory, with per-operator TraceAnnotation ranges "
    "(reference NVTX ranges + NvtxWithMetrics.scala:27; view with "
    "tensorboard or xprof)."))


# ---------------------------------------------------------------------------
# Batching contracts (reference CoalesceGoal algebra,
# GpuCoalesceBatches.scala:94-130)
# ---------------------------------------------------------------------------

class CoalesceGoal:
    def max_with(self, other: "CoalesceGoal") -> "CoalesceGoal":
        if isinstance(self, RequireSingleBatchT) or \
                isinstance(other, RequireSingleBatchT):
            return RequireSingleBatch
        assert isinstance(self, TargetSize) and isinstance(other, TargetSize)
        return self if self.size >= other.size else other

    def satisfies(self, other: "CoalesceGoal") -> bool:
        if isinstance(other, RequireSingleBatchT):
            return isinstance(self, RequireSingleBatchT)
        return True


@dataclass(frozen=True)
class TargetSize(CoalesceGoal):
    size: int


class RequireSingleBatchT(CoalesceGoal):
    def __repr__(self):
        return "RequireSingleBatch"


RequireSingleBatch = RequireSingleBatchT()


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

class Metrics:
    """Per-operator metric map (reference GpuMetricNames).  add() is
    called concurrently from drain_partitions worker threads, so the
    read-modify-write is locked."""

    def __init__(self):
        self.values: dict[str, float] = {}
        self._lock = threading.Lock()

    def add(self, name: str, v: float):
        with self._lock:
            self.values[name] = self.values.get(name, 0.0) + v

    def __getitem__(self, name: str) -> float:
        return self.values.get(name, 0.0)


@dataclass
class ExecCtx:
    """Execution context: backend + conf + metrics + device runtime.

    The runtime members are the execution-side wiring of the memory
    subsystem (reference RapidsExecutorPlugin.init, Plugin.scala:124-154):
    a shared BufferCatalog (spill tiers), a DeviceSemaphore bounding chip
    occupancy, and a worker pool draining partitions concurrently.
    """

    backend: str = "device"          # "device" | "host"
    conf: TpuConf = field(default_factory=lambda: TpuConf({}))
    metrics: dict[str, Metrics] = field(default_factory=dict)
    # per-run stage cache: exchanges materialize their shuffle output here
    # once per execution (reference: shuffle files / ShuffleBufferCatalog)
    cache: dict = field(default_factory=dict)
    # shuffle_id -> ShuffleLineage (exec/recovery.py): how each shuffle's
    # map outputs were produced, so a terminal fetch loss re-executes
    # exactly the dead map partitions instead of failing the query
    # (reference: MapOutputTracker registrations driving DAGScheduler
    # stage resubmission on FetchFailed)
    lineage: dict = field(default_factory=dict)
    _lock: threading.RLock = field(default_factory=threading.RLock)
    _inflight: dict = field(default_factory=dict)

    def metrics_for(self, node: "PlanNode") -> Metrics:
        key = f"{type(node).__name__}@{id(node):x}"
        with self._lock:
            if key not in self.metrics:
                self.metrics[key] = Metrics()
            return self.metrics[key]

    @property
    def is_device(self) -> bool:
        return self.backend == "device"

    @property
    def metrics_enabled(self) -> bool:
        if "metrics_enabled" not in self.cache:
            from spark_rapids_tpu.conf import METRICS_ENABLED
            self.cache["metrics_enabled"] = self.conf.get(METRICS_ENABLED)
        return self.cache["metrics_enabled"]

    # -- device runtime ----------------------------------------------------
    @property
    def task_concurrency(self) -> int:
        return max(1, self.conf.get(CONCURRENT_TASKS))

    @property
    def catalog(self):
        with self._lock:
            if "catalog" not in self.cache:
                from spark_rapids_tpu.memory.catalog import BufferCatalog
                cat = BufferCatalog(conf=self.conf)
                # spill I/O is a cooperative cancellation point: a
                # cancelled query must stop pushing bytes to disk
                cat.lifecycle = self.lifecycle
                # cross-query governor (memory/governor.py): attribute
                # this catalog's device bytes to the query and let OOM
                # retries arbitrate against peer queries instead of
                # blind-sweeping.  No-op when the governor conf is off
                from spark_rapids_tpu.memory.governor import maybe_register
                maybe_register(cat, self.query_id, self.lifecycle,
                               self.conf)
                self.cache["catalog"] = cat
            return self.cache["catalog"]

    @property
    def semaphore(self):
        with self._lock:
            if "semaphore" not in self.cache:
                from spark_rapids_tpu.memory.catalog import DeviceSemaphore
                self.cache["semaphore"] = DeviceSemaphore(
                    self.task_concurrency)
            return self.cache["semaphore"]

    # -- query lifecycle (exec/lifecycle.py) -------------------------------
    @property
    def lifecycle(self):
        """Per-query lifecycle handle (cancel event + deadline), minted
        lazily alongside the query id.  Direct ExecCtx users get one
        that is already RUNNING; TpuSession pre-populates the cache
        with an ADMITTED handle it controls."""
        lc = self.cache.get("lifecycle")
        if lc is not None:
            return lc
        with self._lock:
            lc = self.cache.get("lifecycle")
            if lc is None:
                from spark_rapids_tpu.exec.lifecycle import QueryLifecycle
                lc = QueryLifecycle.from_conf(self.query_id, self.conf)
                lc.start()
                self.cache["lifecycle"] = lc
            return lc

    def check_cancel(self) -> None:
        """Cooperative cancellation point: raises the terminal
        QueryCancelled/QueryDeadlineExceeded once the query is
        cancelled or past its deadline (reference: tasks polling
        TaskContext.isInterrupted inside long loops)."""
        self.lifecycle.check()

    def dispatch(self, fn, *args, **kwargs):
        """Run a heavy device program under (a) the DeviceSemaphore
        bounding chip occupancy (reference GpuSemaphore.acquireIfNecessary
        — acquired at the dispatch chokepoint, never while blocking on
        other tasks, so nested partition drains cannot deadlock) and
        (b) the OOM-spill-retry hook (DeviceMemoryEventHandler loop).
        Every dispatch is a cancellation point: a cancelled query stops
        before it can occupy the chip again."""
        self.check_cancel()
        if not self.is_device:
            return fn(*args, **kwargs)
        from spark_rapids_tpu.memory.catalog import run_with_spill_retry
        with self.semaphore:
            return run_with_spill_retry(fn, self.catalog, *args, **kwargs)

    def dispatch_retry(self, fn, batch, *, split: bool = True,
                       op: str | None = None, pairs: bool = False,
                       checkpoint=None, restore=None) -> list:
        """Run ``fn(batch)`` under the full OOM retry scope
        (memory/retry.py): spill on RESOURCE_EXHAUSTED, and when spill
        frees nothing split the batch in half by rows and retry each
        half — the reference's RmmRapidsRetryIterator.withRetry.
        Returns the outputs in row order (one unless a split happened);
        ``split=False`` is withRetryNoSplit for steps whose partial
        outputs would break semantics.  ``pairs=True`` returns
        ``(piece, output)`` tuples so callers can retain the processed
        pieces for a later :meth:`retry_sync` redo."""
        self.check_cancel()
        if not self.is_device:
            r = fn(batch)
            return [(batch, r)] if pairs else [r]
        from spark_rapids_tpu.memory import retry as _retry
        with self.semaphore:
            return _retry.with_retry(
                fn, self.catalog, batch,
                split=_retry.split_half if split else None, op=op,
                pairs=pairs, checkpoint=checkpoint, restore=restore,
                settings=self.conf.settings)

    def retry_sync(self, sync_fn, *, redo=None, op: str = "sync"):
        """Guard a blocking sync of asynchronously dispatched device
        work (chunk-flush device_get): on OOM spill, ``redo()`` the
        poisoned dispatches from retained inputs, and sync again — the
        async-backend OOMs that used to surface outside every retry
        loop are recovered here."""
        self.check_cancel()
        if not self.is_device:
            return sync_fn()
        from spark_rapids_tpu.memory import retry as _retry
        return _retry.retry_sync(sync_fn, self.catalog, redo=redo, op=op,
                                 settings=self.conf.settings)

    def register_lineage(self, shuffle_id, lineage) -> None:
        with self._lock:
            self.lineage[shuffle_id] = lineage

    def lineage_for(self, shuffle_id):
        with self._lock:
            return self.lineage.get(shuffle_id)

    # -- observability (spark_rapids_tpu/obs) ------------------------------
    @property
    def query_id(self) -> str:
        """Stable per-execution id (16 hex chars), minted lazily and
        shared with the tracer and diagnostic bundles."""
        with self._lock:
            qid = self.cache.get("query_id")
            if qid is None:
                import uuid
                qid = self.cache["query_id"] = uuid.uuid4().hex[:16]
            return qid

    @property
    def trace_id(self) -> str:
        t = self.tracer
        return t.trace_id if t is not None else self.query_id

    @property
    def tracer(self):
        """Per-query span tracer, or None when tracing is off.  The
        disabled check reads the RAW conf string so the default path
        never imports the obs package (ci/premerge.sh asserts
        spark_rapids_tpu.obs.trace stays out of sys.modules)."""
        with self._lock:
            if "tracer" in self.cache:
                return self.cache["tracer"]
        raw = self.conf.settings.get("spark.rapids.obs.trace.enabled")
        t = None
        if raw is not None and str(raw).lower() in ("true", "1", "yes"):
            from spark_rapids_tpu.obs.trace import TRACE_MAX_EVENTS, Tracer
            t = Tracer(query_id=self.query_id,
                       max_events=self.conf.get(TRACE_MAX_EVENTS))
        with self._lock:
            return self.cache.setdefault("tracer", t)

    @property
    def profiler(self):
        """Per-query cost-attribution profiler (obs/profile.py), or
        None when profiling is off.  Mirrors :attr:`tracer`: the
        disabled check reads the RAW conf string so the default path
        never imports obs.profile/obs.metering (ci/premerge.sh asserts
        sys.modules stays clean)."""
        with self._lock:
            if "profiler" in self.cache:
                return self.cache["profiler"]
        raw = self.conf.settings.get("spark.rapids.obs.profile.enabled")
        p = None
        if raw is not None and str(raw).lower() in ("true", "1", "yes"):
            from spark_rapids_tpu.obs.profile import QueryProfiler
            p = QueryProfiler(self.query_id, self.conf, ctx=self)
        with self._lock:
            return self.cache.setdefault("profiler", p)

    def trace_span(self, name: str, cat: str = "query", *,
                   parent_id=None, **args):
        """Context manager opening a span (yields it for annotate());
        a no-op nullcontext (yielding None) when tracing is off."""
        t = self.tracer
        if t is None:
            import contextlib
            return contextlib.nullcontext()
        return t.span(name, cat, parent_id=parent_id, **args)

    def trace_event(self, name: str, cat: str = "query", *,
                    parent_id=None, **args) -> None:
        t = self.tracer
        if t is not None:
            t.event(name, cat, parent_id=parent_id, **args)

    def close(self) -> None:
        """End-of-execution cleanup: close shuffle transports, then the
        BufferCatalog (spilled disk files, host arena) if created; last,
        export the query trace when a trace dir is configured."""
        from spark_rapids_tpu.shuffle import ShuffleTransport
        with self._lock:
            prof = self.cache.get("profiler")
        if prof is not None:
            # BEFORE the catalog pop (spill totals are captured off it)
            # and BEFORE trace export (counter tracks must land in it)
            try:
                prof.finalize(self)
            # enginelint: disable=RL001 (profile finalize is best-effort teardown; the query already finished)
            except Exception:
                pass
        with self._lock:
            tkeys = [k for k, v in self.cache.items()
                     if isinstance(v, ShuffleTransport)]
            transports = [self.cache.pop(k) for k in tkeys]
            catalog = self.cache.pop("catalog", None)
            tracer = self.cache.get("tracer")
        for t in transports:
            t.close()
        if catalog is not None:
            catalog.close()
        if tracer is not None:
            try:
                from spark_rapids_tpu.obs.trace import TRACE_DIR
                d = self.conf.get(TRACE_DIR)
                if d:
                    import os
                    os.makedirs(d, exist_ok=True)
                    tracer.export(os.path.join(
                        d, f"trace_{tracer.query_id}.json"))
            # enginelint: disable=RL001 (trace export is best-effort teardown; the query already finished)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def cached(self, key, factory):
        """Thread-safe once-per-execution materialization (exchange /
        broadcast / join-build stage cache).  Exactly one caller runs
        ``factory``; concurrent callers block until it completes."""
        with self._lock:
            if key in self.cache:
                return self.cache[key]
            ev = self._inflight.get(key)
            if ev is None:
                ev = self._inflight[key] = threading.Event()
                owner = True
            else:
                owner = False
        if owner:
            try:
                val = factory()
                with self._lock:
                    self.cache[key] = val
            finally:
                with self._lock:
                    self._inflight.pop(key, None)
                ev.set()
            return val
        ev.wait()
        with self._lock:
            if key in self.cache:
                return self.cache[key]
        # the owner failed; when the query was cancelled or timed out the
        # owner's failure IS the cancellation — surface that, not a
        # secondary "another task" error
        self.check_cancel()
        raise RuntimeError(f"stage materialization failed for {key!r} "
                           "in another task")


# ---------------------------------------------------------------------------
# Plan node
# ---------------------------------------------------------------------------

class PlanNode:
    """Base physical operator.

    Subclasses implement ``partition_iter`` producing batches for one
    partition on the active backend. ``output_schema`` is the operator's
    output schema; ``children`` its inputs.
    """

    def __init__(self, children: Sequence["PlanNode"]):
        self.children = tuple(children)

    def __init_subclass__(cls, **kw):
        """Auto-instrument every operator's partition_iter with the
        standard metric set (totalTime / numOutputBatches /
        numOutputRows + an xprof TraceAnnotation range) — the reference
        wires GpuMetricNames into every GpuExec (GpuExec.scala:27-56);
        here the base class does it so operators cannot forget.
        totalTime is inclusive of children, as in the reference.
        numOutputRows: on the host backend always; on the device backend
        only when the batch already carries a host-side count
        (ColumnBatch.known_rows — set by the pack builder, shuffle
        writers and OOM splitters) — reading num_rows off a device batch
        would force a D2H sync per batch, so unknown counts stay
        unrecorded rather than paid for.  When a tracer is active, one
        summary span per (operator, partition) is recorded on
        exhaustion."""
        super().__init_subclass__(**kw)
        impl = cls.__dict__.get("partition_iter")
        if impl is None:
            return

        def timed_partition_iter(self, ctx, pid, _impl=impl):
            if not ctx.metrics_enabled:
                yield from _impl(self, ctx, pid)
                return
            import jax.profiler as _prof
            m = ctx.metrics_for(self)
            label = type(self).__name__
            tracer = ctx.tracer
            prof = ctx.profiler
            it = _impl(self, ctx, pid)
            first_t0 = None
            batches = 0
            rows = 0
            active = 0.0
            # enginelint: disable=RL004 (driven by next(it); terminates with the child iterator and propagates its exceptions)
            while True:
                t0 = time.perf_counter()
                if first_t0 is None:
                    first_t0 = t0
                try:
                    with _prof.TraceAnnotation(label):
                        batch = next(it)
                except StopIteration:
                    break
                dt = time.perf_counter() - t0
                m.add("totalTime", dt)
                m.add("numOutputBatches", 1)
                active += dt
                batches += 1
                if not ctx.is_device:
                    m.add("numOutputRows", batch.num_rows)
                    rows += batch.num_rows
                else:
                    kr = getattr(batch, "known_rows", None)
                    if kr is not None:
                        m.add("numOutputRows", kr)
                        rows += kr
                yield batch
            if first_t0 is not None:
                if tracer is not None:
                    # dur is wall clock first-pull -> exhaustion
                    # (includes consumer suspension; the active time is
                    # in totalTime)
                    tracer.complete(label, "operator", first_t0,
                                    time.perf_counter(), node=label,
                                    partition=pid, batches=batches,
                                    rows=rows)
                if prof is not None:
                    # one bounded record per (operator, partition) —
                    # never per-batch work (the <3% overhead budget)
                    prof.record_op(self, label, active,
                                   time.perf_counter() - first_t0,
                                   batches, rows, pid)

        timed_partition_iter.__wrapped__ = impl
        cls.partition_iter = timed_partition_iter

    # -- contract ----------------------------------------------------------
    @property
    def output_schema(self) -> T.Schema:
        raise NotImplementedError

    def num_partitions(self, ctx: ExecCtx) -> int:
        if self.children:
            return self.children[0].num_partitions(ctx)
        return 1

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        raise NotImplementedError

    def partition_iter_slice(self, ctx: ExecCtx, pid: int, lo: int,
                             hi: int | None) -> Iterator:
        """Batches [lo, hi) of one partition.  Default: enumerate-and-skip
        over partition_iter; ShuffleExchangeExec overrides with a sliced
        transport fetch that skips materializing the rest.  Keeps the
        adaptive reader safe over ANY child (e.g. a BackendSwitchExec
        inserted by transition overrides).  Uses the UNinstrumented
        implementation: repeated slice windows must not inflate this
        operator's output metrics with skipped batches (the consumer's
        own wrapper records what is actually emitted)."""
        fn = type(self).partition_iter
        fn = getattr(fn, "__wrapped__", fn)
        for i, b in enumerate(fn(self, ctx, pid)):
            if i < lo:
                continue
            if hi is not None and i >= hi:
                break
            yield b

    #: bound (fully-typed) expressions this operator evaluates — the
    #: planner's tagging pass checks device_supported on these, since
    #: dtype-dependent checks can't run on unresolved trees
    @property
    def bound_exprs(self) -> list:
        return []

    @property
    def output_ordering(self) -> list | None:
        """Column names such that, WITHIN each emitted batch, rows equal
        on any prefix of them are contiguous (a lexicographic sort by
        these columns guarantees it).  None = no guarantee.  Downstream
        sort-based group-bys use this to skip their re-sort when the
        child already clusters the grouping keys — the reference keeps
        the analogous sort-order metadata on SparkPlan.outputOrdering
        and GpuSortAggregate picks merge-aggregation off it
        (aggregate.scala:348-560)."""
        return None

    #: True when this operator JITs multiple input batches together
    #: (concat, merge, build-side materialization) — such programs need
    #: same-device inputs, so the planner aligns mesh-committed batches
    #: flowing into them.  Per-batch operators (project/filter/limit)
    #: override to False and pass placement through untouched.
    combines_batches: bool = True

    # -- batching contracts (reference GpuExec.scala:71-86) ----------------
    @property
    def children_coalesce_goal(self) -> list[CoalesceGoal | None]:
        return [None] * len(self.children)

    @property
    def output_batching(self) -> CoalesceGoal | None:
        return None

    # -- execution helpers -------------------------------------------------
    def execute(self, ctx: ExecCtx) -> Iterator:
        """All partitions' batches, in partition order.  On the device
        backend partitions run concurrently on a worker pool (reference:
        Spark's task scheduler running doExecuteColumnar RDD
        partitions).  Metrics/trace ranges are recorded per operator by
        the auto-instrumented partition_iter (see __init_subclass__).

        The FIRST execute() on a ctx is the query root: it opens the
        query span and is the failure-diagnostics chokepoint — a query
        that dies here emits a bounded diagnostic bundle when
        spark.rapids.obs.diagnostics.dir is set (obs/diag.py). Both
        checks read raw conf strings so the disabled path never imports
        the obs package."""
        with ctx._lock:
            root = not ctx.cache.get("query_root_claimed")
            if root:
                ctx.cache["query_root_claimed"] = True
        if not root:
            yield from drain_partitions(ctx, self)
            return
        try:
            with ctx.trace_span("query", "query",
                                root=type(self).__name__,
                                backend=ctx.backend):
                yield from drain_partitions(ctx, self)
        except GeneratorExit:
            raise
        except Exception as e:
            # a cancelled/deadline-exceeded query closes its trace with
            # the terminal lifecycle state so the timeline shows WHY the
            # query span ended early (and the diag bundle below carries
            # the same state for post-mortems)
            if getattr(e, "terminal", False):
                lc = ctx.cache.get("lifecycle")
                if lc is not None and lc.state in ("CANCELLED",
                                                   "DEADLINE_EXCEEDED"):
                    t = ctx.tracer
                    if t is not None:
                        t.set_query_state(lc.state)
                        t.event("query.lifecycle", "query",
                                state=lc.state)
            out_dir = ctx.conf.settings.get(
                "spark.rapids.obs.diagnostics.dir")
            emit = False
            if out_dir:
                with ctx._lock:
                    emit = not ctx.cache.get("diag_emitted")
                    ctx.cache["diag_emitted"] = True
            if emit:
                from spark_rapids_tpu.obs.diag import maybe_emit_bundle
                maybe_emit_bundle(ctx, self, e, str(out_dir))
            raise

    # -- plan introspection ------------------------------------------------
    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + self.node_desc() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def node_desc(self) -> str:
        return type(self).__name__


# ---------------------------------------------------------------------------
# Concurrent partition drain
# ---------------------------------------------------------------------------

def drain_partitions(ctx: ExecCtx, node: PlanNode) -> Iterator:
    """Yield every partition's batches in partition order.

    Device backend with >1 partitions: partitions are drained concurrently
    by a worker pool; each worker holds the DeviceSemaphore while pulling a
    batch (chip-occupancy bound, reference GpuSemaphore.acquireIfNecessary,
    GpuSemaphore.scala:74-126) and parks finished batches in the
    BufferCatalog as spillable buffers (priority READ_SHUFFLE) so completed
    partitions don't pin HBM while earlier partitions are still being
    consumed (reference RapidsCachingWriter storing map output spillable,
    RapidsShuffleInternalManager.scala:90-155).
    """
    for _pid, b in drain_partitions_indexed(ctx, node):
        yield b


def drain_partitions_indexed(ctx: ExecCtx, node: PlanNode) -> Iterator:
    """drain_partitions, but yielding ``(partition_id, batch)`` so the
    consumer knows which child partition produced each batch — the
    shuffle exchange records this as the map-output lineage
    (exec/recovery.py re-drains exactly the partitions whose outputs
    were lost).  Same worker pool, same spillable parking, same
    partition-ordered delivery."""
    n = node.num_partitions(ctx)
    lc = ctx.lifecycle
    workers = min(ctx.task_concurrency, n) if ctx.is_device else 1
    if workers <= 1 or n <= 1:
        for pid in range(n):
            with ctx.trace_span("partition", "partition",
                                node=type(node).__name__, partition=pid):
                for b in node.partition_iter(ctx, pid):
                    lc.check()
                    yield pid, b
        return

    import concurrent.futures as cf
    from spark_rapids_tpu.memory.catalog import (SpillableColumnarBatch,
                                                 SpillPriority)
    catalog = ctx.catalog
    tracer = ctx.tracer
    # worker threads have empty span stacks; parent their partition spans
    # onto whatever span is open on the draining thread (query/stage)
    drain_parent = tracer.current_span_id() if tracer is not None else None
    # early consumer exit (LIMIT satisfied, error, cancel): the finally
    # block raises this flag and in-flight workers stop at their NEXT
    # batch boundary instead of draining every partition to completion
    stop = threading.Event()

    def drain(pid: int):
        # chip occupancy is bounded inside ctx.dispatch, not here: holding
        # the semaphore across a next() that may itself drain partitions
        # (join build sides, nested exchanges) would deadlock
        out: list = []
        with ctx.trace_span("partition", "partition",
                            parent_id=drain_parent,
                            node=type(node).__name__, partition=pid):
            it = node.partition_iter(ctx, pid)
            try:
                while not stop.is_set():
                    lc.check()
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                    out.append(SpillableColumnarBatch(
                        b, catalog, SpillPriority.READ_SHUFFLE))
            except BaseException:
                # the batches already parked would otherwise sit in the
                # catalog until ctx.close(); the post-cancel invariant
                # is "parked spillable batches closed"
                for sb in out:
                    sb.close()
                raise
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()
        return out

    with cf.ThreadPoolExecutor(max_workers=workers,
                               thread_name_prefix="tpu-task") as pool:
        futures = [pool.submit(drain, pid) for pid in range(n)]
        try:
            for pid, fut in enumerate(futures):
                for sb in fut.result():
                    lc.check()
                    yield pid, sb.get()
                    sb.close()
        finally:
            # early consumer exit / error: stop in-flight workers at
            # their next batch boundary, then release every
            # still-registered buffer (close is idempotent; unconsumed
            # = leaked otherwise)
            stop.set()
            for fut in futures:
                if fut.cancel():
                    continue
                try:
                    for sb in fut.result():
                        sb.close()
                # enginelint: disable=RL001 (finally-block cleanup: raising would mask the in-flight exception; normal completion already consumed every future)
                except BaseException:
                    pass


# ---------------------------------------------------------------------------
# Collect surface
# ---------------------------------------------------------------------------

def _rows_from_host(b: HostBatch) -> list[tuple]:
    cols = [c.to_list() for c in b.columns]
    return list(zip(*cols)) if cols else [()] * b.num_rows


def collect_host(plan: PlanNode, conf: TpuConf | None = None,
                 ctx: ExecCtx | None = None) -> list[tuple]:
    """Run on the CPU oracle; rows as python tuples.  ``ctx`` lets the
    session pass a context pre-bound to its lifecycle handle (so
    cancel/deadline reach the run); the ctx is closed here either
    way."""
    with (ctx or ExecCtx(backend="host", conf=conf or TpuConf({}))) as ctx:
        out: list[tuple] = []
        for b in plan.execute(ctx):
            out.extend(_rows_from_host(b))
        return out


def collect_device(plan: PlanNode, conf: TpuConf | None = None,
                   ctx: ExecCtx | None = None) -> list[tuple]:
    """Run on the TPU path; rows as python tuples (D2H at the end only).
    With spark.rapids.tpu.profile.dir set, the whole execution records an
    xprof trace (reference: nsight timelines over NVTX ranges).  ``ctx``
    lets the session pass a context pre-bound to its lifecycle handle."""
    import contextlib
    with (ctx or ExecCtx(backend="device", conf=conf or TpuConf({}))) as ctx:
        profile_dir = ctx.conf.get(PROFILE_DIR)
        prof = contextlib.nullcontext()
        if profile_dir:
            import jax.profiler as _prof
            prof = _prof.trace(profile_dir)
        with prof:
            out: list[tuple] = []
            for b in plan.execute(ctx):
                hb = device_to_host(b)
                out.extend(_rows_from_host(hb))
            return out


def collect(plan: PlanNode, backend: str = "device",
            conf: TpuConf | None = None) -> list[tuple]:
    if backend == "host":
        return collect_host(plan, conf)
    return collect_device(plan, conf)


def device_to_host(b: ColumnBatch) -> HostBatch:
    """D2H: ColumnBatch -> HostBatch (reference GpuColumnarToRowExec /
    GpuBringBackToHost transition)."""
    import jax
    import numpy as np
    from spark_rapids_tpu.host.batch import HostColumn
    # ONE device_get for num_rows + all column leaves: separate fetches
    # pay a full host round trip each on a tunneled backend
    n, host = jax.device_get(
        (b.num_rows, [(c.data, c.validity, c.lengths) for c in b.columns]))
    n = int(n)
    cols = []
    for f, (data, validity, lengths) in zip(b.schema, host):
        v = np.asarray(validity[:n], dtype=np.bool_)
        if isinstance(f.data_type, T.StringType):
            bm = np.asarray(data[:n])
            ln = np.asarray(lengths[:n])
            py = np.empty(n, dtype=object)
            for i in range(n):
                py[i] = bytes(bm[i, :ln[i]]).decode("utf-8", "replace") \
                    if v[i] else None
            cols.append(HostColumn(py, v, f.data_type))
        elif isinstance(f.data_type, T.ArrayType):
            m = np.asarray(data[:n])
            ln = np.asarray(lengths[:n])
            py = np.empty(n, dtype=object)
            for i in range(n):
                py[i] = m[i, :ln[i]].tolist() if v[i] else None
            cols.append(HostColumn(py, v, f.data_type))
        else:
            cols.append(HostColumn(np.asarray(data[:n]), v, f.data_type))
    return HostBatch(cols, b.schema)


def host_to_device(b: HostBatch, capacity: int | None = None) -> ColumnBatch:
    """H2D: HostBatch -> ColumnBatch (reference HostColumnarToGpu).
    Columns are staged into per-dtype packed buffers and moved with one
    transfer per dtype (columnar/batch._PackBuilder)."""
    import numpy as np
    from spark_rapids_tpu.columnar.batch import _PackBuilder, round_capacity
    from spark_rapids_tpu.columnar.column import round_string_width
    from spark_rapids_tpu.columnar.batch import _codec_auto
    n = b.num_rows
    cap = capacity or round_capacity(max(n, 1))
    pack = _PackBuilder(cap, _codec_auto(cap, None))
    for f, col in zip(b.schema, b.columns):
        if isinstance(f.data_type, T.StringType):
            enc = [(x.encode("utf-8") if x is not None else b"")
                   for x in col.data]
            maxw = max((len(e) for e in enc), default=1)
            w = round_string_width(max(maxw, 1))
            bm = np.zeros((n, w), dtype=np.uint8)
            lens = np.zeros(n, dtype=np.int32)
            for i, e in enumerate(enc):
                bm[i, :len(e)] = np.frombuffer(e, dtype=np.uint8)
                lens[i] = len(e)
            pack.add_var(bm, lens, col.validity, w)
        elif isinstance(f.data_type, T.ArrayType):
            vals = [(v if v is not None else []) for v in col.data]
            maxw = max((len(v) for v in vals), default=1)
            w = round_string_width(max(maxw, 1))
            m = np.zeros((n, w), dtype=f.data_type.np_dtype)
            lens = np.zeros(n, dtype=np.int32)
            for i, v in enumerate(vals):
                m[i, :len(v)] = v
                lens[i] = len(v)
            pack.add_var(m, lens, col.validity, w)
        else:
            pack.add_fixed(np.asarray(col.data), col.validity)
    return pack.build(n, b.schema)

"""Exchange execs: shuffle (repartition) and broadcast.

Reference: GpuShuffleExchangeExecBase + ShuffledBatchRDD
(GpuShuffleExchangeExec.scala:70, SURVEY.md §2.4) and
GpuBroadcastExchangeExec (host-serialized torrent broadcast :47-368).

Execution model: an exchange is a stage barrier.  On first pull it
materializes every child partition, computes partition ids per batch on
the executing backend, splits, and caches the per-output-partition batch
lists in the ExecCtx (the analog of map-output in the
ShuffleBufferCatalog; reference RapidsCachingWriter stores partition
tables in the spillable device store).  Subsequent partition pulls serve
from the cache.  On the device backend the id+split computation is one
jitted program per batch — the local, single-process analog of the mesh
all-to-all path (exec/mesh_exec.py, which the planner selects instead of
this exec when ``spark.rapids.tpu.mesh.deviceCount`` > 1 and the shape
matches; see plan/overrides.py lower()).
"""
from __future__ import annotations

from functools import partial
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.exec.compile_cache import guarded_jit
from spark_rapids_tpu.exec.partitioning import Partitioning
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.ops import host_kernels as hk
from spark_rapids_tpu.ops import kernels as dk

__all__ = ["ShuffleExchangeExec", "BroadcastExchangeExec",
           "AdaptiveShuffleReaderExec"]

from spark_rapids_tpu.conf import ConfEntry, register, _bool

ADAPTIVE_ENABLED = register(ConfEntry(
    "spark.sql.adaptive.enabled", True,
    "Adaptive execution: coalesce small shuffle output partitions using "
    "the map-output sizes (reference GpuCustomShuffleReaderExec + "
    "GpuTransitionOverrides.optimizeAdaptiveTransitions :51-94).",
    conv=_bool))
ADVISORY_PARTITION_BYTES = register(ConfEntry(
    "spark.sql.adaptive.advisoryPartitionSizeInBytes", 64 << 20,
    "Target post-shuffle partition size for adaptive coalescing.",
    conv=int))
SKEWED_PARTITION_THRESHOLD = register(ConfEntry(
    "spark.sql.adaptive.skewedPartitionThresholdInBytes", 256 << 20,
    "A shuffle output partition larger than this is skewed: the adaptive "
    "reader splits it into multiple reader groups at map-batch "
    "granularity targeting advisoryPartitionSizeInBytes each (the skew "
    "half of Spark 3.0 AQE; small partitions are coalesced, large ones "
    "split).", conv=int))


@guarded_jit(static_argnames=("num_parts",))
def _jit_group_by_part(batch: ColumnBatch, ids: jax.Array, num_parts: int):
    """Sort rows by partition id; return (sorted_batch, counts[num_parts]).

    The analog of Table.contiguousSplit (GpuPartitioning.scala:45-52):
    one stable sort groups each partition's rows contiguously; the small
    counts vector is the only thing synced to host, and each partition is
    then sliced into a right-sized capacity (no num_parts x capacity
    buffer blowup).
    """
    cap = batch.capacity
    ids = jnp.where(batch.row_mask(), ids, num_parts)  # padding last
    order = jnp.argsort(ids, stable=True)
    counts = jnp.sum(ids[None, :] == jnp.arange(num_parts,
                                                dtype=jnp.int32)[:, None],
                     axis=1, dtype=jnp.int32)
    starts = jnp.cumsum(counts) - counts  # exclusive prefix, on device
    cols = dk.gather_columns(batch.columns, order, batch.num_rows)
    return ColumnBatch(cols, batch.num_rows, batch.schema), counts, starts


@guarded_jit(static_argnames=("out_cap",))
def _jit_slice_part(sorted_batch: ColumnBatch, starts, counts, p,
                    out_cap: int):
    """Copy partition ``p``'s rows [starts[p], starts[p]+counts[p]) into
    a fresh out_cap batch.  ``starts``/``counts`` stay device-resident
    and ``p`` is a cached device scalar: the per-partition offsets never
    round-trip to host (only the counts vector does, once per batch,
    for the static capacity choice)."""
    start = starts[p]
    idx = jnp.clip(start + jnp.arange(out_cap, dtype=jnp.int32), 0,
                   sorted_batch.capacity - 1)
    return dk.take(sorted_batch, idx, counts[p])


def _fp_extra(n: PlanNode) -> str | None:
    """Per-class fingerprint payload for operator parameters that
    node_desc/bound_exprs do not surface.  Returning None marks the
    class UNKNOWN: the node then contributes its object identity, so
    structurally-identical-looking subtrees through it never dedup —
    a missed optimization, never a wrong reuse."""
    from spark_rapids_tpu.exec.aggregate import HashAggregateExec
    from spark_rapids_tpu.exec.basic import (FilterExec, GlobalLimitExec,
                                             LocalLimitExec, ProjectExec,
                                             UnionExec)
    from spark_rapids_tpu.exec.expand import ExpandExec
    from spark_rapids_tpu.exec.generate import GenerateExec
    from spark_rapids_tpu.exec.joins import CrossJoinExec, JoinExec
    from spark_rapids_tpu.exec.sortexec import (CoalesceBatchesExec,
                                                SortExec)
    from spark_rapids_tpu.exec.transitions import BackendSwitchExec

    if isinstance(n, ShuffleExchangeExec):
        p = n.partitioning
        keys = getattr(p, "_keys", None) or getattr(p, "_orders_raw", ())
        return f"{type(p).__name__}:{p.num_partitions}:{keys!r}"
    if isinstance(n, AdaptiveShuffleReaderExec):
        return f"{n.allow_coalesce}:{n.allow_skew_split}"
    if isinstance(n, (LocalLimitExec, GlobalLimitExec)):
        return str(n._limit)
    if isinstance(n, CoalesceBatchesExec):
        return repr(n._goal)
    if isinstance(n, HashAggregateExec):
        # desc/bound_exprs/schema do NOT identify the aggregate: min(v) and
        # max(v) finals are both plain BoundReferences and partial buffer
        # schemas can coincide ('_buf_0'), so two different aggregations
        # over one shared scan would otherwise fingerprint identically and
        # ReuseExchange would serve one consumer the other's data.
        return (f"{n.mode}:{n._update_specs!r}:{n._merge_specs!r}:"
                f"{getattr(n, '_agg_offsets', None)!r}")
    if isinstance(n, BroadcastExchangeExec):
        return ""
    from spark_rapids_tpu.exec.stage_boundary import StageBoundaryExec
    if isinstance(n, (ProjectExec, FilterExec, UnionExec, JoinExec,
                      CrossJoinExec, SortExec,
                      ExpandExec, GenerateExec, BackendSwitchExec,
                      StageBoundaryExec)):
        # desc + bound_exprs + schema already carry their parameters
        return ""
    return None


def plan_fingerprint(node: PlanNode) -> str:
    """Structural identity of a physical subtree: node descriptions,
    bound expressions, output schemas, per-class parameter payloads
    (_fp_extra), and LEAF OBJECT identity (two subtrees match only when
    they read the very same source execs).  Operators outside the known
    set contribute object identity too, so unknown semantics can never
    collide.  Identical fingerprints mean identical map output — the
    basis for exchange reuse (Spark's ReuseExchange rule, which the
    reference inherits: a DataFrame referenced twice otherwise executes
    its whole shuffle pipeline twice — q65's agg-over-agg self-join ran
    the store_sales scan+join+partial-agg twice)."""
    import hashlib
    h = hashlib.sha1()

    def feed(n: PlanNode):
        h.update(type(n).__name__.encode())
        h.update(n.node_desc().encode())
        h.update(repr(n.output_schema).encode())
        for e in getattr(n, "bound_exprs", []):
            h.update(repr(e).encode())
        extra = _fp_extra(n)
        if extra is None or not n.children:
            h.update(str(id(n)).encode())
        else:
            h.update(extra.encode())
        for c in n.children:
            feed(c)

    feed(node)
    return h.hexdigest()


class ShuffleExchangeExec(PlanNode):
    """Repartition child output by a Partitioning strategy."""

    def __init__(self, partitioning: Partitioning, child: PlanNode,
                 shuffle_id: "int | str | None" = None):
        super().__init__([child])
        self.partitioning = partitioning
        partitioning.bind(child.output_schema)
        # explicit id: cross-process serving (two processes cannot
        # agree on a local identity); otherwise resolved lazily to the
        # subtree fingerprint at first execution (children are still
        # being rewritten by coalesce/transition insertion now)
        self._shuffle_id = shuffle_id

    @property
    def shuffle_id(self):
        if self._shuffle_id is None:
            self._shuffle_id = plan_fingerprint(self)
        return self._shuffle_id

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self.partitioning.num_partitions

    def _shuffled(self, ctx: ExecCtx):
        # keyed by the structural shuffle_id, NOT object identity:
        # duplicate exchange subtrees (a DataFrame used twice in one
        # query) materialize the map side ONCE per execution and both
        # consumers fetch from it (ReuseExchange)
        return ctx.cached(("shuffle", self.shuffle_id, ctx.backend),
                          lambda: self._do_shuffle(ctx))

    def _do_shuffle(self, ctx: ExecCtx):
        """Materialize the map side through the shuffle transport SPI
        (reference RapidsCachingWriter.write storing spillable partition
        tables, RapidsShuffleInternalManager.scala:90-155; transport
        loaded by reflection, RapidsShuffleTransport.scala:638-658).
        Host backend keeps plain batch lists (the oracle path).

        The device path also registers a ShuffleLineage handle in the
        ExecCtx: which child partition produced each map batch, and
        whether the tiny-input coalesce rewrite applied — everything
        needed to re-execute exactly the lost map partitions after a
        terminal fetch failure (exec/recovery.py; reference:
        MapOutputTracker lineage driving DAGScheduler stage
        resubmission)."""
        from spark_rapids_tpu.exec.core import drain_partitions
        child = self.children[0]
        if ctx.is_device:
            with ctx.trace_span("stage.map", "stage",
                                shuffle=str(self.shuffle_id)[:12],
                                node=self.node_desc()):
                return self._do_shuffle_device(ctx, child)
        batches = list(drain_partitions(ctx, child))
        self.partitioning.prepare(batches, False)
        n = self.partitioning.num_partitions
        out: list[list] = [[] for _ in range(n)]
        for bi, b in enumerate(batches):
            if b.num_rows == 0:
                continue
            ids = self.partitioning.host_ids(b, bi)
            for p in range(n):
                piece = hk.host_filter(b, ids == p)
                if piece.num_rows:
                    out[p].append(piece)
        return out

    def _do_shuffle_device(self, ctx: ExecCtx, child: PlanNode):
        from spark_rapids_tpu.exec.core import drain_partitions_indexed
        from spark_rapids_tpu.exec.recovery import ShuffleLineage
        from spark_rapids_tpu.shuffle import make_transport
        cluster = ctx.cache.get("cluster")
        if cluster is not None and getattr(self, "_cluster_ok", False):
            # cluster runtime: shard the map side over the worker pool
            # (cluster/exec.py); None means it could not run there
            # (unpicklable fragment, dead pool) and the classic
            # in-process path below stays the fallback
            from spark_rapids_tpu.cluster.exec import cluster_do_shuffle
            out = cluster_do_shuffle(cluster, self, ctx, child)
            if out is not None:
                return out
        indexed = list(drain_partitions_indexed(ctx, child))
        map_src = {bi: cpid for bi, (cpid, _) in enumerate(indexed)}
        batches = [b for _, b in indexed]
        self.partitioning.prepare(batches, True)
        n = self.partitioning.num_partitions
        transport = make_transport(ctx.conf, ctx)
        # Map-side tiny-input coalescing: when the whole map side is
        # below the advisory partition size, splitting it n ways
        # only buys n slice programs + n downstream per-partition
        # chains of dispatch latency.  Putting EVERYTHING in
        # partition 0 is correct for every partitioning (all rows of
        # any key land in one partition) — the map-side counterpart
        # of the reader's AQE small-partition coalescing
        # (GpuCustomShuffleReaderExec; Spark's AQE does this on the
        # read side only because its map side is fixed at plan time).
        # It is an ADAPTIVE rewrite, so it obeys the same gates as
        # the read side: off when spark.sql.adaptive.enabled is
        # false, and off when an allow_coalesce=False reader
        # consumes this exchange — explicit repartition(n) promises
        # n non-degenerate partitions (Spark's REPARTITION_BY_NUM
        # contract).
        coalesce_ok = (ADAPTIVE_ENABLED.get(ctx.conf.settings)
                       and not getattr(self, "_no_map_coalesce",
                                       False))
        coalesced = False
        if coalesce_ok and n > 1 and len(batches) >= 1:
            total_bytes = sum(b.device_size_bytes() for b in batches)
            coalesced = total_bytes <= ADVISORY_PARTITION_BYTES.get(
                ctx.conf.settings)
        for bi, b in enumerate(batches):
            self._write_map_batch(ctx, transport, bi, b, coalesced, n)
        ctx.register_lineage(self.shuffle_id, ShuffleLineage(
            exchange=self, coalesced=coalesced, num_parts=n,
            map_src=map_src, conf_fp=getattr(self, "_conf_fp", None)))
        return transport

    def _write_map_batch(self, ctx: ExecCtx, transport, bi: int, b,
                         coalesced: bool, n: int,
                         epoch: int | None = None) -> None:
        """Partition one map batch and hand its pieces to the transport.
        Shared by the initial materialization (epoch=None -> current) and
        recovery recomputation, which tags writes with the post-
        invalidation epoch so a straggler from the dead attempt can
        never displace them."""
        from spark_rapids_tpu.columnar.batch import round_capacity
        if coalesced:
            transport.write_partition(self.shuffle_id, bi, 0, b,
                                      epoch=epoch)
            return
        ids = self.partitioning.device_ids(b, bi)
        sb, counts_d, starts_d = ctx.dispatch(_jit_group_by_part, b, ids, n)
        # enginelint: disable=RL003 (per-partition counts gate host-side slicing; one sync per batch by design)
        counts = np.asarray(jax.device_get(counts_d))
        for p in range(n):
            if counts[p] == 0:
                continue
            piece = ctx.dispatch(
                _jit_slice_part, sb, starts_d, counts_d,
                dk.device_scalar(p), round_capacity(int(counts[p])))
            # counts already crossed to host for the skip check above:
            # record the exact row count on the piece so downstream
            # numOutputRows never needs a fresh D2H sync (jit dispatch
            # strips known_rows at the trace boundary)
            piece.known_rows = int(counts[p])
            ctx.trace_event("shuffle.map_write", "shuffle", map=bi,
                            part=p, rows=int(counts[p]),
                            epoch=epoch if epoch is not None else 0)
            transport.write_partition(self.shuffle_id, bi, p, piece,
                                      epoch=epoch)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        yield from self.partition_iter_slice(ctx, pid, 0, None)

    def partition_iter_slice(self, ctx: ExecCtx, pid: int, lo: int,
                             hi: int | None) -> Iterator:
        """One reduce partition's batches, restricted to map-batch slice
        [lo, hi) — each adaptive skew-split group materializes only its
        own range.  Device pulls run inside the stage-recovery loop:
        a terminal MapOutputLostError invalidates and recomputes exactly
        the lost map outputs, then resumes the pull where it stopped."""
        shuffled = self._shuffled(ctx)
        if ctx.is_device:
            from spark_rapids_tpu.exec.recovery import recovering_fetch
            with ctx.trace_span("shuffle.fetch", "shuffle",
                                shuffle=str(self.shuffle_id)[:12],
                                partition=pid, lo=lo,
                                hi=hi if hi is not None else -1):
                yield from recovering_fetch(ctx, self, shuffled, pid,
                                            lo, hi)
        else:
            yield from shuffled[pid][lo:hi]

    def node_desc(self) -> str:
        return (f"ShuffleExchangeExec[{type(self.partitioning).__name__}"
                f"({self.partitioning.num_partitions})]")


class AdaptiveShuffleReaderExec(PlanNode):
    """Adaptive shuffle reader: re-plans the reduce side from ACTUAL
    map-output sizes (the AQE analog; reference
    GpuCustomShuffleReaderExec.scala:131 reading CoalescedPartitionSpecs,
    plus Spark 3.0's skew-reader split).

    * adjacent partitions smaller than advisoryPartitionSizeInBytes are
      coalesced into one reader group;
    * a partition larger than skewedPartitionThresholdInBytes is SPLIT
      into several groups at map-batch granularity, each targeting the
      advisory size, so one hot key range cannot serialize the stage.

    The shuffle is its query-stage barrier: grouping is decided AFTER the
    map side materializes, per execution.  Each group is a list of
    ``(child_pid, lo, hi)`` map-batch slices (hi=None -> to the end).

    ``allow_skew_split`` is only set by the planner where the consumer
    has per-row semantics (join sides, writes): splitting one hash
    partition into several reader groups between a partial and a final
    aggregation would emit duplicate keys, so that path keeps
    coalesce-only (Spark scopes its skew reader to joins the same way,
    OptimizeSkewedJoin).  ``allow_coalesce=False`` makes the reader
    split-only: user-requested partition counts are never REDUCED
    (Spark's REPARTITION_BY_NUM contract), but a skewed partition may
    still fan out.
    """

    def __init__(self, child: ShuffleExchangeExec,
                 allow_skew_split: bool = False,
                 allow_coalesce: bool = True):
        super().__init__([child])
        assert isinstance(child, ShuffleExchangeExec)
        self.allow_skew_split = allow_skew_split
        self.allow_coalesce = allow_coalesce
        if not allow_coalesce:
            # the exchange materializes before its consumers run, so it
            # cannot discover this reader then — flag it at plan time:
            # the map side must keep all n partitions non-degenerate
            child._no_map_coalesce = True

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    def _groups(self, ctx: ExecCtx) -> list[list[tuple]]:
        return ctx.cached(("aqe_groups", id(self), ctx.backend),
                          lambda: self._compute_groups(ctx))

    def _compute_groups(self, ctx: ExecCtx) -> list[list[tuple]]:
        child = self.children[0]
        n = child.num_partitions(ctx)
        identity = [[(pid, 0, None)] for pid in range(n)]
        # transition insertion may have wrapped the shuffle (backend
        # switch); without direct access to map-output stats, do NOT
        # coalesce — unknown sizes must not serialize the reduce side
        if not ctx.is_device or not isinstance(child, ShuffleExchangeExec):
            return identity
        shuffled = child._shuffled(ctx)  # stage barrier: materialize maps
        target = ctx.conf.get(ADVISORY_PARTITION_BYTES)
        skew_at = ctx.conf.get(SKEWED_PARTITION_THRESHOLD)
        sizes = shuffled.partition_sizes(child.shuffle_id) \
            if hasattr(shuffled, "partition_sizes") else None
        if not sizes:
            return identity
        groups: list[list[tuple]] = []
        cur: list[tuple] = []
        cur_bytes = 0
        n_splits = 0

        def flush():
            nonlocal cur, cur_bytes
            if cur:
                groups.append(cur)
            cur, cur_bytes = [], 0

        for pid in range(n):
            sz = sizes.get(pid, 0)
            per_batch = shuffled.batch_sizes(child.shuffle_id, pid) \
                if (self.allow_skew_split and sz > skew_at
                    and hasattr(shuffled, "batch_sizes")) else None
            if per_batch and len(per_batch) > 1:
                flush()
                before = len(groups)
                lo, acc = 0, 0
                for i, bsz in enumerate(per_batch):
                    if acc > 0 and acc + bsz > target:
                        groups.append([(pid, lo, i)])
                        lo, acc = i, 0
                    acc += bsz
                groups.append([(pid, lo, None)])
                n_splits += len(groups) - before - 1
                continue
            if not self.allow_coalesce:
                groups.append([(pid, 0, None)])
                continue
            if cur and cur_bytes + sz > target:
                flush()
            cur.append((pid, 0, None))
            cur_bytes += sz
        flush()
        if not groups:
            return identity
        n_coalesced = sum(len(g) - 1 for g in groups)
        if n_coalesced or n_splits:
            from spark_rapids_tpu.obs.registry import get_registry
            reg = get_registry()
            if n_coalesced:
                reg.inc("aqe_partitions_coalesced", n_coalesced)
            if n_splits:
                reg.inc("aqe_skew_splits", n_splits)
            ctx.trace_event("aqe.replan", "aqe", node=self.node_desc(),
                            partitions=n, groups=len(groups),
                            coalesced=n_coalesced, skew_splits=n_splits)
        return groups

    def num_partitions(self, ctx: ExecCtx) -> int:
        return len(self._groups(ctx))

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        for child_pid, lo, hi in self._groups(ctx)[pid]:
            yield from self.children[0].partition_iter_slice(
                ctx, child_pid, lo, hi)

    def node_desc(self) -> str:
        return "AdaptiveShuffleReaderExec" + (
            "[skew-split]" if self.allow_skew_split else "")


class BroadcastExchangeExec(PlanNode):
    """Materialize the child once; every consumer partition sees the
    full (concatenated) output (reference GpuBroadcastExchangeExec:
    collect to host, torrent-broadcast, lazy device rebuild — here the
    single-process analog caches one batch per backend)."""

    def __init__(self, child: PlanNode):
        super().__init__([child])

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return 1

    def materialize(self, ctx: ExecCtx):
        return ctx.cached(("broadcast", id(self), ctx.backend),
                          lambda: self._materialize(ctx))

    def _materialize(self, ctx: ExecCtx):
        from spark_rapids_tpu.exec.core import drain_partitions
        child = self.children[0]
        batches = list(drain_partitions(ctx, child))
        if ctx.is_device:
            if not batches:
                from spark_rapids_tpu.exec.core import host_to_device
                b = host_to_device(HostBatch.empty(child.output_schema))
            else:
                b = dk.concat_batches(batches) if len(batches) > 1 \
                    else batches[0]
        else:
            b = hk.host_concat(batches) if batches \
                else HostBatch.empty(child.output_schema)
        return b

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        yield self.materialize(ctx)

    def node_desc(self) -> str:
        return "BroadcastExchangeExec"


class RemoteShuffleReaderExec(PlanNode):
    """Reduce-side scan of a REMOTE peer's map output over the TCP
    transport: the cross-process half of the accelerated shuffle
    (reference read path: RapidsCachingReader -> RapidsShuffleIterator
    -> transport client fetch, RapidsShuffleInternalManager.scala:307-345
    + RapidsShuffleClient.scala).  The map side runs in another process
    serving its partitions through TcpShuffleServer; this exec streams
    them into the local pipeline, so a full plan executes with map tasks
    in one process and reduce tasks in another.
    """

    def __init__(self, address, shuffle_id: "int | str", num_parts: int,
                 schema: T.Schema):
        super().__init__([])
        self.address = tuple(address)
        self.shuffle_id = shuffle_id
        self._num_parts = num_parts
        self._schema = schema

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self._num_parts

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        # the retrying fetch (shuffle/retry.py): transient peer failures
        # reconnect and resume mid-partition instead of killing the
        # whole reduce-side pull (reference: RapidsShuffleIterator
        # surfacing fetch failures to stage retry).  One fault registry
        # per execution so nth/times counters span all pulls.
        from spark_rapids_tpu.faults import FaultRegistry
        from spark_rapids_tpu.shuffle.retry import fetch_remote_with_retry
        faults = ctx.cached(("fault_registry",),
                            lambda: FaultRegistry.from_conf(ctx.conf))
        # propagate the originating query's trace across the wire so the
        # serving peer's "shuffle.serve" event parents onto THIS span —
        # one trace covers the fetch, its retries, and any recovery
        tracer = ctx.tracer
        trace = tracer.trace_header() if tracer is not None else None
        yield from fetch_remote_with_retry(self.address, self.shuffle_id,
                                           pid, device=ctx.is_device,
                                           conf=ctx.conf, faults=faults,
                                           tracer=tracer, trace=trace,
                                           lifecycle=ctx.lifecycle)

    def node_desc(self) -> str:
        return (f"RemoteShuffleReaderExec[{self.address[0]}:"
                f"{self.address[1]}, shuffle={self.shuffle_id}, "
                f"parts={self._num_parts}]")

"""StageBoundaryExec: the query-stage barrier that triggers adaptive
re-planning.

The planner (plan/overrides.py ``_insert_stage_boundaries``) wraps each
join whose build side is an AQE-inserted shuffle in one of these.  At
execution time, the FIRST pull on the boundary forces the build-side
map stage to materialize, hands its actual statistics to
``plan/adaptive.py``'s re-optimizer, and swaps in whatever node the
re-optimizer returns — the original join, or a broadcast-strategy
rewrite with the probe shuffle dropped and dynamic filters installed.
Subsequent pulls (and EXPLAIN ANALYZE's post-execution tree walk) see
the re-planned child: the rendered plan shows what actually ran.

The decision is cached per (execution, backend): every output partition
of one query execution sees one consistent plan, while a fresh
execution re-decides from fresh statistics.  The host (oracle) backend
resolves to the static child, so the differential oracle always checks
the adaptive plan's rows against the un-replanned semantics.
"""
from __future__ import annotations

from typing import Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode

__all__ = ["StageBoundaryExec"]


class StageBoundaryExec(PlanNode):
    """Pass-through barrier above a re-plannable join (see module doc).

    ``df_targets`` are the dynamic-filter candidates computed at
    plan-prepare time (``plan.adaptive.dynamic_filter_targets``) —
    resolved BEFORE stage fusion hides the probe-side scan inside a
    fused region, and carried here for the runtime re-optimizer.
    """

    combines_batches = False

    def __init__(self, child: PlanNode, df_targets=()):
        super().__init__([child])
        self.df_targets = tuple(df_targets)

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    def _resolved(self, ctx: ExecCtx) -> PlanNode:
        if not ctx.is_device:
            return self.children[0]
        return ctx.cached(("aqe_stage", id(self), ctx.backend),
                          lambda: self._replan(ctx))

    def _replan(self, ctx: ExecCtx) -> PlanNode:
        from spark_rapids_tpu.plan.adaptive import replan_stage
        new = replan_stage(ctx, self)
        if new is not self.children[0]:
            # reparent so explain_analyze / tree renders walk the plan
            # that actually executed
            self.children = (new,)
        # runtime half of the plan invariant verifier: the re-planned
        # subtree must still satisfy the boundary/schema contracts
        # (plan/verify.py; the prepare-time passes ran their own hooks)
        from spark_rapids_tpu.plan.verify import PLAN_VERIFY, verify_plan
        if ctx.conf is not None and ctx.conf.get(PLAN_VERIFY):
            verify_plan(self, ctx.conf, "aqe_replan")
        return new

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self._resolved(ctx).num_partitions(ctx)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        yield from self._resolved(ctx).partition_iter(ctx, pid)

    def node_desc(self) -> str:
        return "StageBoundaryExec" + (
            f"[df={len(self.df_targets)}]" if self.df_targets else "")

"""Mesh-distributed execs: shuffle + aggregation under ``shard_map``.

This is the engine-level wiring of the ICI all-to-all data plane
(:mod:`spark_rapids_tpu.parallel.mesh_shuffle`): when the session conf
sets ``spark.rapids.tpu.mesh.deviceCount`` > 1, the planner lowers a
grouped aggregation to :class:`MeshAggregateExec` (one compiled
partial -> all-to-all -> final-merge program per device) and a hash
repartition to :class:`MeshExchangeExec`, instead of the in-process
stage-barrier loop in :mod:`spark_rapids_tpu.exec.exchange`.

Reference mapping (SURVEY.md §2.6, §3.4): the reference reaches its
accelerated shuffle through RapidsShuffleInternalManager.getWriter/
getReaderInternal (RapidsShuffleInternalManager.scala:285-345) with a
UCX peer-to-peer data plane; the TPU-native plane is one XLA
``all_to_all`` collective inside ``shard_map``, fused with the partial
and final aggregations so the compiler overlaps the collective with
compute.  Expression layout (pre-projection, update/merge specs, final
projection) is shared with :class:`HashAggregateExec` — the same
aggregation-buffer contract the reference's partial/final modes use
(aggregate.scala:77-169).
"""
from __future__ import annotations

import time
from functools import partial
from typing import Iterator, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.exec.aggregate import HashAggregateExec, _relabel_d
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.exec.joins import JoinExec
from spark_rapids_tpu.expr.core import Expression, bind, eval_device
from spark_rapids_tpu.ops import kernels as dk
from spark_rapids_tpu.ops.segmented import sorted_group_by
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.parallel.mesh import (local_view, make_mesh, restack,
                                            shard_batches, shard_map,
                                            split_shards)
from spark_rapids_tpu.parallel.mesh_shuffle import (canonicalize,
                                                    exchange_local,
                                                    exchange_local_checked,
                                                    partition_ids_for_keys)

__all__ = ["DeviceSliceLost", "MeshSendOverflow", "MeshAggregateExec",
           "MeshExchangeExec", "MeshJoinExec", "all_gather_batch",
           "mesh_for"]


def _committed_device(b: ColumnBatch):
    """The single device ``b`` is committed to, or None (uncommitted
    batches live wherever the default device put them)."""
    if b.columns and getattr(b.columns[0].data, "committed", False):
        devs = b.columns[0].data.devices()
        if len(devs) == 1:
            return next(iter(devs))
    return None


def _note_a2a_bytes(stacked) -> None:
    """Static worst-case accounting for one collective launch: in an
    all-to-all every input row crosses the interconnect at most once, so
    the stacked program input's total byte size bounds the traffic.
    Incremented host-side at launch (a counter inside the jitted program
    is not expressible), so the counter moves per collective, not per
    byte actually routed off-device."""
    n = sum(getattr(leaf, "nbytes", 0)
            for leaf in jax.tree_util.tree_leaves(stacked))
    get_registry().inc("mesh_all_to_all_bytes", float(n))


class _MeshOutputMixin:
    """Mesh execs yield per-device committed batches.  When the planner
    sees a NON-mesh consumer above (which would mix devices inside its
    own jitted programs — per-batch join probes, window kernels), it
    sets ``align_output`` and the exec moves each yielded batch to the
    default device at the mesh->single-device boundary (review finding:
    patching individual consumers is whack-a-mole).

    Every batch that actually MOVES across devices here increments
    ``mesh_gather_fallbacks`` — the counter that tells you the plan fell
    off the mesh (docs/tuning-guide.md "Pod-scale execution"): a fully
    region-resident pipeline reads 0 because region members exchange
    inside one program and the boundary batches are consumed
    device-aware (place_shards affinity)."""

    align_output: bool = False

    def _aligned(self, it):
        if not self.align_output:
            yield from it
            return
        target = jax.devices()[0]
        for b in it:
            # host-backend batches (oracle path) carry no placement
            if not isinstance(b, ColumnBatch):
                yield b
                continue
            src = _committed_device(b)
            if src is not None and src != target:
                get_registry().inc("mesh_gather_fallbacks")
            yield jax.device_put(b, target)


class DeviceSliceLost(RuntimeError):
    """A mesh device slice died under a collective program (injected
    ``mesh.slice.lost`` fault, or an XLA/PJRT device-loss status): the
    on-mesh outputs are unrecoverable, but the child lineage is intact
    so the exec can recompute single-device."""


class MeshSendOverflow(RuntimeError):
    """A bounded [P, C] all-to-all send buffer
    (spark.rapids.tpu.mesh.exchange.sendCapacityRows) could not carry a
    skewed destination's rows.  Never silent: the overflow flag comes
    back from the program and the exchange retries at worst-case
    capacity (the mesh analog of PR 2's detect-then-split-and-retry —
    here the 'split' is the other direction: give the buffer room)."""


# status fragments PJRT/XLA surface when a participating device (or the
# ICI link to it) is gone mid-program, as opposed to a program bug
_DEVICE_LOSS_MARKERS = ("UNAVAILABLE", "DATA_LOSS", "device is lost",
                        "Device lost", "heartbeat timeout")


def _check_slice_fault(ctx: ExecCtx, op: str, mesh) -> None:
    """Deterministic injection point ``mesh.slice.lost`` (ctx: op,
    devices): fires before the collective launches, as a real slice
    loss would surface at program dispatch."""
    faults = getattr(ctx.catalog, "faults", None)
    if faults is None:
        return
    devices = ",".join(str(d.id) for d in mesh.devices.flat)
    if faults.check("mesh.slice.lost", op=op, devices=devices) is not None:
        raise DeviceSliceLost(
            f"injected fault: mesh slice lost under {op} "
            f"(devices [{devices}])")


def _reraise_unless_slice_lost(err: BaseException) -> None:
    """Let slice-loss errors fall through to the single-device
    recompute; anything else propagates unchanged."""
    if isinstance(err, DeviceSliceLost):
        return
    text = f"{type(err).__name__}: {err}"
    if any(m in text for m in _DEVICE_LOSS_MARKERS):
        return
    raise err


def _note_slice_recovery(ctx: ExecCtx, wall_s: float) -> None:
    """A lost slice was replaced by a single-device recompute: account
    it as one stage recovery so chaos/bench metrics see mesh losses and
    shuffle losses through the same counters (exec/recovery.py)."""
    m = ctx.catalog.metrics
    m["stage_recomputes"] = m.get("stage_recomputes", 0) + 1
    m["recovery_wall_s"] = m.get("recovery_wall_s", 0.0) + wall_s


def all_gather_batch(b: ColumnBatch, p: int, axis: str) -> ColumnBatch:
    """In-program replication: every device ends up with ALL rows of the
    sharded batch, front-packed.  Per-column tiled ``all_gather`` plus a
    segment-aware real mask (gathered rows are packed per shard segment,
    not globally — the MeshSortExec gather), then one compaction to
    restore the front-packed num_rows/row_mask contract downstream
    traced bodies rely on.  This is the replicated mesh join's build
    broadcast and the global window's input gather."""
    from spark_rapids_tpu.columnar.column import DeviceColumn
    cap = b.capacity
    counts = jax.lax.all_gather(b.num_rows, axis)  # int32[P]
    cols = []
    for c in b.columns:
        data = jax.lax.all_gather(c.data, axis, tiled=True)
        val = jax.lax.all_gather(c.validity, axis, tiled=True)
        if c.is_string:
            ln = jax.lax.all_gather(c.lengths, axis, tiled=True)
            cols.append(DeviceColumn(data, val, c.dtype, ln))
        else:
            cols.append(DeviceColumn(data, val, c.dtype))
    gcap = p * cap
    idx = jnp.arange(gcap, dtype=jnp.int32)
    real = (idx % cap) < counts[idx // cap]
    # num_rows = gcap so compact's row_mask covers every gathered slot;
    # compact itself front-packs and sets the true count
    gb = ColumnBatch(cols, jnp.asarray(gcap, jnp.int32), b.schema)
    return dk.compact(gb, real)


def mesh_for(ctx: ExecCtx, size: int, axis_name: str = "data"):
    """The ctx-cached 1-D device mesh, or None if < size devices exist."""
    key = ("mesh", size, axis_name)
    if key not in ctx.cache:
        devs = jax.devices()
        ctx.cache[key] = (make_mesh(size, axis_name, devs[:size])
                          if len(devs) >= size else None)
    return ctx.cache[key]


def place_shards(batches: Sequence[ColumnBatch], p: int):
    """Assign child batches to device shards WITHOUT a central gather.

    Round-2 verdict item 7: the old implementation concatenated every
    child batch in the driver process and re-sliced — a full gather
    before the "distributed" program.  Here batches are greedily
    assigned to shards and concatenated only WITHIN their shard
    (each shard touches ~1/p of the data; on a multi-host plane each
    host would run its own group).  Capacities and string widths are
    made uniform across shards (stacking onto the mesh requires it) by
    padding, not by gathering.  Row->shard assignment is arbitrary —
    callers shuffle by key immediately after (the reference's map-side
    split has the same freedom).

    Placement is by REAL rows, not storage capacity: inputs arrive
    padded (a region's split output keeps its program's static
    capacity; a scan can hand over one table-sized batch), and
    capacity-based placement both skews every real row onto one device
    and inflates the shared shard capacity to the fattest padded input
    — a multi-join region then sorts mostly padding on every device.
    Oversized free batches are sliced into ~1/p row ranges; committed
    batches keep their device (cross-device concat is both an error
    and a needless ICI hop) and are shrunk to their real rows instead.
    """
    groups: list[list[ColumnBatch]] = [[] for _ in range(p)]
    loads = [0] * p
    # device affinity first: batches already committed to a mesh device
    # (e.g. MeshJoinExec probe output) stay on it
    devs = jax.devices()[:p]
    dev_index = {repr(d): i for i, d in enumerate(devs)}
    rest = []
    for b in batches:
        n = b.host_num_rows()
        i = None
        if b.columns and getattr(b.columns[0].data, "committed", False):
            bdevs = b.columns[0].data.devices()
            if len(bdevs) == 1:
                i = dev_index.get(repr(next(iter(bdevs))))
        if i is not None:
            groups[i].append(b)
            loads[i] += n
        else:
            rest.append((n, b))
    total = sum(n for n, _ in rest)
    chunk = max(1024, -(-total // p))
    parts = []
    for n, b in rest:
        if n <= chunk:
            parts.append((n, b))
        else:
            for lo in range(0, n, chunk):
                hi = min(lo + chunk, n)
                parts.append((hi - lo, dk.slice_rows(b, lo, hi)))
    for n, b in sorted(parts, key=lambda t: -t[0]):
        i = loads.index(min(loads))
        groups[i].append(b)
        loads[i] += n
    cap = round_capacity(max(max(loads), 8))
    # global string widths per column (concat pads only within a group)
    schema = batches[0].schema
    widths = [max((b.columns[ci].max_len for b in batches), default=1)
              if isinstance(f.data_type, T.StringType) else None
              for ci, f in enumerate(schema)]
    shards = []
    for g in groups:
        if not g:
            shards.append(_empty_shard(schema, cap, widths))
            continue
        # drop each member to its own real-row bucket first: a padded
        # upstream capacity must not leak into the group concat
        g = [dk.shrink_capacity(b, round_capacity(max(b.host_num_rows(), 1)))
             for b in g]
        if len(g) == 1:
            s = g[0] if g[0].capacity == cap \
                else dk.pad_capacity(g[0], cap)
        else:
            need = max(cap, round_capacity(sum(b.capacity for b in g)))
            s = dk.concat_batches(g, out_capacity=need)
            if s.capacity > cap:
                s = dk.shrink_capacity(s, cap)
        shards.append(_pad_widths(s, widths))
    return shards


def drain_cached(ctx: ExecCtx, node: PlanNode) -> list:
    """Drain a child ONCE per execution and cache the batch list, so a
    size probe, an exchange, and a build can share one materialization
    (review finding: the partitioned-join size check must not drain the
    build side twice)."""
    from spark_rapids_tpu.exec.core import drain_partitions
    return ctx.cached(("drained", id(node), ctx.backend),
                      lambda: list(drain_partitions(ctx, node)))


def concat_or_empty(batches, schema: T.Schema) -> ColumnBatch:
    """One device batch from a drained list (empty-schema fallback).

    Region-era inputs may be committed to DIFFERENT mesh devices
    (split_shards keeps boundary batches device-resident); a concat
    must see them on one device, so mixed placements are aligned to the
    first committed device before concatenation — this is a build-side
    materialization (replicated to every device right after), not a
    gather fallback."""
    if not batches:
        from spark_rapids_tpu.exec.core import host_to_device
        from spark_rapids_tpu.host.batch import HostBatch
        return host_to_device(HostBatch.empty(schema))
    if len(batches) == 1:
        return batches[0]
    devs = {repr(_committed_device(b)) for b in batches}
    if len(devs) > 1:
        target = _committed_device(batches[0]) or jax.devices()[0]
        batches = [b if _committed_device(b) == target
                   else jax.device_put(b, target) for b in batches]
    return dk.concat_batches(batches)


def _empty_shard(schema: T.Schema, cap: int, widths) -> ColumnBatch:
    from spark_rapids_tpu.columnar.column import DeviceColumn
    cols = []
    for f, w in zip(schema, widths):
        validity = jnp.zeros(cap, jnp.bool_)
        if w is not None:
            cols.append(DeviceColumn(jnp.zeros((cap, w), jnp.uint8),
                                     validity, f.data_type,
                                     jnp.zeros(cap, jnp.int32)))
        else:
            cols.append(DeviceColumn(
                jnp.zeros(cap, f.data_type.np_dtype), validity,
                f.data_type))
    return ColumnBatch(cols, jnp.asarray(0, jnp.int32), schema)


def _pad_widths(b: ColumnBatch, widths) -> ColumnBatch:
    from spark_rapids_tpu.columnar.column import DeviceColumn
    cols = []
    changed = False
    for c, w in zip(b.columns, widths):
        if w is not None and c.max_len < w:
            cols.append(DeviceColumn(
                jnp.pad(c.data, ((0, 0), (0, w - c.max_len))), c.validity,
                c.dtype, c.lengths))
            changed = True
        else:
            cols.append(c)
    return ColumnBatch(cols, b.num_rows, b.schema) if changed else b


class MeshAggregateExec(_MeshOutputMixin, PlanNode):
    """Grouped aggregation as ONE distributed program over the mesh.

    Device plan per shard: pre-project -> partial sorted group-by ->
    all-to-all exchange of buffer rows by key hash -> merge group-by ->
    final projection.  Falls back to a complete-mode
    :class:`HashAggregateExec` on the host backend, when fewer devices
    than ``mesh_size`` exist, or on empty input.
    """

    def __init__(self, group_exprs: Sequence[Expression],
                 result_exprs: Sequence[Expression], child: PlanNode,
                 mesh_size: int, axis_name: str = "data"):
        super().__init__([child])
        self.mesh_size = mesh_size
        self.axis_name = axis_name
        self._group_exprs = list(group_exprs)
        self._result_exprs = list(result_exprs)
        # expression layout (pre/update/merge/final) — HashAggregateExec
        # owns this contract; partial mode exposes the buffer schema.
        self._layout = HashAggregateExec(group_exprs, result_exprs, child,
                                         mode="partial")
        self._output_schema = T.Schema(
            [T.StructField(f.name, f.data_type, True)
             for f in HashAggregateExec.final_from_partial(
                 self._layout, child).output_schema])
        self._jitted = {}

    @property
    def output_schema(self) -> T.Schema:
        return self._output_schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self.mesh_size if ctx.is_device else 1

    # -- fallback ------------------------------------------------------
    def _complete_exec(self) -> HashAggregateExec:
        # built lazily so transition-inserted wrappers around the child
        # (same schema) are picked up
        return HashAggregateExec(self._group_exprs, self._result_exprs,
                                 self.children[0], mode="complete")

    # -- distributed program -------------------------------------------
    def _local_step(self):
        """The per-device body (local view in, local view out) — the
        unit a MeshRegionExec splices into ITS shard_map program so a
        whole pipeline compiles as one per-device executable."""
        L = self._layout
        key_idx = list(range(len(L._group_bound)))
        p = self.mesh_size
        axis = self.axis_name
        out_schema = self._output_schema

        def step(b: ColumnBatch) -> ColumnBatch:
            cols = [eval_device(e, b) for e in L._pre_exprs]
            pre = ColumnBatch(cols, b.num_rows, L._pre_schema)
            part_out = _relabel_d(
                sorted_group_by(pre, key_idx, L._update_specs),
                L._buffer_schema)
            if key_idx:
                pid = partition_ids_for_keys(part_out, key_idx, p)
            else:
                # grand aggregate: merge all partial rows on device 0
                pid = jnp.where(part_out.row_mask(), 0, p)
            ex = _relabel_d(exchange_local(part_out, pid, p, axis),
                            L._buffer_schema)
            merged = _relabel_d(
                sorted_group_by(ex, key_idx, L._merge_specs),
                L._buffer_schema)
            out_cols = [eval_device(e, merged) for e in L._final_exprs]
            out = ColumnBatch(out_cols, merged.num_rows, out_schema)
            if not key_idx:
                # grand-aggregate finalization stays ON-device: device 0
                # carries the merged row, every other shard suppresses
                # its identity row — no host hop before the final value
                on0 = jax.lax.axis_index(axis) == 0
                out = canonicalize(ColumnBatch(
                    out.columns, jnp.where(on0, out.num_rows, 0),
                    out.schema))
            return out

        return step

    def _step_key_parts(self) -> tuple:
        """Fragment-key material for the local step (mesh part added by
        the program builder — a region key composes these per member)."""
        L = self._layout
        return ("mesh_agg", tuple(L._pre_exprs), L._pre_schema,
                tuple(L._update_specs), tuple(L._merge_specs),
                tuple(L._final_exprs), self._output_schema,
                len(L._group_bound), self.mesh_size)

    def _program(self, mesh):
        memo = id(mesh)
        if memo in self._jitted:
            return self._jitted[memo]
        from jax.sharding import PartitionSpec as P

        from spark_rapids_tpu.exec import compile_cache as cc
        axis = self.axis_name
        step = self._local_step()
        key = cc.fragment_key(*self._step_key_parts(),
                              cc.mesh_key_part(mesh, axis))

        def build():
            def prog(stacked: ColumnBatch) -> ColumnBatch:
                return restack(step(local_view(stacked)))
            return cc.instrument(jax.jit(shard_map(
                prog, mesh=mesh, in_specs=P(axis), out_specs=P(axis))))

        fn = cc.get_or_build(key, build)
        self._jitted[memo] = fn
        return fn

    def _outputs_cache_key(self, ctx: ExecCtx) -> tuple:
        return ("meshagg", id(self), ctx.backend)

    def _outputs(self, ctx: ExecCtx):
        return ctx.cached(self._outputs_cache_key(ctx),
                          lambda: self._compute_outputs(ctx))

    def _fallback_outputs(self, ctx: ExecCtx):
        """Single-device recompute: the complete-mode aggregation is the
        mesh program's lineage (same layout contract), re-run on the
        default device — also the degenerate path when the mesh never
        existed or the child produced nothing."""
        out = [list(self._complete_exec().partition_iter(ctx, 0))]
        out += [[] for _ in range(self.mesh_size - 1)]
        return out

    def _compute_outputs(self, ctx: ExecCtx):
        from spark_rapids_tpu.exec.core import drain_partitions
        batches = list(drain_partitions(ctx, self.children[0]))
        mesh = mesh_for(ctx, self.mesh_size, self.axis_name)
        t0 = None
        if mesh is not None and batches:
            try:
                _check_slice_fault(ctx, "meshagg", mesh)
                shards = place_shards(batches, self.mesh_size)
                stacked = shard_batches(shards, mesh, self.axis_name)
                _note_a2a_bytes(stacked)
                result = self._program(mesh)(stacked)
                return [[b] for b in split_shards(result)]
            except Exception as err:
                _reraise_unless_slice_lost(err)
                t0 = time.perf_counter()
        out = self._fallback_outputs(ctx)
        if t0 is not None:
            _note_slice_recovery(ctx, time.perf_counter() - t0)
        return out

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        if not ctx.is_device:
            yield from self._complete_exec().partition_iter(ctx, pid)
            return
        yield from self._aligned(iter(self._outputs(ctx)[pid]))

    def node_desc(self) -> str:
        return (f"MeshAggregateExec[mesh={self.mesh_size}, "
                f"keys={self._layout._group_names}, "
                f"out={self._output_schema.names}]")


class MeshExchangeExec(_MeshOutputMixin, PlanNode):
    """Hash repartition as an all-to-all collective over the mesh.

    Device path: pack child output into per-device shards, then ONE
    compiled program computes Spark-bit-exact murmur3 partition ids and
    exchanges rows (reference write path GpuHashPartitioning +
    RapidsCachingWriter, read path RapidsShuffleIterator — here both
    sides are the same collective).  Host backend delegates to the
    in-process ShuffleExchangeExec.
    """

    def __init__(self, keys: Sequence[Expression], child: PlanNode,
                 mesh_size: int, axis_name: str = "data",
                 num_partitions: int | None = None):
        super().__init__([child])
        self.mesh_size = mesh_size
        self.axis_name = axis_name
        # output partition count is independent of the device count
        # (round-2 verdict: the old num_partitions == deviceCount gate
        # silently sent other repartitions down the in-process loop):
        # rows route to device (pid % mesh_size); each device then serves
        # its owned subset of the N output partitions.
        self._num_parts = num_partitions or mesh_size
        self._keys = list(keys)
        self._bound = [bind(k, child.output_schema) for k in self._keys]
        self._jitted = {}

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self._num_parts

    def _host_exchange(self):
        from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
        from spark_rapids_tpu.exec.partitioning import HashPartitioning
        return ShuffleExchangeExec(
            HashPartitioning(self._keys, self._num_parts), self.children[0])

    def _augment(self, b: ColumnBatch):
        cols = list(b.columns)
        fields = list(self.output_schema.fields)
        kidx = []
        for i, k in enumerate(self._bound):
            cols.append(eval_device(k, b))
            fields.append(T.StructField(f"_pk{i}", k.dtype, True))
            kidx.append(len(cols) - 1)
        return ColumnBatch(cols, b.num_rows, T.Schema(fields)), kidx

    def _local_step(self, send_capacity: int | None = None):
        """Per-device body returning ``(batch, overflow)`` — the region
        splices this into its own shard_map program; overflow is
        statically False at worst-case capacity (send_capacity=None)."""
        p = self.mesh_size
        n = self._num_parts
        axis = self.axis_name

        def step(b: ColumnBatch):
            aug, kidx = self._augment(b)
            pid = partition_ids_for_keys(aug, kidx, n)
            dev = jnp.where(pid < n, pid % p, p)  # padding -> p (dropped)
            return exchange_local_checked(b, dev, p, axis,
                                          send_capacity=send_capacity)

        return step

    def _step_key_parts(self, send_capacity: int | None = None) -> tuple:
        return ("mesh_exchange", tuple(self._bound),
                self.children[0].output_schema, self._num_parts,
                send_capacity, self.mesh_size)

    def _program(self, mesh, send_capacity: int | None = None):
        memo = (id(mesh), send_capacity)
        if memo in self._jitted:
            return self._jitted[memo]
        from jax.sharding import PartitionSpec as P

        from spark_rapids_tpu.exec import compile_cache as cc
        axis = self.axis_name
        step = self._local_step(send_capacity)
        key = cc.fragment_key(*self._step_key_parts(send_capacity),
                              cc.mesh_key_part(mesh, axis))

        def build():
            def prog(stacked: ColumnBatch):
                out, overflow = step(local_view(stacked))
                return restack(out), restack(overflow)
            return cc.instrument(jax.jit(shard_map(
                prog, mesh=mesh, in_specs=P(axis),
                out_specs=(P(axis), P(axis)))))

        fn = cc.get_or_build(key, build)
        self._jitted[memo] = fn
        return fn

    def _pick_jit(self):
        # per output partition: keep rows of the device shard whose
        # recomputed partition id matches (device-local slice of the N
        # output partitions; no cross-device traffic)
        if not hasattr(self, "_pick"):
            n = self._num_parts

            def pick(b, pid):
                aug, kidx = self._augment(b)
                ids = partition_ids_for_keys(aug, kidx, n)
                return dk.compact(b, ids == pid)

            from spark_rapids_tpu.exec import compile_cache as cc
            self._pick = cc.instrument(jax.jit(pick))
        return self._pick

    def _outputs_cache_key(self, ctx: ExecCtx) -> tuple:
        return ("meshex", id(self), ctx.backend)

    def _outputs(self, ctx: ExecCtx):
        return ctx.cached(self._outputs_cache_key(ctx),
                          lambda: self._compute_outputs(ctx))

    def _fallback_outputs(self, ctx: ExecCtx):
        """Single-device recompute from lineage: the in-process exchange
        over the same child and keys — also the degenerate path when
        the mesh never existed or the child produced nothing."""
        he = self._host_exchange()
        return ("host", [list(he.partition_iter(ctx, pid))
                         for pid in range(self._num_parts)])

    def _run_exchange(self, ctx: ExecCtx, mesh, stacked):
        """Launch the exchange program; a bounded send buffer that
        overflowed under key skew retries ONCE at worst-case capacity
        (counted, never truncated — the mesh analog of split-and-retry)."""
        import numpy as np

        from spark_rapids_tpu.conf import MESH_SEND_CAPACITY
        send_cap = ctx.conf.get(MESH_SEND_CAPACITY) or None
        result, flags = self._program(mesh, send_cap)(stacked)
        if send_cap is not None and bool(
                # enginelint: disable=RL003 (overflow-flag check; one scalar sync gates the recompile fallback)
                np.asarray(jax.device_get(flags)).any()):
            get_registry().inc("mesh_send_overflows")
            result, _ = self._program(mesh, None)(stacked)
        return result

    def _compute_outputs(self, ctx: ExecCtx):
        if not ctx.is_device:
            return self._fallback_outputs(ctx)
        # drain_cached, not drain_partitions: in partitioned mesh-join
        # mode _use_partitioned already drained this subtree for its size
        # probe — share that materialization instead of executing twice
        batches = drain_cached(ctx, self.children[0])
        mesh = mesh_for(ctx, self.mesh_size, self.axis_name)
        t0 = None
        if mesh is not None and batches:
            try:
                _check_slice_fault(ctx, "meshex", mesh)
                shards = place_shards(batches, self.mesh_size)
                stacked = shard_batches(shards, mesh, self.axis_name)
                _note_a2a_bytes(stacked)
                result = self._run_exchange(ctx, mesh, stacked)
                return ("mesh", split_shards(result))
            except Exception as err:
                _reraise_unless_slice_lost(err)
                t0 = time.perf_counter()
        out = self._fallback_outputs(ctx)
        if t0 is not None:
            _note_slice_recovery(ctx, time.perf_counter() - t0)
        return out

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        yield from self._aligned(self._partition_iter_mesh(ctx, pid))

    def _partition_iter_mesh(self, ctx: ExecCtx, pid: int) -> Iterator:
        kind, out = self._outputs(ctx)
        if kind == "host":
            yield from out[pid]
            return
        # device shard (pid % mesh) holds every row of output partition
        # pid; slice it out locally and right-size the capacity (the
        # exchange shard capacity is p*C — passing it through would make
        # every downstream op pay O(n_parts * p * C))
        shard = out[pid % self.mesh_size]
        b = ctx.dispatch(self._pick_jit(), shard,
                         jnp.asarray(pid, jnp.int32))
        count = b.host_num_rows()
        if count > 0 or self._num_parts == 1:
            yield ctx.dispatch(dk.shrink_capacity, b,
                               round_capacity(max(count, 1)))

    def node_desc(self) -> str:
        return (f"MeshExchangeExec[mesh={self.mesh_size}, "
                f"parts={self._num_parts}, "
                f"keys={[output_name_safe(k) for k in self._keys]}]")


def output_name_safe(e: Expression) -> str:
    from spark_rapids_tpu.expr.core import output_name
    try:
        return output_name(e)
    # enginelint: disable=RL001 (descriptive label only; falls back to repr)
    except Exception:  # noqa: BLE001 - descriptive label only
        return repr(e)


class MeshJoinExec(_MeshOutputMixin, JoinExec):
    """Equi-join distributed over the mesh, broadcast OR partitioned.

    Two modes, selected at runtime by the materialized build-side size
    against ``spark.rapids.tpu.mesh.join.buildThresholdBytes``:

    - **replicated build** (the GpuBroadcastHashJoinExec analog,
      SURVEY §2.4): the build side is materialized once and REPLICATED
      to every mesh device (torrent-broadcast analog — small table
      resident per chip); the stream side is placed as per-device
      shards (place_shards, no central gather) and each device probes
      its own shard.  No collectives at all.
    - **partitioned** (the GpuShuffledHashJoinExec.scala:162 analog):
      BOTH sides hash-exchange on the join keys over the mesh
      (:class:`MeshExchangeExec` — exchange_local all-to-all inside
      shard_map), then each device joins its co-partitioned shards
      locally.  Equal keys land on the same device because both
      exchanges compute the same murmur3 over type-identical key
      columns, so a build side larger than one device's HBM share
      scales instead of replicating.

    Full outer joins keep the in-process path (their unmatched-build
    tail needs a cross-shard matched union).
    """

    def __init__(self, left: PlanNode, right: PlanNode, left_keys,
                 right_keys, join_type: str, mesh_size: int,
                 condition=None, build_threshold_bytes: int = 128 << 20):
        assert join_type != "full", "full outer stays in-process"
        super().__init__(left, right, left_keys, right_keys, join_type,
                         condition)
        self.mesh_size = mesh_size
        # the island path never names the mesh axis (its collectives run
        # through MeshExchangeExec), but the in-region body issues its
        # own all_gather/all-to-all under the region's axis
        self.axis_name = "data"
        self.build_threshold_bytes = build_threshold_bytes
        # unbound key exprs in POST-swap orientation (children[0] =
        # stream, children[1] = build) for the partitioned exchanges
        if self._swapped:
            left_keys, right_keys = right_keys, left_keys
        self._stream_keys_unbound = list(left_keys)
        self._build_keys_unbound = list(right_keys)
        # constructed eagerly (cheap PlanNodes): partition_iter runs on
        # concurrent drain workers, and a lazy check-then-set here would
        # race into duplicate exchanges doing the all-to-all twice
        self._exchanges = (
            MeshExchangeExec(self._stream_keys_unbound, self.children[0],
                             mesh_size, num_partitions=mesh_size),
            MeshExchangeExec(self._build_keys_unbound, self.children[1],
                             mesh_size, num_partitions=mesh_size))

    def num_partitions(self, ctx: ExecCtx) -> int:
        if not ctx.is_device:
            return self.children[0].num_partitions(ctx)
        return self.mesh_size

    # -- hooks ---------------------------------------------------------
    def _shard_devices(self, ctx: ExecCtx):
        devs = jax.devices()
        if len(devs) < self.mesh_size:
            # degrade like mesh_for/MeshAggregateExec: with fewer real
            # devices than the configured mesh, run single-device so a
            # downstream fallback consumer never sees mixed placements
            return devs[:1]
        return devs[:self.mesh_size]

    def _mesh_shards(self, ctx: ExecCtx):
        def make():
            devs = self._shard_devices(ctx)
            batches = drain_cached(ctx, self.children[0]) or \
                [concat_or_empty([], self.children[0].output_schema)]
            shards = place_shards(batches, len(devs))
            return [jax.device_put(s, d) for s, d in zip(shards, devs)]
        return ctx.cached((id(self), "mesh_stream_shards"), make)

    def _stream_batches(self, ctx: ExecCtx, pid: int):
        if self._use_partitioned(ctx):
            lex, _ = self._partitioned_exchanges()
            yield from lex.partition_iter(ctx, pid)
            return
        shards = self._mesh_shards(ctx)
        if pid < len(shards):
            yield shards[pid]

    # -- partitioned mode ---------------------------------------------
    def _use_partitioned(self, ctx: ExecCtx) -> bool:
        """Runtime mode pick: partitioned when the materialized build
        side exceeds the conf threshold (the reference decides build
        strategy from plan statistics, GpuShuffledHashJoinExec vs
        GpuBroadcastHashJoinExec; the engine decides from the ACTUAL
        drained size — exact, at the cost of one central
        materialization that a stats-based planner would avoid)."""
        if not ctx.is_device:
            return False

        def decide() -> bool:
            if self.build_threshold_bytes == 0:
                get_registry().inc("mesh_join_partitioned")
                ctx.trace_event(
                    "aqe.replan", "aqe", node=self.node_desc(),
                    build_bytes=-1, threshold=0, decision="partitioned")
                return True
            # cheap probe: sum bytes over the drained batch list (no
            # concat, no build prep); the list is ctx-cached so the
            # chosen path reuses it instead of draining again
            batches = drain_cached(ctx, self.children[1])
            nbytes = sum(getattr(x, "nbytes", 0)
                         for b in batches
                         for x in jax.tree_util.tree_leaves(b))
            partitioned = nbytes > self.build_threshold_bytes
            # the mesh analog of plan/adaptive.py's broadcast switch:
            # record the measured-size strategy pick on the trace (no
            # aqe_* counter — this is the static mesh join's built-in
            # decision, not a stage-boundary re-plan) and on the counter
            # registry (EXPLAIN ANALYZE renders these next to
            # mesh_all_to_all_bytes)
            reg = get_registry()
            if partitioned:
                reg.inc("mesh_join_partitioned")
            else:
                reg.inc("mesh_join_replicated")
                reg.inc("mesh_join_broadcast_bytes", float(nbytes))
            ctx.trace_event(
                "aqe.replan", "aqe", node=self.node_desc(),
                build_bytes=int(nbytes),
                threshold=int(self.build_threshold_bytes),
                decision="partitioned" if partitioned else "replicated")
            return partitioned
        return ctx.cached((id(self), "mesh_join_partitioned"), decide)

    def _partitioned_exchanges(self):
        return self._exchanges

    # -- region interior -----------------------------------------------
    def _region_step(self, mode: str, out_cap: int,
                     send_capacity: int | None = None):
        """Per-device traceable join body for MeshRegionExec interiors:
        ``(stream_local, build_local) -> (joined, (total, flags))``.

        ``mode`` is the host-side replicated/partitioned pick
        (_use_partitioned): replicated runs the build-side broadcast as
        an in-program all_gather; partitioned runs BOTH key exchanges as
        in-program all-to-alls (reusing the eagerly-built
        MeshExchangeExec steps, so partition ids are Spark-bit-exact and
        co-partitioning is guaranteed by construction).

        ``out_cap`` is the STATIC join output capacity — a host sync of
        the probe total is impossible inside shard_map, so the region
        launcher guesses, reads the returned ``total`` in ONE stacked
        aux fetch, and retries at the rounded-up measured capacity when
        the guess was short (the output is discarded, never truncated
        silently).  ``flags`` carries the bounded-send-buffer overflow
        bits of the partitioned exchanges (empty when replicated)."""
        from spark_rapids_tpu.ops.join import (gather_join_output,
                                               join_indices_from_probe,
                                               join_probe)
        jt = self.join_type
        n_right_raw = len(self.children[1].output_schema.fields)

        def step(sb: ColumnBatch, bb: ColumnBatch):
            flags = ()
            if mode == "partitioned":
                sb, s_ovf = self._exchanges[0]._local_step(send_capacity)(sb)
                bb, b_ovf = self._exchanges[1]._local_step(send_capacity)(bb)
                flags = (s_ovf, b_ovf)
            else:
                bb = all_gather_batch(bb, self.mesh_size, self.axis_name)
            lb2, lkeys = self._augment_device(sb, self._lkeys_b)
            rb2, rkeys = self._augment_device(bb, self._rkeys_b)
            probe_arrays, total = join_probe(lb2, rb2, list(lkeys),
                                             list(rkeys), jt)
            plan = join_indices_from_probe(lb2.capacity, probe_arrays, jt,
                                           out_cap)
            kf = T.Schema(list(lb2.schema.fields)
                          + (list(rb2.schema.fields)
                             if self.include_right else []))
            out = gather_join_output(lb2, rb2, *plan, kf,
                                     self.include_right)
            out = self._project_out(out, sb.num_columns, lb2.num_columns,
                                    n_right_raw, device=True)
            if self._condition is not None:
                c = eval_device(self._cond_b, out)
                out = dk.compact(out, c.data & c.validity)
            if self._swapped and self.include_right:
                out = self._reorder_device(out, sb.num_columns)
            out = ColumnBatch(out.columns, out.num_rows, self._schema)
            return out, (total, flags)

        return step

    def _region_step_key_parts(self, mode: str, out_cap: int,
                               send_capacity: int | None = None) -> tuple:
        """Fragment-key material for the in-region join body (the region
        key composes these per member; mesh part added by the builder)."""
        parts = ("mesh_join", mode, out_cap, self.join_type, self._swapped,
                 tuple(self._lkeys_b), tuple(self._rkeys_b),
                 self.children[0].output_schema,
                 self.children[1].output_schema,
                 self._cond_b if self._condition is not None else None,
                 self._schema, self.mesh_size)
        if mode == "partitioned":
            parts = parts + self._exchanges[0]._step_key_parts(send_capacity)
            parts = parts + self._exchanges[1]._step_key_parts(send_capacity)
        return parts

    def _materialize(self, ctx: ExecCtx, which: int):
        # route through the shared drained-list cache so the size probe
        # and the replicated build share one drain of the build child
        if ctx.is_device:
            child = self.children[which]
            return concat_or_empty(drain_cached(ctx, child),
                                   child.output_schema)
        return super()._materialize(ctx, which)

    def _device_build(self, ctx: ExecCtx, pid: int):
        if not self._use_partitioned(ctx):
            return MeshJoinExec._device_build_replicated(self, ctx, pid)

        def build():
            _, rex = self._partitioned_exchanges()
            rb = concat_or_empty(list(rex.partition_iter(ctx, pid)),
                                 self.children[1].output_schema)
            rb2, rkeys = self._augment_device(rb, self._rkeys_b)
            from spark_rapids_tpu.exec.joins import _jit_build_prep
            prep = _jit_build_prep(rb2, rkeys[0]) \
                if self._use_fast_path() else None
            return rb2, rkeys, prep
        return ctx.cached((id(self), "mesh_part_build", pid), build)

    def _device_build_replicated(self, ctx: ExecCtx, pid: int):
        rb2, rkeys, prep = self._build_device(ctx)
        devs = self._shard_devices(ctx)
        d = devs[pid % len(devs)]

        def rep():
            return (jax.device_put(rb2, d), rkeys,
                    None if prep is None else jax.device_put(prep, d))
        return ctx.cached((id(self), "mesh_build", repr(d)), rep)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        fn = JoinExec.partition_iter
        fn = getattr(fn, "__wrapped__", fn)
        yield from self._aligned(fn(self, ctx, pid))

    def node_desc(self) -> str:
        jt = "right" if self._swapped else self.join_type
        return f"MeshJoinExec[{jt}, mesh={self.mesh_size}]"

"""Physical execution operators (the reference's GpuExec layer, §2.4)."""
from spark_rapids_tpu.exec.core import (CoalesceGoal, ExecCtx, PlanNode,
                                        RequireSingleBatch, TargetSize,
                                        collect, collect_device, collect_host,
                                        device_to_host, host_to_device)
from spark_rapids_tpu.exec.basic import (FilterExec, GlobalLimitExec,
                                         LocalLimitExec, LocalScanExec,
                                         ProjectExec, RangeExec, UnionExec)
from spark_rapids_tpu.exec.aggregate import HashAggregateExec
from spark_rapids_tpu.exec.joins import CrossJoinExec, JoinExec
from spark_rapids_tpu.exec.window import WindowExec
from spark_rapids_tpu.exec.partitioning import (HashPartitioning,
                                                RangePartitioning,
                                                RoundRobinPartitioning,
                                                SinglePartitioning)
from spark_rapids_tpu.exec.exchange import (BroadcastExchangeExec,
                                            ShuffleExchangeExec)
from spark_rapids_tpu.exec.sortexec import (CoalesceBatchesExec, SortExec,
                                            resolve_orders)
from spark_rapids_tpu.exec.fused import FusedStageExec

__all__ = [
    "FusedStageExec",
    "CoalesceGoal", "ExecCtx", "PlanNode", "RequireSingleBatch", "TargetSize",
    "collect", "collect_device", "collect_host", "device_to_host",
    "host_to_device",
    "FilterExec", "GlobalLimitExec", "LocalLimitExec", "LocalScanExec",
    "ProjectExec", "RangeExec", "UnionExec",
    "HashAggregateExec", "CoalesceBatchesExec", "SortExec", "resolve_orders",
    "JoinExec", "CrossJoinExec", "WindowExec",
    "HashPartitioning", "RangePartitioning", "RoundRobinPartitioning",
    "SinglePartitioning", "ShuffleExchangeExec", "BroadcastExchangeExec",
]

"""Whole-stage fusion: run an adjacent filter/project pipeline as ONE
jitted program — one dispatch and one kernel launch per batch instead of
one per operator.

This is the engine's analog of whole-stage codegen, the reference
plugin's biggest small-query lever (PAPER.md §L3, GpuTransitionOverrides):
BENCH_r05 showed per-operator dispatch dominating below sf10.  The
planner (plan/overrides.py ``_fuse_stages``) collapses runs of
elementwise operators into a ``FusedStageExec`` whose body chains the
member programs inside a single ``jax.jit`` region, letting XLA fuse the
predicate, the compaction, and the projections into one kernel schedule
and elide every intermediate batch materialization.

Fusion changes the EXEC tree only — member ops keep their original child
links, so schema / ordering / batching delegation walks the unfused
chain unchanged, and ``node_desc`` renders the replaced pipeline for
EXPLAIN ANALYZE.

Fused stages stay citizens of the existing planes:

- the body is dispatched under ``ExecCtx.dispatch_retry`` → cooperative
  cancellation is checked per batch and OOM split-and-retry replays the
  whole fused program on each half (every member is elementwise, so
  split pieces produce identical rows in order);
- the jitted program comes from ``exec/compile_cache.py`` → identical
  stages across plans, queries, and sessions share one compiled
  executable, and compile/hit counters feed EXPLAIN ANALYZE;
- with ``spark.rapids.sql.fusion.donateInputs`` (default on) the input
  batch's buffers are donated to the region (SNIPPETS.md [1]–[2]
  ``donate_argnums``) so XLA reuses them for outputs.  Injected OOM
  faults fire BEFORE the program runs, so chaos split-and-retry is
  unaffected; a REAL device OOM after donation cannot replay the
  consumed batch and surfaces an actionable error naming the conf.
"""
from __future__ import annotations

import warnings
from typing import Iterator, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.exec.basic import FilterExec, ProjectExec
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.expr.core import eval_device, eval_host
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.ops import host_kernels as hk
from spark_rapids_tpu.ops import kernels as dk

__all__ = ["FusedStageExec", "fusible", "stage_body", "stage_key_parts"]

# donation is best-effort by design: a dtype-changing projection leaves
# some input buffers unreusable and jax warns per compile — expected here
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def fusible(node: PlanNode) -> bool:
    """Exactly FilterExec, or ProjectExec without partition-aware
    expressions (those read (pid, offset) outside the jit region and are
    fusion barriers).  Subclasses are excluded: they may override
    ``partition_iter`` semantics the fused body would bypass."""
    if type(node) is FilterExec:
        return True
    return type(node) is ProjectExec and not node._paware


def _is_donated_reuse_error(e: BaseException) -> bool:
    msg = str(e).lower()
    return "donat" in msg or "deleted" in msg


def stage_body(ops):
    """The single traced body chaining ``ops`` (innermost-first) — ONE
    program whether jitted standalone per batch (FusedStageExec) or
    spliced into a mesh region's per-device shard_map program
    (exec/mesh_region.py), where the same filter/projection chain runs
    shard-resident with no extra dispatch."""
    def body(b):
        for op in ops:
            if type(op) is FilterExec:
                c = eval_device(op._cond, b)
                b = dk.compact(b, c.data & c.validity)
            else:
                cols = [eval_device(e, b) for e in op._bound]
                b = ColumnBatch(cols, b.num_rows, op._schema)
        return b
    return body


def stage_key_parts(ops) -> list:
    """Fragment-key material for a filter/project chain: what
    ``stage_body``'s trace closes over, per member."""
    parts = []
    for op in ops:
        if type(op) is FilterExec:
            parts.append(("filter", op._cond))
        else:
            parts.append(("project", tuple(op._bound), op._schema))
    return parts


class FusedStageExec(PlanNode):
    """N adjacent elementwise operators executed as one jitted program.

    ``ops`` is innermost-first (ops[0] consumes the stage input,
    ops[-1] produces the stage output); each op keeps its ORIGINAL child
    link so property delegation traverses the unfused chain."""

    combines_batches = False

    def __init__(self, ops: Sequence[PlanNode]):
        assert len(ops) >= 2 and all(fusible(op) for op in ops)
        super().__init__([ops[0].children[0]])
        self._ops = tuple(ops)
        # cleared by the fusion pass when the stage input is shared by
        # another consumer: donating a shared batch deletes the buffers
        # under the sibling (e.g. a CTE scanned once, consumed twice)
        self.donate_ok = True

    @property
    def output_schema(self) -> T.Schema:
        return self._ops[-1].output_schema

    @property
    def output_ordering(self):
        # every member preserves row order; ProjectExec's rename-aware
        # ordering walk still works because child links are intact
        return self._ops[-1].output_ordering

    @property
    def output_batching(self):
        return self._ops[-1].output_batching

    @property
    def bound_exprs(self):
        return [e for op in self._ops for e in op.bound_exprs]

    @property
    def fused_ops(self) -> tuple:
        return self._ops

    def _stage_key(self, donate: bool) -> str:
        from spark_rapids_tpu.exec import compile_cache as cc
        return cc.fragment_key("fused_stage", stage_key_parts(self._ops),
                               self.children[0].output_schema, donate)

    def _jit_fn(self, donate: bool):
        if not hasattr(self, "_fused_jits"):
            self._fused_jits = {}
        if donate not in self._fused_jits:
            from spark_rapids_tpu.exec import compile_cache as cc
            kw = {"donate_argnums": 0} if donate else {}
            self._fused_jits[donate] = cc.shared_jit(
                self._stage_key(donate), stage_body(self._ops), **kw)
        return self._fused_jits[donate]

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        child_it = self.children[0].partition_iter(ctx, pid)
        if pid == 0:
            ctx.metrics_for(self).add("fusedOperators", len(self._ops))
        if not ctx.is_device:
            # host fallback mirrors the members' host paths sequentially
            # (the bench verifier runs the SAME plan on both backends)
            for b in child_it:
                for op in self._ops:
                    if type(op) is FilterExec:
                        c = eval_host(op._cond, b)
                        keep = c.data.astype(np.bool_) & c.validity
                        b = hk.host_filter(b, keep)
                    else:
                        cols = [eval_host(e, b) for e in op._bound]
                        b = HostBatch(cols, op._schema)
                yield b
            return
        from spark_rapids_tpu.exec.compile_cache import FUSION_DONATE
        donate = FUSION_DONATE.get(ctx.conf.settings) and self.donate_ok
        fn = self._jit_fn(donate)
        for b in child_it:
            # canonical pow2 entry capacity: shape polymorphism must not
            # fragment the shared executable cache
            cap = round_capacity(b.capacity)
            if cap != b.capacity:
                b = ctx.dispatch(dk.pad_capacity, b, cap)
            try:
                yield from ctx.dispatch_retry(fn, b, op="fused_stage")
            except Exception as e:
                if donate and _is_donated_reuse_error(e):
                    raise RuntimeError(
                        "OOM retry inside a fused stage needed an input "
                        "batch whose buffers were already donated to the "
                        "fused jit region; set "
                        "spark.rapids.sql.fusion.donateInputs=false to "
                        "trade buffer reuse for full split-and-retry "
                        "coverage") from e
                raise

    def node_desc(self) -> str:
        inner = " -> ".join(op.node_desc() for op in self._ops)
        return f"FusedStageExec[{len(self._ops)} ops: {inner}]"

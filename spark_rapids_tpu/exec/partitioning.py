"""Partitioning strategies: hash / range / round-robin / single.

Reference (SURVEY.md §2.4 Partitioning): GpuHashPartitioning.scala (cudf
murmur3 % n), GpuRangePartitioning.scala + GpuRangePartitioner.scala
(sampled bounds, then upper-bound search), GpuRoundRobinPartitioning,
GpuSinglePartitioning; device slicing via Table.contiguousSplit
(GpuPartitioning.scala:45-52).

TPU design: partition ids are computed on device (bit-exact Spark
murmur3 pmod for hash; rank-vs-bounds comparison for range) and each
output partition is front-pack compacted — no host round trip, so the
split fuses into the surrounding program.  Range bounds are quantile
rows of an on-device sort of the full input (the exchange is already a
stage barrier holding all batches), deterministic across backends where
the reference's reservoir sample is not.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.compile_cache import guarded_jit
from spark_rapids_tpu.expr.core import (Expression, bind, eval_device,
                                        eval_host)
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.ops import host_kernels as hk
from spark_rapids_tpu.ops import kernels as dk
from spark_rapids_tpu.ops.segmented import _cols_differ
from spark_rapids_tpu.ops.sort import SortOrder, encode_key_operands
from spark_rapids_tpu.parallel.mesh_shuffle import partition_ids_for_keys

__all__ = ["Partitioning", "HashPartitioning", "RangePartitioning",
           "RoundRobinPartitioning", "SinglePartitioning"]


class Partitioning:
    """Computes int32 partition ids per row on either backend."""

    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions

    def bind(self, schema: T.Schema) -> None:
        """Resolve key expressions against the child schema."""

    def prepare(self, batches, is_device: bool) -> None:
        """One-time setup over ALL materialized input batches (range
        bounds); called by the exchange before partitioning."""

    def device_ids(self, batch: ColumnBatch, batch_index: int) -> jax.Array:
        raise NotImplementedError

    def host_ids(self, batch: HostBatch, batch_index: int) -> np.ndarray:
        raise NotImplementedError


def _augment_device(batch: ColumnBatch, bound_keys) -> tuple:
    cols = list(batch.columns)
    fields = list(batch.schema.fields)
    idx = []
    for i, k in enumerate(bound_keys):
        v = eval_device(k, batch)
        cols.append(v)
        fields.append(T.StructField(f"_pk{i}", k.dtype, True))
        idx.append(len(cols) - 1)
    return ColumnBatch(cols, batch.num_rows, T.Schema(fields)), idx


class HashPartitioning(Partitioning):
    """Spark-bit-exact murmur3 pmod (reference GpuHashPartitioning)."""

    def __init__(self, keys: Sequence[Expression], num_partitions: int):
        super().__init__(num_partitions)
        self._keys = list(keys)
        self._bound = None

    def bind(self, schema: T.Schema) -> None:
        self._bound = [bind(k, schema) for k in self._keys]

    def device_ids(self, batch: ColumnBatch, batch_index: int) -> jax.Array:
        b2, idx = _augment_device(batch, self._bound)
        ids = partition_ids_for_keys(b2, idx, self.num_partitions)
        # padding rows got id == num_partitions; compact drops them anyway
        return ids

    def host_ids(self, batch: HostBatch, batch_index: int) -> np.ndarray:
        from spark_rapids_tpu.expr.core import EvalCtx, Val
        from spark_rapids_tpu.expr.hashing import murmur3_val, DEFAULT_SEED
        n = batch.num_rows
        ctx = EvalCtx(np, False, n, np.ones(n, np.bool_))
        seed = np.full(n, DEFAULT_SEED, dtype=np.uint32)
        for k in self._bound:
            c = eval_host(k, batch)
            seed = murmur3_val(Val(c.data, c.validity, None, c.dtype),
                               seed, ctx)
        h = seed.astype(np.int32)
        n_p = self.num_partitions
        return ((h % n_p) + n_p) % n_p


class RoundRobinPartitioning(Partitioning):
    """Even distribution by running row index (reference
    GpuRoundRobinPartitioning; deterministic instead of random-start)."""

    def __init__(self, num_partitions: int):
        super().__init__(num_partitions)
        self._offsets: list[int] = []

    def prepare(self, batches, is_device: bool) -> None:
        # precompute each batch's global row offset so both backends and
        # any batch order produce identical assignment (counts fetched in
        # ONE device round trip, not one per batch)
        if is_device:
            counts = [int(c) for c in
                      # enginelint: disable=RL003 (ONE stacked round trip for all batch counts; this IS the batched sync)
                      jax.device_get([b.num_rows for b in batches])]
        else:
            counts = [b.num_rows for b in batches]
        off = 0
        self._offsets = []
        for c in counts:
            self._offsets.append(off)
            off += c

    def device_ids(self, batch: ColumnBatch, batch_index: int) -> jax.Array:
        off = self._offsets[batch_index]
        return (jnp.arange(batch.capacity, dtype=jnp.int32) + off) \
            % self.num_partitions

    def host_ids(self, batch: HostBatch, batch_index: int) -> np.ndarray:
        off = self._offsets[batch_index]
        return (np.arange(batch.num_rows, dtype=np.int32) + off) \
            % self.num_partitions


class SinglePartitioning(Partitioning):
    def __init__(self):
        super().__init__(1)

    def device_ids(self, batch: ColumnBatch, batch_index: int) -> jax.Array:
        return jnp.zeros(batch.capacity, jnp.int32)

    def host_ids(self, batch: HostBatch, batch_index: int) -> np.ndarray:
        return np.zeros(batch.num_rows, np.int32)


# ---------------------------------------------------------------------------
# Range partitioning
# ---------------------------------------------------------------------------

def _rank_operands(cols, orders: Sequence[SortOrder], valid_rows):
    """Sort operand list for ranking rows under ``orders`` (nulls
    participate per nulls_first)."""
    operands = [(~valid_rows).astype(jnp.uint8)]
    for o, c in zip(orders, cols):
        null_ind = jnp.where(c.validity,
                             jnp.uint8(1 if o.resolved_nulls_first else 0),
                             jnp.uint8(0 if o.resolved_nulls_first else 1))
        operands.append(null_ind)
        operands.extend(encode_key_operands(c, o.ascending))
    return operands


def _combined_rank_ids(a_cols, b_cols, orders, real_a, real_b):
    """Dense ranks comparable across two row sets (a=data, b=bounds)."""
    from jax import lax
    na = real_a.shape[0]
    cc = na + real_b.shape[0]
    comb = []
    for ca, cb in zip(a_cols, b_cols):
        validity = jnp.concatenate([ca.validity, cb.validity])
        if ca.is_string:
            w = max(ca.max_len, cb.max_len)
            da = jnp.pad(ca.data, ((0, 0), (0, w - ca.max_len)))
            db = jnp.pad(cb.data, ((0, 0), (0, w - cb.max_len)))
            comb.append(DeviceColumn(jnp.concatenate([da, db]), validity,
                                     ca.dtype,
                                     jnp.concatenate([ca.lengths, cb.lengths])))
        else:
            comb.append(DeviceColumn(jnp.concatenate([ca.data, cb.data]),
                                     validity, ca.dtype))
    valid = jnp.concatenate([real_a, real_b])
    operands = _rank_operands(comb, orders, valid)
    iota = jnp.arange(cc, dtype=jnp.int32)
    sorted_ops = lax.sort(operands + [iota], num_keys=len(operands),
                          is_stable=True)
    order = sorted_ops[-1]
    differ = jnp.zeros(cc, jnp.bool_)
    for c in comb:
        sc = DeviceColumn(c.data[order], c.validity[order], c.dtype,
                          None if c.lengths is None else c.lengths[order])
        differ = differ | _cols_differ(sc)
    pos = jnp.arange(cc, dtype=jnp.int32)
    seg = jnp.cumsum(((pos > 0) & differ).astype(jnp.int32))
    ids = jnp.zeros(cc, jnp.int32).at[order].set(seg)
    return ids[:na], ids[na:]


class RangePartitioning(Partitioning):
    """Ordered partitioning by quantile bounds (reference
    GpuRangePartitioning + GpuRangePartitioner).

    ``prepare`` concatenates the input, sorts it by ``orders`` on the
    executing backend and takes n-1 equally spaced rows as bounds; a
    row's partition = count of bounds strictly below it (Spark
    RangePartitioner.getPartition semantics).
    """

    def __init__(self, orders: Sequence, num_partitions: int):
        super().__init__(num_partitions)
        self._orders_raw = list(orders)
        self._orders: list[SortOrder] = []
        self._key_exprs: list[Expression] = []
        self._bounds_d: list[DeviceColumn] | None = None
        self._bounds_h: HostBatch | None = None

    def bind(self, schema: T.Schema) -> None:
        from spark_rapids_tpu.exec.sortexec import resolve_orders
        self._schema = schema
        self._orders = resolve_orders(self._orders_raw, schema)

    def prepare(self, batches, is_device: bool) -> None:
        nb = self.num_partitions - 1
        if nb <= 0 or not batches:
            self._bounds_d = []
            self._bounds_h = None
            return
        if is_device:
            big = dk.concat_batches(batches) if len(batches) > 1 else batches[0]
            sb = _jit_sorted(big, tuple(self._orders))
            n = big.num_rows
            pos = ((jnp.arange(1, self.num_partitions, dtype=jnp.int64)
                    * n.astype(jnp.int64)) // self.num_partitions)
            pos = jnp.clip(pos, 0, jnp.maximum(n - 1, 0)).astype(jnp.int32)
            key_cols = [sb.columns[o.child_index] for o in self._orders]
            self._bounds_d = [
                DeviceColumn(c.data[pos], c.validity[pos], c.dtype,
                             None if c.lengths is None else c.lengths[pos])
                for c in key_cols]
            self._bounds_real = n > 0  # no bounds when input empty
        else:
            big = hk.host_concat(list(batches))
            sb = hk.host_sort(big, self._orders)
            n = big.num_rows
            if n == 0:
                self._bounds_h = None
                return
            pos = np.clip((np.arange(1, self.num_partitions, dtype=np.int64)
                           * n) // self.num_partitions, 0, n - 1)
            self._bounds_h = sb.take(pos)

    def device_ids(self, batch: ColumnBatch, batch_index: int) -> jax.Array:
        if not self._bounds_d:
            return jnp.zeros(batch.capacity, jnp.int32)
        key_cols = [batch.columns[o.child_index] for o in self._orders]
        nb = self.num_partitions - 1
        real_b = jnp.broadcast_to(jnp.asarray(self._bounds_real), (nb,))
        row_rank, bound_rank = _combined_rank_ids(
            key_cols, self._bounds_d, self._orders, batch.row_mask(), real_b)
        sorted_b = jnp.sort(bound_rank)
        return jnp.searchsorted(sorted_b, row_rank,
                                side="left").astype(jnp.int32)

    def host_ids(self, batch: HostBatch, batch_index: int) -> np.ndarray:
        n = batch.num_rows
        if self._bounds_h is None:
            return np.zeros(n, np.int32)
        # rank rows against bounds with the host sort's key codes
        nb = self._bounds_h.num_rows
        key_idx = [o.child_index for o in self._orders]
        comb_cols = []
        for ki in key_idx:
            a, b = batch.columns[ki], self._bounds_h.columns[ki]
            data = np.concatenate([a.data, b.data])
            validity = np.concatenate([a.validity, b.validity])
            from spark_rapids_tpu.host.batch import HostColumn
            comb_cols.append(HostColumn(data, validity, a.dtype))
        from spark_rapids_tpu.host.batch import HostBatch as HB
        schema = T.Schema([batch.schema.fields[ki] for ki in key_idx])
        comb = HB(comb_cols, schema)
        orders2 = [SortOrder(i, o.ascending, o.nulls_first)
                   for i, o in enumerate(self._orders)]
        perm = hk.host_sort_permutation(comb, orders2)
        # dense ranks with key-equality grouping
        ranks = np.zeros(n + nb, np.int64)
        r = 0
        for j in range(1, n + nb):
            prev, cur = perm[j - 1], perm[j]
            if any(not _host_keys_equal(c, prev, cur) for c in comb_cols):
                r += 1
            ranks[cur] = r
        ranks[perm[0]] = 0
        row_rank = ranks[:n]
        bound_rank = np.sort(ranks[n:])
        return np.searchsorted(bound_rank, row_rank,
                               side="left").astype(np.int32)


def _host_keys_equal(c, i: int, j: int) -> bool:
    vi, vj = c.validity[i], c.validity[j]
    if not vi or not vj:
        return vi == vj
    a, b = c.data[i], c.data[j]
    if isinstance(c.dtype, (T.FloatType, T.DoubleType)):
        fa, fb = float(a), float(b)
        if fa != fa and fb != fb:
            return True
        return fa == fb
    return a == b


@guarded_jit(static_argnames=("orders",))
def _jit_sorted(batch: ColumnBatch, orders):
    from spark_rapids_tpu.ops.sort import sort_batch
    return sort_batch(batch, list(orders))

"""Generate exec: explode / posexplode of split-string arrays.

Reference: GpuGenerateExec (GpuGenerateExec.scala:101) — per input row a
generator emits 0..n output rows; the child columns are repeated per
generated row, optionally with a position column, and ``outer`` keeps
rows whose generator yields nothing (null-extended).

Two generators: ``Explode`` over real ArrayType columns (padded element
matrix + lengths, columnar/column.py) and the fused ``SplitExplode`` =
explode(split(string, delimiter)) in one device program.  TPU design:
per-row counts (array lengths / delimiter cumulative-sums over the
padded byte matrix), output row -> (source row, element index) via the
same offsets/searchsorted plan as the join gather — all static shapes,
one host sync for the output total.
"""
from __future__ import annotations

from functools import partial
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.exec.compile_cache import guarded_jit
from spark_rapids_tpu.expr.core import Expression, bind, eval_device, \
    eval_host
from spark_rapids_tpu.host.batch import HostBatch, HostColumn

__all__ = ["GenerateExec", "SplitExplode"]


class SplitExplode(Expression):
    """Generator: explode(split(child, delimiter)) (single-byte delim)."""

    sql_name = "SplitExplode"

    def __init__(self, child: Expression, delimiter: str):
        assert len(delimiter.encode("utf-8")) == 1, \
            "SplitExplode supports single-byte delimiters"
        self.children = [child]
        self.delimiter = delimiter

    @property
    def dtype(self):
        return T.StringType()

    @property
    def nullable(self):
        return True

    def with_new_children(self, children):
        return SplitExplode(children[0], self.delimiter)

    def __repr__(self):
        return f"SplitExplode({self.children[0]!r}, {self.delimiter!r})"


class Explode(Expression):
    """Generator: explode(array_col) (reference GpuGenerateExec explode
    over LIST columns)."""

    sql_name = "Explode"

    def __init__(self, child: Expression):
        self.children = [child]

    @property
    def dtype(self):
        at = self.children[0].dtype
        assert isinstance(at, T.ArrayType), at
        return at.element_type

    @property
    def nullable(self):
        return True

    def with_new_children(self, children):
        return Explode(children[0])

    def __repr__(self):
        return f"Explode({self.children[0]!r})"


@guarded_jit(static_argnames=("out_cap", "pos_col", "outer"))
def _jit_generate_array(batch: ColumnBatch, col: DeviceColumn,
                        out_cap: int, pos_col: bool, outer: bool):
    """Explode an array column: one output row per element, child
    columns gathered per output row + [pos] + element column."""
    cap = batch.capacity
    w = col.max_len
    real = batch.row_mask()
    counts = jnp.where(col.validity & real, col.lengths, 0)
    emit = jnp.maximum(counts, 1) if outer else counts
    emit = jnp.where(real, emit, 0)
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(emit)[:-1].astype(jnp.int32)])
    total = jnp.sum(emit, dtype=jnp.int32)

    j = jnp.arange(out_cap, dtype=jnp.int32)
    in_range = j < total
    src = (jnp.searchsorted(offsets, j, side="right") - 1).astype(jnp.int32)
    src = jnp.clip(src, 0, cap - 1)
    k = j - offsets[src]
    has_elem = in_range & (k < counts[src])

    kc = jnp.clip(k, 0, w - 1)
    # fused single-element gather: col.data[src, kc] avoids
    # materializing the [out_cap, w] row-gather intermediate
    elem = col.data[src, kc]
    elem = jnp.where(has_elem, elem, jnp.zeros((), col.data.dtype))
    elem_col = DeviceColumn(elem, has_elem, col.dtype.element_type)

    out_cols = []
    for c in batch.columns:
        v = c.validity[src] & in_range
        if c.is_var_width:
            out_cols.append(DeviceColumn(
                jnp.where(v[:, None], c.data[src], 0), v, c.dtype,
                jnp.where(v, c.lengths[src], 0)))
        else:
            out_cols.append(DeviceColumn(
                jnp.where(v, c.data[src], jnp.zeros((), c.data.dtype)),
                v, c.dtype))
    if pos_col:
        out_cols.append(DeviceColumn(
            jnp.where(has_elem, k.astype(jnp.int32), 0), has_elem,
            T.IntegerType()))
    out_cols.append(elem_col)
    return out_cols, total


@guarded_jit(static_argnames=())
def _jit_counts(col: DeviceColumn, real: jax.Array, delim: int):
    """Per-row piece counts (0 for null/padding rows) + total."""
    w = col.max_len
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    is_d = (col.data == jnp.uint8(delim)) & (pos < col.lengths[:, None])
    counts = jnp.where(col.validity & real,
                       jnp.sum(is_d, axis=1, dtype=jnp.int32) + 1, 0)
    return counts, jnp.sum(counts, dtype=jnp.int64)


@guarded_jit(static_argnames=("out_cap", "pos_col", "outer"))
def _jit_generate(batch: ColumnBatch, col: DeviceColumn, counts, delim: int,
                  out_cap: int, pos_col: bool, outer: bool):
    """Build the generated batch: child columns gathered per output row +
    [pos] + piece string column."""
    cap = batch.capacity
    w = col.max_len
    real = batch.row_mask()
    emit = jnp.maximum(counts, 1) if outer else counts
    emit = jnp.where(real, emit, 0)
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(emit)[:-1].astype(jnp.int32)])
    total = jnp.sum(emit, dtype=jnp.int32)

    j = jnp.arange(out_cap, dtype=jnp.int32)
    in_range = j < total
    src = (jnp.searchsorted(offsets, j, side="right") - 1).astype(jnp.int32)
    src = jnp.clip(src, 0, cap - 1)
    k = j - offsets[src]                       # piece index within the row
    has_piece = in_range & (k < counts[src])   # outer null-extension rows

    # delimiter cumulative counts per source row
    posw = jnp.arange(w, dtype=jnp.int32)[None, :]
    is_d = (col.data == jnp.uint8(delim)) & (posw < col.lengths[:, None])
    cum = jnp.cumsum(is_d, axis=1)             # [cap, w]
    src_cum = cum[src]                         # [out_cap, w]
    # k-th delimiter position = first index with cum == k
    start = jnp.where(k > 0,
                      _first_ge(src_cum, k) + 1, 0)
    end = _first_ge(src_cum, k + 1)
    end = jnp.minimum(end, col.lengths[src])
    start = jnp.minimum(start, end)
    plen = (end - start).astype(jnp.int32)

    take = start[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    take = jnp.clip(take, 0, w - 1)
    bytes_out = jnp.take_along_axis(col.data[src], take, axis=1)
    mask = jnp.arange(w, dtype=jnp.int32)[None, :] < plen[:, None]
    validity = has_piece
    bytes_out = jnp.where(mask & validity[:, None], bytes_out, 0)
    piece = DeviceColumn(bytes_out, validity, T.StringType(),
                         jnp.where(validity, plen, 0))

    out_cols = []
    for c in batch.columns:
        v = c.validity[src] & in_range
        if c.is_string:
            out_cols.append(DeviceColumn(
                jnp.where(v[:, None], c.data[src], 0), v, c.dtype,
                jnp.where(v, c.lengths[src], 0)))
        else:
            out_cols.append(DeviceColumn(
                jnp.where(v, c.data[src], jnp.zeros((), c.data.dtype)),
                v, c.dtype))
    if pos_col:
        pv = in_range & has_piece
        out_cols.append(DeviceColumn(
            jnp.where(pv, k.astype(jnp.int32), 0), pv, T.IntegerType()))
    out_cols.append(piece)
    return out_cols, total


def _first_ge(cum: jax.Array, k) -> jax.Array:
    """Per output row: first column index where cum >= k (w if none)."""
    w = cum.shape[1]
    kk = k[:, None] if jnp.ndim(k) == 1 else k
    hit = cum >= kk
    idx = jnp.where(hit, jnp.arange(w, dtype=jnp.int32)[None, :], w)
    return jnp.min(idx, axis=1).astype(jnp.int32)


class GenerateExec(PlanNode):
    """explode/posexplode of a SplitExplode generator, child columns
    repeated per generated row (reference GpuGenerateExec.scala:101)."""

    def __init__(self, generator: Expression, child: PlanNode,
                 outer: bool = False, pos: bool = False,
                 output_names=("col",)):
        super().__init__([child])
        assert isinstance(generator, (SplitExplode, Explode)), \
            "only SplitExplode/Explode generators are supported"
        self.generator = generator
        self.outer = outer
        self.pos = pos
        self._gen_bound = bind(generator.children[0], child.output_schema)
        if isinstance(generator, SplitExplode):
            assert isinstance(self._gen_bound.dtype, T.StringType), \
                "SplitExplode input must be a string"
            out_dtype = T.StringType()
        else:
            assert isinstance(self._gen_bound.dtype, T.ArrayType), \
                "Explode input must be an array"
            out_dtype = self._gen_bound.dtype.element_type
        names = list(output_names)
        fields = list(child.output_schema.fields)
        if pos:
            fields.append(T.StructField(
                names[0] if len(names) > 1 else "pos", T.IntegerType(), True))
        fields.append(T.StructField(names[-1], out_dtype, True))
        self._schema = T.Schema(fields)

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def bound_exprs(self):
        return [self._gen_bound]

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        child_it = self.children[0].partition_iter(ctx, pid)
        if not ctx.is_device:
            for b in child_it:
                yield self._host_generate(b)
            return
        if isinstance(self.generator, Explode):
            for b in child_it:
                gcol = self._eval_jit()(b)
                real = b.row_mask()
                counts = jnp.where(gcol.validity & real, gcol.lengths, 0)
                if self.outer:
                    counts = jnp.where(real, jnp.maximum(counts, 1), 0)
                # enginelint: disable=RL003 (total gates output allocation; single scalar sync per batch)
                total = int(jax.device_get(
                    jnp.sum(counts, dtype=jnp.int64)))
                if total == 0:
                    continue
                out_cap = round_capacity(total)
                cols, tot = ctx.dispatch(
                    _jit_generate_array, b, gcol, out_cap, self.pos,
                    self.outer)
                yield ColumnBatch(cols, tot, self._schema)
            return
        delim = self.generator.delimiter.encode("utf-8")[0]
        for b in child_it:
            gcol = self._eval_jit()(b)
            real = b.row_mask()
            counts, total_d = _jit_counts(gcol, real, delim)
            if self.outer:
                # enginelint: disable=RL003 (outer rows need a host total to size the output; single scalar sync)
                total = int(jax.device_get(
                    jnp.sum(jnp.where(real, jnp.maximum(counts, 1), 0),
                            dtype=jnp.int64)))
            else:
                # enginelint: disable=RL003 (total gates output allocation; single scalar sync per batch)
                total = int(jax.device_get(total_d))
            if total == 0:
                continue
            out_cap = round_capacity(total)
            cols, tot = ctx.dispatch(
                _jit_generate, b, gcol, counts, delim, out_cap,
                self.pos, self.outer)
            yield ColumnBatch(cols, tot, self._schema)

    def _eval_jit(self):
        if not hasattr(self, "_gen_jit"):
            from spark_rapids_tpu.exec import compile_cache as cc
            self._gen_jit = cc.shared_jit(
                cc.fragment_key("generate", self._gen_bound),
                lambda b: eval_device(self._gen_bound, b))
        return self._gen_jit

    def _host_generate(self, b: HostBatch) -> HostBatch:
        gv = eval_host(self._gen_bound, b)
        is_array = isinstance(self.generator, Explode)
        src_idx, poss, pieces = [], [], []
        for i in range(b.num_rows):
            if not gv.validity[i]:
                if self.outer:
                    src_idx.append(i)
                    poss.append(None)
                    pieces.append(None)
                continue
            if is_array:
                parts = list(gv.data[i])
                if not parts and self.outer:
                    src_idx.append(i)
                    poss.append(None)
                    pieces.append(None)
                    continue
            else:
                parts = str(gv.data[i]).split(self.generator.delimiter)
            for k, p in enumerate(parts):
                src_idx.append(i)
                poss.append(k)
                pieces.append(p)
        cols = []
        idx = np.asarray(src_idx, dtype=np.int64)
        for c in b.columns:
            cols.append(HostColumn(c.data[idx] if len(idx) else
                                   c.data[:0], c.validity[idx] if len(idx)
                                   else c.validity[:0], c.dtype))
        if self.pos:
            pv = np.asarray([p is not None for p in poss], np.bool_)
            pd = np.asarray([0 if p is None else p for p in poss], np.int32)
            cols.append(HostColumn(pd, pv, T.IntegerType()))
        sv = np.asarray([p is not None for p in pieces], np.bool_)
        out_dtype = self._schema.fields[-1].data_type
        if isinstance(out_dtype, T.StringType):
            sd = np.empty(len(pieces), dtype=object)
            for i, p in enumerate(pieces):
                sd[i] = p
        else:
            sd = np.zeros(len(pieces), dtype=out_dtype.np_dtype)
            for i, p in enumerate(pieces):
                if p is not None:
                    sd[i] = p
        cols.append(HostColumn(sd, sv, out_dtype))
        return HostBatch(cols, self._schema)

    def node_desc(self) -> str:
        kind = "posexplode" if self.pos else "explode"
        return f"GenerateExec[{kind}{'_outer' if self.outer else ''}]"

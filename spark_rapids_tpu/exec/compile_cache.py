"""Process-wide compiled-kernel cache: jitted programs keyed on a
canonical plan-fragment fingerprint and shared across exec-node
instances, plans, queries, and sessions.

The reference's hot loop never compiles: every kernel is a pre-built
libcudf entry point (SURVEY §3.3).  The XLA analog used to re-``jax.jit``
per exec-node INSTANCE (``basic.py`` ``_project_jit``, ``joins.py``
``_cond_jit`` …), so two queries over the same plan fragment — or one
query re-run — paid tracing again because the wrapper died with the
plan.  Here the wrapper itself is process-wide: identical fragments
resolve to ONE shared jit callable, and jax's own executable cache keys
the compiled artifacts per (shape, dtype) signature underneath it.
Batch capacities are pow2-bucketed at the producers (and re-normalized
at fused-stage entry), so shape polymorphism cannot fragment that
inner cache.

Key design: the python-level key is the *program* (canonicalized
expression trees + schemas + static closure state), NOT the capacity
bucket — one wrapper serves every bucket, and the (capacity, dtype)
signature selects the executable inside jax.  ``SharedJit`` tracks the
signatures it has seen so ``compile_count`` / ``compile_wall_s`` move
exactly when a new executable is built, which makes "a second run of
the same query compiles nothing" a testable invariant (ci/premerge.sh).

Counters (MetricsRegistry): ``fusion_cache_hits`` / ``fusion_cache_misses``
move per fragment-key lookup; ``compile_count`` / ``compile_wall_s`` per
first invocation of a new input signature (trace + compile + first run).
"""
from __future__ import annotations

import contextlib
import hashlib
import threading
import time
import weakref
import types as _pytypes
from collections import OrderedDict
from functools import partial as _partial

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import bool_conf, conf, int_conf
from spark_rapids_tpu.obs.registry import get_registry

__all__ = ["fragment_key", "fingerprint", "get_or_build", "shared_jit",
           "instrument", "SharedJit", "cache_info", "reset_cache",
           "mesh_key_part",
           "FUSION_ENABLED", "FUSION_MIN_OPS", "FUSION_DONATE",
           "COMPILE_CACHE_DIR"]

FUSION_ENABLED = bool_conf(
    "spark.rapids.sql.fusion.enabled", True,
    "Collapse adjacent filter/project pipelines into single FusedStageExec "
    "nodes whose body is ONE jitted program — one dispatch and one kernel "
    "launch per batch instead of one per operator (the whole-stage-codegen "
    "analog; reference GpuTransitionOverrides, PAPER.md §L3). Disable to "
    "restore the per-operator plan shape.")

FUSION_MIN_OPS = int_conf(
    "spark.rapids.sql.fusion.minOperators", 2,
    "Minimum number of adjacent fusible operators before a FusedStageExec "
    "replaces the run; below it the per-operator nodes are kept.")

FUSION_DONATE = bool_conf(
    "spark.rapids.sql.fusion.donateInputs", True,
    "Donate input buffers to the fused jit region (jax donate_argnums) so "
    "XLA may reuse them for outputs — halves peak HBM per fused batch. "
    "Only applied when the stage's input is provably exclusive: the "
    "planner disables donation per stage when any producer below it is "
    "consumed by multiple parents (a CTE scanned once, joined twice) or "
    "shares a parked scan materialization, since donating a shared batch "
    "deletes its buffers under the sibling consumer. Tradeoff: a donated "
    "batch cannot be re-dispatched, so a REAL device OOM inside a fused "
    "stage cannot replay/split that batch and surfaces an actionable "
    "error instead; set false to trade buffer reuse for full "
    "split-and-retry coverage (docs/tuning-guide.md).")

COMPILE_CACHE_DIR = conf(
    "spark.rapids.sql.compile.cacheDir", "",
    "When set, force the persistent XLA compilation cache ON rooted at "
    "this directory (overriding spark.rapids.tpu.compilationCache.* "
    "including its XLA:CPU auto-off), so cold sessions start warm: a "
    "fragment compiled by ANY past process on this machine loads from "
    "disk instead of recompiling. Empty (default) defers to the "
    "spark.rapids.tpu.compilationCache.enabled mode.")

COMPILE_CACHE_MAX_ENTRIES = int_conf(
    "spark.rapids.sql.compile.cacheMaxEntries", 1024,
    "Upper bound on distinct plan fragments kept in the process-wide "
    "compile cache; least-recently-used entries (and their jax "
    "executables) are dropped past it.", internal=True)

COMPILE_CACHE_MAP_PRESSURE = int_conf(
    "spark.rapids.sql.compile.mapPressureLimit", 0,
    "Purge every cached executable when the process's memory-mapping "
    "count reaches this value at a compile event.  Each XLA:CPU "
    "executable pins ~10 mappings for the life of the process, so a "
    "long-lived engine eventually hits the kernel's vm.max_map_count "
    "and the NEXT compile dies with an unexplained SIGSEGV/SIGABRT "
    "inside backend_compile.  0 (default) = auto: 70% of "
    "/proc/sys/vm/max_map_count, disabled where /proc is absent.",
    internal=True)


# ---------------------------------------------------------------------------
# Canonical fingerprints
# ---------------------------------------------------------------------------

_MAX_DEPTH = 64

#: attribute values whose equality the recursion cannot prove
#: (callables, modules): poisoned with a process-unique serial, NOT
#: ``id()`` — a dead object's id can be reused by a NEW object, and an
#: id-based key would then falsely HIT the old entry.  The serial makes
#: such fingerprints unique per call: sharing is lost (the per-instance
#: ``hasattr`` guards still amortize the cost), correctness is not.
_OPAQUE = (_pytypes.FunctionType, _pytypes.MethodType,
           _pytypes.BuiltinFunctionType, _pytypes.ModuleType, _partial)

_SERIAL_LOCK = threading.Lock()
_SERIAL = 0


def _next_serial() -> int:
    global _SERIAL
    with _SERIAL_LOCK:
        _SERIAL += 1
        return _SERIAL


def _fp(v, out: list, seen: set, depth: int) -> None:
    if depth > _MAX_DEPTH:
        out.append(f"<deep:#{_next_serial()}>")
        return
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        out.append(repr(v))
        out.append(";")
        return
    if isinstance(v, T.DataType):
        # DataType reprs are structural (ArrayType includes its element)
        out.append(f"dt<{v!r}>;")
        return
    if isinstance(v, T.StructField):
        out.append(f"sf<{v.name}:")
        _fp(v.data_type, out, seen, depth + 1)
        out.append(f"{v.nullable}>;")
        return
    if isinstance(v, T.Schema):
        out.append("schema[")
        for f in v.fields:
            _fp(f, out, seen, depth + 1)
        out.append("];")
        return
    if isinstance(v, (list, tuple)):
        out.append("[" if isinstance(v, list) else "(")
        for x in v:
            _fp(x, out, seen, depth + 1)
        out.append("];" if isinstance(v, list) else ");")
        return
    if isinstance(v, dict):
        out.append("{")
        for k in sorted(v, key=repr):
            out.append(f"{k!r}=")
            _fp(v[k], out, seen, depth + 1)
        out.append("};")
        return
    if isinstance(v, _OPAQUE) or callable(v) and not hasattr(v, "children"):
        out.append(f"<opaque:{type(v).__name__}:#{_next_serial()}>;")
        return
    if id(v) in seen:
        out.append("<cycle>;")
        return
    seen.add(id(v))
    try:
        # generic object (Expression, resolved sort order, agg spec …):
        # class identity + every attribute, with expression children
        # LAST so tree shape is unambiguous.  Attributes the recursion
        # cannot canonicalize fall back to identity above — safety
        # (never share a program whose state we cannot prove equal)
        # over sharing.
        try:
            d = vars(v)
        except TypeError:
            out.append(f"<slots:{type(v).__name__}:#{_next_serial()}>;")
            return
        out.append(type(v).__name__)
        out.append("{")
        children = d.get("children", ())
        for k in sorted(d):
            if k == "children":
                continue
            out.append(f"{k}=")
            _fp(d[k], out, seen, depth + 1)
        out.append("}(")
        for c in children:
            _fp(c, out, seen, depth + 1)
        out.append(");")
    finally:
        seen.discard(id(v))


def fingerprint(*parts) -> str:
    """Canonical structural serialization of expressions / schemas /
    static closure state.  Unlike ``repr``, this captures non-child
    attributes (a LIKE pattern, a Cast target type, a resolved sort
    direction), every node's bound dtype, and poisons the result with a
    unique serial — never a lossy summary — for state it cannot prove
    canonical."""
    out: list = []
    _fp(list(parts), out, set(), 0)
    return "".join(out)


def mesh_key_part(mesh, axis_name: str) -> tuple:
    """The mesh component of a fragment key: a ``shard_map`` program is
    specialized to its mesh SHAPE (the all-to-all degree is baked into
    every buffer shape) and to the participating device set (the
    executable is lowered against those devices' memories), so a mesh-2
    and a mesh-4 lowering of the same fragment must MISS each other,
    and both must miss the single-chip program (which has no mesh part
    at all).  ``mesh`` may be a ``jax.sharding.Mesh`` or a plain device
    count."""
    if isinstance(mesh, int):
        return ("mesh", mesh, axis_name)
    devs = tuple(int(d.id) for d in mesh.devices.flat)
    return ("mesh", len(devs), axis_name, devs)


def fragment_key(kind: str, *parts) -> str:
    """Cache key for one plan fragment's program: a ``kind`` tag plus the
    md5 of the canonical fingerprint of everything the traced closure
    captures."""
    digest = hashlib.md5(fingerprint(*parts).encode()).hexdigest()
    return f"{kind}:{digest}"


# ---------------------------------------------------------------------------
# Shared jit wrappers + compile accounting
# ---------------------------------------------------------------------------

# XLA's CPU backend is not reliably safe against backend_compile running
# *concurrently* with other compiles OR with executions on sibling
# python threads (drain threads segfault inside the LLVM JIT while a
# peer dispatches) — observed as rare full-suite SIGSEGVs on single-host
# CPU runs.  On the CPU backend every SharedJit call therefore passes a
# process-wide readers-writer lock: warm dispatches share it, while a
# first-signature call — the one that traces + compiles — holds it
# exclusively.  Both sides are re-entrant for the lock-holding thread
# (jit-of-jit tracing re-enters wrappers).  Non-CPU backends take no
# lock at all.

class _CompileRWLock:
    """Many concurrent executors, one exclusive compiler."""

    __slots__ = ("_cond", "_readers", "_writer", "_depth")

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer: int | None = None
        self._depth = 0

    @contextlib.contextmanager
    def reading(self):
        me = threading.get_ident()
        with self._cond:
            if self._writer == me:
                counted = False  # already exclusive; pass through
            else:
                while self._writer is not None:
                    self._cond.wait()
                self._readers += 1
                counted = True
        try:
            yield
        finally:
            if counted:
                with self._cond:
                    self._readers -= 1
                    if not self._readers:
                        self._cond.notify_all()

    @contextlib.contextmanager
    def writing(self):
        me = threading.get_ident()
        with self._cond:
            if self._writer == me:
                self._depth += 1
            else:
                while self._writer is not None or self._readers:
                    self._cond.wait()
                self._writer = me
                self._depth = 1
        try:
            yield
        finally:
            with self._cond:
                self._depth -= 1
                if not self._depth:
                    self._writer = None
                    self._cond.notify_all()


_COMPILE_RW = _CompileRWLock()
_NULL_GUARD = contextlib.nullcontext()
_SERIALIZE_COMPILES: bool | None = None


def _cpu_backend() -> bool:
    global _SERIALIZE_COMPILES
    if _SERIALIZE_COMPILES is None:
        try:
            import jax
            _SERIALIZE_COMPILES = jax.default_backend() == "cpu"
        # enginelint: disable=RL001 (backend probe; falls back to non-serialized compiles)
        except Exception:
            _SERIALIZE_COMPILES = False
        if _SERIALIZE_COMPILES:
            from spark_rapids_tpu.runtime import sync_cpu_dispatch
            sync_cpu_dispatch()  # locks can't see the async native pool
    return _SERIALIZE_COMPILES


def compile_guard():
    """Exclusive guard to hold while a call WILL trace + compile."""
    return _COMPILE_RW.writing() if _cpu_backend() else _NULL_GUARD


def dispatch_guard():
    """Shared guard to hold while dispatching an already-built program."""
    return _COMPILE_RW.reading() if _cpu_backend() else _NULL_GUARD


# ---------------------------------------------------------------------------
# Mapping-pressure valve
# ---------------------------------------------------------------------------

_ALL_SHARED: "weakref.WeakSet" = weakref.WeakSet()
_MAP_LIMIT: int | None = None


def _map_pressure_limit() -> int:
    global _MAP_LIMIT
    if _MAP_LIMIT is None:
        lim = COMPILE_CACHE_MAP_PRESSURE.default
        if not lim:
            try:
                with open("/proc/sys/vm/max_map_count") as f:
                    lim = int(f.read()) * 7 // 10
            except (OSError, ValueError):
                lim = 0
        _MAP_LIMIT = lim
    return _MAP_LIMIT


def _map_count() -> int:
    try:
        with open("/proc/self/maps", "rb") as f:
            return f.read().count(b"\n")
    except OSError:
        return 0


def purge_compiled() -> None:
    """Drop every compiled executable the process holds.

    Clears the fragment cache, every SharedJit's signature bookkeeping,
    and jax's own executable caches, then collects — executables only
    release their code-page mappings once the last reference dies.
    Callers must already hold the exclusive compile guard (or be
    otherwise single-threaded): live plans keep their wrapper objects
    and simply recompile on next dispatch."""
    import gc
    import jax
    with _LOCK:
        _CACHE.clear()
    for sj in list(_ALL_SHARED):
        with sj._lock:
            sj._sigs.clear()
    jax.clear_caches()
    gc.collect()
    get_registry().inc("compile_cache_purges")


def _purge_if_pressured() -> bool:
    lim = _map_pressure_limit()
    if not lim or _map_count() < lim:
        return False
    purge_compiled()
    return True


class SharedJit:
    """A process-wide jit callable with per-signature compile accounting.

    jax compiles one executable per abstract input signature inside the
    wrapper; this class mirrors that bookkeeping at the python level so
    the first call for a NEW (shapes, dtypes, tree) signature — the one
    that traces and compiles — moves ``compile_count`` and is timed into
    ``compile_wall_s``.  Signatures already seen dispatch with no extra
    accounting beyond one set lookup."""

    __slots__ = ("fn", "_sigs", "_lock", "__weakref__")

    def __init__(self, fn):
        self.fn = fn
        self._sigs: set = set()
        self._lock = threading.Lock()
        _ALL_SHARED.add(self)

    def signature_count(self) -> int:
        return len(self._sigs)

    @staticmethod
    def _signature(args, kwargs):
        import jax
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = (treedef, tuple(
            (l.shape, str(l.dtype)) if hasattr(l, "shape") else l
            for l in leaves))
        hash(sig)  # unhashable static leaf -> fall back to uncounted
        return sig

    def __call__(self, *args, **kwargs):
        try:
            sig = self._signature(args, kwargs)
        # enginelint: disable=RL001 (unhashable static leaf falls back to an uncounted dispatch)
        except Exception:
            with dispatch_guard():
                return self.fn(*args, **kwargs)
        with self._lock:
            new = sig not in self._sigs
            if new:
                self._sigs.add(sig)
        if not new:
            with dispatch_guard():
                return self.fn(*args, **kwargs)
        t0 = time.perf_counter()
        try:
            with compile_guard():
                if _purge_if_pressured():
                    with self._lock:
                        self._sigs.add(sig)  # purge cleared it
                return self.fn(*args, **kwargs)
        finally:
            elapsed = time.perf_counter() - t0
            reg = get_registry()
            reg.inc("compile_count")
            reg.inc("compile_wall_s", elapsed)
            reg.observe("compile.wall_seconds", elapsed)


def instrument(fn) -> SharedJit:
    """Wrap an already-jitted callable with compile accounting."""
    return SharedJit(fn)


def guarded_jit(**jit_kwargs):
    """``jax.jit`` + the SharedJit wrapper, as a decorator.

    Module-level kernels (`@guarded_jit(static_argnames=...)`) get the
    same compile accounting as fragment-keyed programs AND pass the
    process-wide compile/dispatch guard, so on the CPU backend no raw
    kernel can compile concurrently with another engine compile or
    dispatch (the XLA-build crash class documented above).  jax already
    requires static args to be hashable, so the signature bookkeeping
    mirrors jax's own executable cache exactly."""
    def wrap(fn):
        import jax
        return SharedJit(jax.jit(fn, **jit_kwargs))
    return wrap


# ---------------------------------------------------------------------------
# The process-wide cache
# ---------------------------------------------------------------------------

_CACHE: "OrderedDict[str, object]" = OrderedDict()
_LOCK = threading.Lock()


def get_or_build(key: str, builder, *, max_entries: int | None = None):
    """Return the process-wide entry for ``key``, building it once.

    ``builder()`` runs OUTSIDE the cache lock (it may construct several
    jit wrappers); a concurrent duplicate build is discarded in favor of
    the first published entry, so callers always share one object per
    key.  ``fusion_cache_hits`` / ``fusion_cache_misses`` move per
    lookup."""
    reg = get_registry()
    with _LOCK:
        got = _CACHE.get(key)
        if got is not None:
            _CACHE.move_to_end(key)
            reg.inc("fusion_cache_hits")
            return got
    val = builder()
    bound = max_entries if max_entries is not None \
        else COMPILE_CACHE_MAX_ENTRIES.default
    with _LOCK:
        got = _CACHE.get(key)
        if got is not None:
            reg.inc("fusion_cache_hits")
            return got
        reg.inc("fusion_cache_misses")
        _CACHE[key] = val
        while len(_CACHE) > max(bound, 1):
            _CACHE.popitem(last=False)
    return val


def shared_jit(key: str, fn, **jit_kwargs) -> SharedJit:
    """``get_or_build`` specialization for the common one-function case:
    jit ``fn`` (with ``jit_kwargs``, e.g. ``donate_argnums``) behind the
    process-wide key and wrap it with compile accounting."""
    def build():
        import jax
        return SharedJit(jax.jit(fn, **jit_kwargs))
    return get_or_build(key, build)


def cache_info() -> dict:
    """Test/diagnostic hook: entry count + per-entry signature counts."""
    with _LOCK:
        entries = list(_CACHE.items())
    return {
        "entries": len(entries),
        "keys": [k for k, _ in entries],
        "signatures": {k: v.signature_count() for k, v in entries
                       if isinstance(v, SharedJit)},
    }


def reset_cache() -> None:
    """Test hook: drop every cached program (jax's own caches are
    untouched — they key on the jitted function object, which dies with
    the entry)."""
    with _LOCK:
        _CACHE.clear()

"""Basic physical operators: scan(local), project, filter, range, union,
limits.

Reference: basicPhysicalOperators.scala (GpuProjectExec ~:40, GpuFilterExec
~:150, GpuRangeExec ~:200, GpuUnionExec), limit.scala (GpuLocalLimitExec,
GpuGlobalLimitExec, GpuCollectLimitExec).
"""
from __future__ import annotations

from functools import partial as _partial
from typing import Iterator, Sequence

import jax as _jax
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.core import (ExecCtx, PlanNode, host_to_device)
from spark_rapids_tpu.exec.compile_cache import guarded_jit as _guarded_jit
from spark_rapids_tpu.expr.core import (Alias, Expression, bind, eval_device,
                                        eval_host, output_name)
from spark_rapids_tpu.host.batch import HostBatch, HostColumn
from spark_rapids_tpu.ops import host_kernels as hk
from spark_rapids_tpu.ops import kernels as dk

__all__ = ["LocalScanExec", "ProjectExec", "FilterExec", "RangeExec",
           "UnionExec", "LocalLimitExec", "GlobalLimitExec"]


@_guarded_jit(static_argnames=("cap",))
def _jit_miid(mask, cap: int, base):
    import jax.numpy as jnp
    data = jnp.where(mask, base + jnp.arange(cap, dtype=jnp.int64), 0)
    return DeviceColumn(data, mask, T.LongType())


@_guarded_jit(static_argnames=("cap",))
def _jit_spid(mask, cap: int, pid):
    import jax.numpy as jnp
    data = jnp.where(mask, pid.astype(jnp.int32), 0)
    return DeviceColumn(data, mask, T.IntegerType())


class LocalScanExec(PlanNode):
    """Scan over in-memory host batches, split into partitions.

    The leaf for tests and local pipelines (file scans live in
    spark_rapids_tpu.io).  On the device backend each host batch is
    transferred H2D (reference HostColumnarToGpu.scala).
    """

    def __init__(self, batches: Sequence[HostBatch], schema: T.Schema,
                 partitions: int = 1):
        super().__init__([])
        self._batches = list(batches)
        self._schema = schema
        self._parts = max(partitions, 1)

    @staticmethod
    def from_pydict(data: dict[str, list], schema: T.Schema,
                    partitions: int = 1, rows_per_batch: int | None = None
                    ) -> "LocalScanExec":
        cols = [HostColumn.from_values(data[f.name], f.data_type)
                for f in schema]
        hb = HostBatch(cols, schema)
        n = hb.num_rows
        rpb = rows_per_batch or max(n, 1)
        batches = [hk.host_slice(hb, i, i + rpb) for i in range(0, n, rpb)] \
            if n else [hb]
        return LocalScanExec(batches, schema, partitions)

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self._parts

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        mine = [b for i, b in enumerate(self._batches)
                if i % self._parts == pid]
        for hb in mine:
            if ctx.is_device:
                yield host_to_device(hb)
            else:
                yield hb

    def node_desc(self) -> str:
        return f"LocalScanExec[{self._schema.names}]"


class ProjectExec(PlanNode):
    """Evaluate bound expressions per batch (GpuProjectExec.project).

    Partition-aware expressions (spark_partition_id /
    monotonically_increasing_id) are rewritten to references of extra
    input columns computed per batch from (pid, row offset) — reference
    GpuSparkPartitionID/GpuMonotonicallyIncreasingID."""

    combines_batches = False

    def __init__(self, exprs: Sequence[Expression], child: PlanNode):
        super().__init__([child])
        self._raw = list(exprs)
        self._bound = [bind(e, child.output_schema) for e in self._raw]
        self._schema = T.Schema([
            T.StructField(output_name(r), b.dtype)
            for r, b in zip(self._raw, self._bound)])
        # hoist partition-aware expressions into extra input columns
        from spark_rapids_tpu.expr.core import BoundReference
        from spark_rapids_tpu.expr.misc import PartitionAwareExpression
        self._paware: list = []
        ncols = len(child.output_schema.fields)
        seen: dict[str, int] = {}

        def hoist(node):
            if isinstance(node, PartitionAwareExpression):
                key = type(node).__name__
                if key not in seen:
                    seen[key] = ncols + len(self._paware)
                    self._paware.append(node)
                return BoundReference(seen[key], node.dtype, False,
                                      f"_{key}")
            return node

        if any(any(isinstance(s, PartitionAwareExpression)
                   for s in e.walk()) for e in self._bound):
            self._bound = [e.transform_up(hoist) for e in self._bound]

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def bound_exprs(self):
        return list(self._bound)

    def _jit_fn(self):
        # one program per batch shape: whole-projection jit (the eager
        # per-op path costs a dispatch round trip per op on a remote TPU),
        # shared process-wide so identical projections across plans and
        # queries reuse one compiled program (exec/compile_cache.py)
        if not hasattr(self, "_project_jit"):
            from spark_rapids_tpu.exec import compile_cache as cc

            def project(b):
                cols = [eval_device(e, b) for e in self._bound]
                return ColumnBatch(cols, b.num_rows, self._schema)

            self._project_jit = cc.shared_jit(
                cc.fragment_key("project", tuple(self._bound), self._schema,
                                self.children[0].output_schema),
                project)
        return self._project_jit

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        child_it = self.children[0].partition_iter(ctx, pid)
        if ctx.is_device:
            import jax.numpy as jnp
            fn = self._jit_fn()
            # running row offset stays a DEVICE scalar: no per-batch sync
            offset = jnp.asarray(0, jnp.int64)
            for b in child_it:
                if self._paware:
                    # augment BEFORE the retry scope: the partition-aware
                    # columns ride along as data, so a split slices them
                    # with their rows and the global offsets stay exact
                    b = self._with_paware_device(b, pid, offset)
                    offset = offset + b.num_rows
                # elementwise: splitting on OOM yields identical rows
                # in order (reference GpuProjectExec withRetry)
                yield from ctx.dispatch_retry(fn, b, op="project")
        else:
            offset = 0
            for b in child_it:
                if self._paware:
                    b = self._with_paware_host(b, pid, offset)
                    offset += b.num_rows
                cols = [eval_host(e, b) for e in self._bound]
                yield HostBatch(cols, self._schema)

    def _with_paware_device(self, b, pid: int, offset):
        import jax.numpy as jnp
        from spark_rapids_tpu.expr.misc import MonotonicallyIncreasingID
        cols = list(b.columns)
        fields = list(b.schema.fields)
        for node in self._paware:
            if isinstance(node, MonotonicallyIncreasingID):
                col_ = _jit_miid(b.row_mask(), b.capacity,
                                 jnp.asarray(pid << 33, jnp.int64) + offset)
            else:
                col_ = _jit_spid(b.row_mask(), b.capacity,
                                 jnp.asarray(pid, jnp.int32))
            cols.append(col_)
            fields.append(T.StructField(f"_{type(node).__name__}",
                                        node.dtype, False))
        return ColumnBatch(cols, b.num_rows, T.Schema(fields))

    def _with_paware_host(self, b, pid: int, offset: int):
        from spark_rapids_tpu.expr.misc import MonotonicallyIncreasingID
        cols = list(b.columns)
        fields = list(b.schema.fields)
        n = b.num_rows
        for node in self._paware:
            if isinstance(node, MonotonicallyIncreasingID):
                data = (np.arange(n, dtype=np.int64) + (pid << 33) + offset)
            else:
                data = np.full(n, pid, dtype=np.int32)
            cols.append(HostColumn(data, np.ones(n, np.bool_), node.dtype))
            fields.append(T.StructField(f"_{type(node).__name__}",
                                        node.dtype, False))
        return HostBatch(cols, T.Schema(fields))

    @property
    def output_batching(self):
        # 1:1 batch mapping: whatever batching contract the child
        # satisfies, the projection's output satisfies too (keeps the
        # planner from inserting a coalesce that would destroy the
        # child's ordering between an aggregate pair)
        return self.children[0].output_batching

    @property
    def output_ordering(self):
        """Elementwise projection preserves row order; the child's
        clustering survives through columns projected as plain
        references (possibly renamed)."""
        from spark_rapids_tpu.expr.core import BoundReference
        child_ord = self.children[0].output_ordering
        if not child_ord:
            return None
        child_names = self.children[0].output_schema.names
        renames: dict[str, str] = {}
        for b, out in zip(self._bound, self._schema.names):
            inner = b.children[0] if isinstance(b, Alias) else b
            if isinstance(inner, BoundReference) \
                    and inner.index < len(child_names):
                renames.setdefault(child_names[inner.index], out)
        names = []
        for n in child_ord:
            if n not in renames:
                break
            names.append(renames[n])
        return names or None

    def node_desc(self) -> str:
        return f"ProjectExec[{self._schema.names}]"


class FilterExec(PlanNode):
    """Boolean condition -> compact kept rows (GpuFilterExec:
    Table.filter via front-packing permutation on device)."""

    combines_batches = False

    def __init__(self, condition: Expression, child: PlanNode):
        super().__init__([child])
        from spark_rapids_tpu.expr.misc import reject_partition_aware
        reject_partition_aware([condition], "a filter condition")
        self._cond = bind(condition, child.output_schema)
        assert isinstance(self._cond.dtype, T.BooleanType), \
            f"filter condition must be boolean, got {self._cond.dtype}"

    @property
    def bound_exprs(self):
        return [self._cond]

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    @property
    def output_ordering(self):
        # front-pack compaction is a stable permutation: surviving rows
        # keep their relative order, so the child's clustering holds
        return self.children[0].output_ordering

    @property
    def output_batching(self):
        # 1:1 batch mapping (fewer rows per batch never violates a goal
        # the child's batching already satisfied)
        return self.children[0].output_batching

    def _jit_fn(self):
        if not hasattr(self, "_filter_jit"):
            from spark_rapids_tpu.exec import compile_cache as cc

            def filt(b):
                c = eval_device(self._cond, b)
                keep = c.data & c.validity  # null -> drop (SQL WHERE)
                return dk.compact(b, keep)

            self._filter_jit = cc.shared_jit(
                cc.fragment_key("filter", self._cond,
                                self.children[0].output_schema),
                filt)
        return self._filter_jit

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        child_it = self.children[0].partition_iter(ctx, pid)
        if ctx.is_device:
            fn = self._jit_fn()
            for b in child_it:
                # row-wise predicate: split pieces filter to the same
                # surviving rows in order (GpuFilterExec withRetry)
                yield from ctx.dispatch_retry(fn, b, op="filter")
        else:
            for b in child_it:
                c = eval_host(self._cond, b)
                keep = c.data.astype(np.bool_) & c.validity
                yield hk.host_filter(b, keep)


class RangeExec(PlanNode):
    """Generate [start, end) step sequences on device (GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 partitions: int = 1, name: str = "id",
                 rows_per_batch: int = 1 << 20):
        super().__init__([])
        self._start, self._end, self._step = start, end, step
        self._parts = partitions
        self._rpb = rows_per_batch
        self._schema = T.Schema([T.StructField(name, T.LongType())])

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self._parts

    def _partition_bounds(self, pid: int) -> tuple[int, int]:
        total = max(0, -(-(self._end - self._start) // self._step))
        per = -(-total // self._parts)
        lo, hi = pid * per, min((pid + 1) * per, total)
        return lo, max(hi, lo)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        import jax.numpy as jnp
        lo, hi = self._partition_bounds(pid)
        for off in range(lo, hi, self._rpb) if hi > lo else []:
            cnt = min(self._rpb, hi - off)
            vals = (np.arange(off, off + cnt, dtype=np.int64) * self._step
                    + self._start)
            validity = np.ones(cnt, np.bool_)
            if ctx.is_device:
                cap = round_capacity(cnt)
                col = DeviceColumn.from_numpy(vals, validity, T.LongType(), cap)
                yield ColumnBatch([col], jnp.asarray(cnt, jnp.int32),
                                  self._schema)
            else:
                yield HostBatch([HostColumn(vals, validity, T.LongType())],
                                self._schema)


class UnionExec(PlanNode):
    """Concatenate children's partitions (GpuUnionExec): output partitions
    are the children's partitions back to back."""

    def __init__(self, children: Sequence[PlanNode]):
        super().__init__(children)
        s0 = children[0].output_schema
        for c in children[1:]:
            assert [f.data_type for f in c.output_schema] == \
                [f.data_type for f in s0], "union schema mismatch"

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return sum(c.num_partitions(ctx) for c in self.children)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        for c in self.children:
            np_ = c.num_partitions(ctx)
            if pid < np_:
                for b in c.partition_iter(ctx, pid):
                    yield _relabel(b, self.output_schema)
                return
            pid -= np_
        raise IndexError("partition out of range")


def _relabel(b, schema: T.Schema):
    if isinstance(b, HostBatch):
        cols = [HostColumn(c.data, c.validity, f.data_type)
                for c, f in zip(b.columns, schema)]
        return HostBatch(cols, schema)
    return ColumnBatch(b.columns, b.num_rows, schema)


def _limited(ctx: ExecCtx, it: Iterator, remaining: int) -> Iterator:
    """Yield batches sliced to at most ``remaining`` total rows."""
    for b in it:
        if remaining <= 0:
            return
        if ctx.is_device:
            b = dk.slice_batch(b, remaining)
            remaining -= b.host_num_rows()
        else:
            b = hk.host_slice(b, 0, remaining)
            remaining -= b.num_rows
        yield b


class LocalLimitExec(PlanNode):
    """Per-partition limit (GpuLocalLimitExec, limit.scala)."""

    def __init__(self, limit: int, child: PlanNode):
        super().__init__([child])
        self._limit = limit

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    @property
    def output_ordering(self):
        return self.children[0].output_ordering  # prefix slice

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        yield from _limited(ctx, self.children[0].partition_iter(ctx, pid),
                            self._limit)


class GlobalLimitExec(PlanNode):
    """Whole-query limit: single output partition (GpuGlobalLimitExec)."""

    combines_batches = False

    def __init__(self, limit: int, child: PlanNode):
        super().__init__([child])
        self._limit = limit

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return 1

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        child = self.children[0]
        all_parts = (b for cpid in range(child.num_partitions(ctx))
                     for b in child.partition_iter(ctx, cpid))
        yield from _limited(ctx, all_parts, self._limit)

"""Window exec: evaluate window expressions over sorted partitions.

Reference: GpuWindowExec (GpuWindowExec.scala:92, doExecuteColumnar:130)
— requires a single batch per partition group and lowers to cuDF rolling
windows.  Here the whole input is materialized (RequireSingleBatch, like
the reference's child goal), sorted once by (partition keys, order
keys), and every window expression is computed from the shared
SegmentInfo arrays (ops/window.py).  Output rows are in sorted order
(Spark leaves window output order undefined).

All window expressions in one exec must share one WindowSpec — Spark's
planner creates one WindowExec per distinct spec, and the planner here
does the same.
"""
from __future__ import annotations

from functools import partial
from typing import Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode, RequireSingleBatch
from spark_rapids_tpu.exec.compile_cache import guarded_jit
from spark_rapids_tpu.expr.core import (Expression, bind, eval_device,
                                        eval_host, output_name)
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr.window import (DenseRank, Lag, Lead, Rank,
                                          RowNumber, WindowExpression,
                                          window_agg_op)
from spark_rapids_tpu.host.batch import HostBatch, HostColumn
from spark_rapids_tpu.ops import host_kernels as hk
from spark_rapids_tpu.ops import kernels as dk
from spark_rapids_tpu.ops import window as W
from spark_rapids_tpu.ops.sort import SortOrder, sort_batch

__all__ = ["WindowExec"]


def _wexpr_dtype(w: WindowExpression, bound_input) -> T.DataType:
    """Output type computed from the BOUND function input (the raw
    WindowExpression.dtype needs resolved children)."""
    from spark_rapids_tpu.ops.segmented import AggSpec
    f = w.function
    if isinstance(f, A.AggregateFunction):
        op = window_agg_op(f)
        in_t = bound_input.dtype if bound_input is not None else T.LongType()
        return AggSpec(op, 0).result_type(in_t)
    if isinstance(f, (Lead, Lag)):
        return bound_input.dtype
    return f.dtype


class WindowExec(PlanNode):
    """Append one output column per window expression.

    ``window_exprs``: WindowExpression (optionally Alias-wrapped), all
    sharing the same WindowSpec.
    """

    def __init__(self, window_exprs: Sequence[Expression], child: PlanNode,
                 keys_partitioned: bool = False):
        super().__init__([child])
        # when the planner hash-partitioned the child on the window
        # partition keys, each child partition holds whole partition
        # groups and the window program runs per partition, preserving
        # upstream task parallelism (reference GpuWindowExec requires a
        # single batch only per partition GROUP, GpuWindowExec.scala:92;
        # collapsing the world was the round-3 scaling cliff)
        self._keys_partitioned = bool(keys_partitioned)
        from spark_rapids_tpu.expr.core import Alias
        self._names = [output_name(e) for e in window_exprs]
        self._wexprs: list[WindowExpression] = []
        for e in window_exprs:
            if isinstance(e, Alias):
                e = e.children[0]
            assert isinstance(e, WindowExpression), e
            self._wexprs.append(e)
        assert self._wexprs, "need at least one window expression"
        spec0 = self._wexprs[0].spec
        for e in self._wexprs[1:]:
            if e.spec != spec0:
                raise ValueError("one WindowExec handles one WindowSpec; "
                                 "split plans per spec as Spark does")
        self.spec = spec0
        cs = child.output_schema
        # bind partition/order/function-input expressions against the child
        self._part_b = [bind(p, cs) for p in self.spec.partition_by]
        self._order_b = [(bind(o[0], cs), o[1] if len(o) > 1 else True,
                          o[2] if len(o) > 2 else None)
                         for o in self.spec.order_by]
        self._fn_inputs: list[Expression | None] = []
        for w in self._wexprs:
            f = w.function
            if isinstance(f, (Lead, Lag)):
                self._fn_inputs.append(bind(f.children[0], cs))
            elif isinstance(f, A.AggregateFunction) and f.input is not None:
                self._fn_inputs.append(bind(f.input, cs))
            else:
                self._fn_inputs.append(None)
        self._out_dtypes = [_wexpr_dtype(w, b)
                            for w, b in zip(self._wexprs, self._fn_inputs)]
        self._schema = T.Schema(
            list(cs.fields)
            + [T.StructField(n, dt, True)
               for n, dt in zip(self._names, self._out_dtypes)])

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def output_batching(self):
        # the bounded-memory global stream emits one batch per input
        # batch; only the grouped single-batch path guarantees one
        if self._global_streamable():
            return None
        return RequireSingleBatch

    def num_partitions(self, ctx: ExecCtx) -> int:
        if self._keys_partitioned:
            return self.children[0].num_partitions(ctx)
        return 1

    # ------------------------------------------------------------------
    def _global_streamable(self) -> bool:
        """True when the whole-input window can run as a bounded-memory
        two-pass stream: empty partition-by + empty order-by makes every
        row's frame the ENTIRE input, so plain aggregates reduce to one
        running state + a broadcast — no single giant batch (VERDICT r4
        item 10; the reference's contract is single batch per GROUP, not
        per world, GpuWindowExec.scala:92)."""
        if self.spec.partition_by or self.spec.order_by:
            return False
        for w, inp in zip(self._wexprs, self._fn_inputs):
            f = w.function
            if not isinstance(f, A.AggregateFunction):
                return False
            try:
                op = window_agg_op(f)
            except ValueError:
                return False
            if op not in ("sum", "count", "count_star", "min", "max",
                          "avg"):
                return False
            if inp is not None and (inp.dtype.np_dtype is None
                                    or isinstance(inp.dtype,
                                                  (T.StringType,
                                                   T.ArrayType))):
                return False
        return True

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        child = self.children[0]
        if ctx.is_device and not self._keys_partitioned \
                and self._global_streamable():
            it = self._stream_global(ctx)
            first = next(it, None)
            if first is not None:
                yield first
                yield from it
                return
            # empty input: fall through to the single-batch path so the
            # empty-schema contract stays identical
        if self._keys_partitioned:
            batches = list(child.partition_iter(ctx, pid))
            if not batches:
                return
        else:
            batches = []
            for p in range(child.num_partitions(ctx)):
                batches.extend(child.partition_iter(ctx, p))
        if ctx.is_device:
            if not batches:
                from spark_rapids_tpu.exec.core import host_to_device
                big = host_to_device(HostBatch.empty(child.output_schema))
            else:
                big = dk.concat_batches(batches) if len(batches) > 1 \
                    else batches[0]
            yield self._run_device(big)
        else:
            big = hk.host_concat(batches) if batches \
                else HostBatch.empty(child.output_schema)
            yield self._run_host(big)

    # ------------------------------------------------------------------
    def _stream_global(self, ctx: ExecCtx) -> Iterator[ColumnBatch]:
        """Two-pass bounded-memory whole-input window: pass 1 streams
        child batches, folding each into an O(1) running state per
        expression and parking the batch SPILLABLE in the BufferCatalog
        (HBM -> host -> disk, so the working set never needs one giant
        batch); pass 2 un-parks each batch and appends the broadcast
        finals."""
        import jax
        import jax.numpy as jnp
        from spark_rapids_tpu.memory.catalog import (SpillableColumnarBatch,
                                                     SpillPriority)
        inputs = self._fn_inputs

        def update(b: ColumnBatch):
            """Per-wexpr state (sum, count, min, max, rows).  Integral
            inputs accumulate in int64 (an f64 fold would round sums and
            extremes past 2^53 — the single-batch segment kernels are
            exact there, and the two paths must agree)."""
            out = []
            real = b.row_mask()
            rows = jnp.sum(real, dtype=jnp.int64)
            for e in inputs:
                if e is None:
                    z = jnp.zeros((), jnp.int64)
                    out.append((z, rows, z, z, rows))
                    continue
                c = eval_device(e, b)
                valid = c.validity & real
                acc = jnp.float64 if c.data.dtype.kind == "f" else jnp.int64
                x = jnp.where(valid, c.data, 0).astype(acc)
                cnt = jnp.sum(valid, dtype=jnp.int64)
                big = jnp.asarray(jnp.inf if acc == jnp.float64
                                  else jnp.iinfo(jnp.int64).max, acc)
                small = jnp.asarray(-jnp.inf if acc == jnp.float64
                                    else jnp.iinfo(jnp.int64).min, acc)
                xd = c.data.astype(acc)
                mn = jnp.min(jnp.where(valid, xd, big))
                mx = jnp.max(jnp.where(valid, xd, small))
                out.append((jnp.sum(x), cnt, mn, mx, rows))
            return tuple(out)

        def merge(a, b):
            return tuple((sa + sb, ca + cb, jnp.minimum(mna, mnb),
                          jnp.maximum(mxa, mxb), ra + rb)
                         for (sa, ca, mna, mxa, ra),
                             (sb, cb, mnb, mxb, rb) in zip(a, b))

        if not hasattr(self, "_gs_jits"):
            from spark_rapids_tpu.exec import compile_cache as cc
            # update folds exactly `inputs`; merge captures nothing —
            # the pair is one process-wide entry keyed on the inputs
            self._gs_jits = cc.get_or_build(
                cc.fragment_key("window_gs_update", tuple(inputs)),
                lambda: (cc.instrument(jax.jit(update)),
                         cc.instrument(jax.jit(merge))))
        upd_jit, merge_jit = self._gs_jits[:2]

        child = self.children[0]
        parked, state = [], None
        for p in range(child.num_partitions(ctx)):
            for b in child.partition_iter(ctx, p):
                # splitting retry scope: the state merge is associative,
                # so an OOMed update re-run over row-halves folds to the
                # identical state (reference withRetry over the
                # pre-process step, GpuWindowExec)
                for part in ctx.dispatch_retry(upd_jit, b,
                                               op="window_update"):
                    state = part if state is None \
                        else ctx.dispatch(merge_jit, state, part)
                parked.append(SpillableColumnarBatch(
                    b, ctx.catalog, SpillPriority.READ_SHUFFLE))
        if state is None:
            return

        def append(b: ColumnBatch, st):
            cols = list(b.columns)
            real = b.row_mask()
            for (s, cnt, mn, mx, rows), w, dt in zip(
                    st, self._wexprs, self._out_dtypes):
                op = window_agg_op(w.function)
                if op == "count_star":
                    val, ok = rows, jnp.bool_(True)
                elif op == "count":
                    val, ok = cnt, jnp.bool_(True)
                elif op == "sum":
                    val, ok = s, cnt > 0
                elif op == "avg":
                    val = s.astype(jnp.float64) / jnp.maximum(cnt, 1)
                    ok = cnt > 0
                elif op == "min":
                    val, ok = mn, cnt > 0
                else:
                    val, ok = mx, cnt > 0
                np_dt = dt.np_dtype
                data = jnp.broadcast_to(
                    jnp.where(ok, val, 0).astype(np_dt.str),
                    (b.capacity,))
                validity = real & ok
                cols.append(DeviceColumn(
                    jnp.where(validity, data, jnp.zeros((), data.dtype)),
                    validity, dt))
            return ColumnBatch(cols, b.num_rows, self._schema)

        if len(self._gs_jits) == 2:
            from spark_rapids_tpu.exec import compile_cache as cc
            self._gs_jits = self._gs_jits + (cc.shared_jit(
                cc.fragment_key("window_gs_append", tuple(self._wexprs),
                                tuple(self._out_dtypes), self._schema),
                append),)
        app_jit = self._gs_jits[2]
        for sb in parked:
            b = sb.get()
            sb.close()
            # appending broadcast finals is elementwise given the fixed
            # state: splitting on OOM yields the same rows in order
            yield from ctx.dispatch_retry(
                lambda bb: app_jit(bb, state), b, op="window_apply")

    # ------------------------------------------------------------------
    def _window_args(self, big: ColumnBatch) -> tuple:
        """Augment ``big`` with evaluated partition/order/input columns
        and build the sort-order spec: ``(aug, orders, part_idx,
        order_idx, input_idx, nbase)``.  Pure eval_device — callable
        both eagerly (the single-device path jits the body separately)
        and INSIDE a trace (MeshWindowExec splices the whole window into
        a per-device shard_map program)."""
        nbase = big.num_columns
        cols = list(big.columns)
        fields = list(big.schema.fields)
        part_idx, order_idx, input_idx = [], [], []
        for e in self._part_b:
            cols.append(eval_device(e, big))
            fields.append(T.StructField(f"_wp{len(part_idx)}", e.dtype, True))
            part_idx.append(len(cols) - 1)
        for e, asc, nf in self._order_b:
            cols.append(eval_device(e, big))
            fields.append(T.StructField(f"_wo{len(order_idx)}", e.dtype, True))
            order_idx.append(len(cols) - 1)
        for e in self._fn_inputs:
            if e is None:
                input_idx.append(None)
            else:
                cols.append(eval_device(e, big))
                fields.append(T.StructField(f"_wi{len(cols)}", e.dtype, True))
                input_idx.append(len(cols) - 1)
        aug = ColumnBatch(cols, big.num_rows, T.Schema(fields))
        orders = [SortOrder(i, True, True) for i in part_idx] + \
            [SortOrder(i, asc, nf)
             for i, (_, asc, nf) in zip(order_idx, self._order_b)]
        return (aug, tuple(orders), tuple(part_idx), tuple(order_idx),
                tuple(input_idx), nbase)

    def _run_device(self, big: ColumnBatch) -> ColumnBatch:
        aug, orders, part_idx, order_idx, input_idx, nbase = \
            self._window_args(big)
        out = _jit_window(aug, orders, part_idx, order_idx, input_idx,
                          tuple(self._wexprs), nbase, self._schema)
        return out

    def _run_host(self, big: HostBatch) -> HostBatch:
        n = big.num_rows
        part_cols = [eval_host(e, big) for e in self._part_b]
        order_cols = [eval_host(e, big) for e, _, _ in self._order_b]
        in_cols = [None if e is None else eval_host(e, big)
                   for e in self._fn_inputs]
        # sort indices by (partition, order) with host sort machinery
        tmp_fields = [T.StructField(f"c{i}", c.dtype, True)
                      for i, c in enumerate(part_cols + order_cols)]
        tmp = HostBatch(part_cols + order_cols, T.Schema(tmp_fields))
        orders = [SortOrder(i, True, True) for i in range(len(part_cols))] + \
            [SortOrder(len(part_cols) + i, asc,
                       nf if nf is not None else None)
             for i, (_, asc, nf) in enumerate(self._order_b)]
        # empty spec: the zero-column tmp batch reports num_rows 0, so
        # host_sort_permutation would return an EMPTY identity — an
        # unordered global window keeps the input order directly
        perm = hk.host_sort_permutation(tmp, orders) if n and orders else \
            np.arange(n, dtype=np.int64)
        base = big.take(perm)
        sp = [c.take(perm) for c in part_cols]
        so = [c.take(perm) for c in order_cols]
        si = [None if c is None else c.take(perm) for c in in_cols]

        def key_tuple(colset, i):
            out = []
            for c in colset:
                if not c.validity[i]:
                    out.append(("\0null",))
                else:
                    v = c.data[i]
                    if isinstance(c.dtype, (T.FloatType, T.DoubleType)):
                        f = float(v)
                        v = "NaN" if f != f else (0.0 if f == 0.0 else f)
                    out.append((v,))
            return tuple(out)

        seg_start = np.zeros(n, np.int64)
        seg_end = np.zeros(n, np.int64)
        peer_start = np.zeros(n, np.int64)
        peer_end = np.zeros(n, np.int64)
        s = 0
        for i in range(1, n + 1):
            if i == n or key_tuple(sp, i) != key_tuple(sp, s):
                seg_start[s:i] = s
                seg_end[s:i] = i - 1
                ps = s
                for j in range(s + 1, i + 1):
                    if j == i or key_tuple(so, j) != key_tuple(so, ps):
                        peer_start[ps:j] = ps
                        peer_end[ps:j] = j - 1
                        ps = j
                s = i

        new_cols = []
        for w, inc, out_dt in zip(self._wexprs, si, self._out_dtypes):
            f = w.function
            frame = w.spec.resolved_frame()
            if isinstance(f, RowNumber):
                data = np.arange(n) - seg_start + 1
                new_cols.append(HostColumn(data.astype(np.int32),
                                           np.ones(n, bool), out_dt))
            elif isinstance(f, Rank):
                data = peer_start - seg_start + 1
                new_cols.append(HostColumn(data.astype(np.int32),
                                           np.ones(n, bool), out_dt))
            elif isinstance(f, DenseRank):
                data = np.zeros(n, np.int32)
                r = 0
                for i in range(n):
                    if i == seg_start[i]:
                        r = 1
                    elif peer_start[i] == i:
                        r += 1
                    data[i] = r
                new_cols.append(HostColumn(data, np.ones(n, bool), out_dt))
            elif isinstance(f, (Lead, Lag)):
                # Lag subclasses Lead: test Lag first (same fix as the
                # device path — both sides previously read forward, which
                # differential testing could not catch)
                off = -f.offset if isinstance(f, Lag) else f.offset
                data = np.empty(n, object)
                validity = np.zeros(n, bool)
                defv = None
                if f.default is not None:
                    from spark_rapids_tpu.expr.core import Literal
                    assert isinstance(f.default, Literal)
                    defv = f.default.value
                for i in range(n):
                    j = i + off
                    if seg_start[i] <= j <= seg_end[i]:
                        if inc.validity[j]:
                            data[i] = inc.data[j]
                            validity[i] = True
                    elif defv is not None:
                        data[i] = defv
                        validity[i] = True
                new_cols.append(_objs_to_host(data, validity, out_dt))
            else:
                op = window_agg_op(f)
                data = np.empty(n, object)
                validity = np.zeros(n, bool)
                for i in range(n):
                    if frame.mode == "rows":
                        lo = seg_start[i] if frame.lower is None else \
                            max(i + frame.lower, seg_start[i])
                        hi = seg_end[i] if frame.upper is None else \
                            min(i + frame.upper, seg_end[i])
                    else:
                        lo = seg_start[i] if frame.lower is None \
                            else peer_start[i]
                        hi = seg_end[i] if frame.upper is None \
                            else peer_end[i]
                    vals = []
                    cnt_rows = 0
                    for j in range(lo, hi + 1):
                        cnt_rows += 1
                        if inc is not None and inc.validity[j]:
                            vals.append(inc.data[j])
                    data[i], validity[i] = _host_agg(op, vals, cnt_rows,
                                                     out_dt)
                new_cols.append(_objs_to_host(data, validity, out_dt))
        return HostBatch(list(base.columns) + new_cols, self._schema)

    def node_desc(self) -> str:
        return f"WindowExec[{self._names}]"


def _host_agg(op, vals, cnt_rows, dtype):
    import math
    if op == "count_star":
        return cnt_rows, True
    if op == "count":
        return len(vals), True
    if not vals:
        return None, False
    fvals = [float(v) for v in vals]
    if op == "sum":
        if isinstance(dtype, T.LongType):
            return int(sum(int(v) for v in vals)), True
        return float(sum(fvals)), True
    if op == "avg":
        return float(sum(fvals) / len(vals)), True
    has_nan = any(isinstance(v, float) and math.isnan(v) for v in vals)
    if op == "min":
        nn = [v for v in vals
              if not (isinstance(v, float) and math.isnan(v))]
        if nn:
            return min(nn), True
        return float("nan"), True
    if op == "max":
        if has_nan:
            return float("nan"), True
        return max(vals), True
    raise ValueError(op)


def _objs_to_host(data, validity, dtype) -> HostColumn:
    if isinstance(dtype, T.StringType):
        return HostColumn(data, validity, dtype)
    npdt = dtype.np_dtype
    arr = np.zeros(len(data), npdt)
    for i, v in enumerate(data):
        if validity[i]:
            arr[i] = v
    return HostColumn(arr, validity, dtype)


def _window_body(aug: ColumnBatch, orders, part_idx, order_idx, input_idx,
                 wexprs, nbase: int, schema: T.Schema) -> ColumnBatch:
    """The traceable window kernel: sort by (partition, order), derive
    the shared segment arrays, evaluate every expression.  ``_jit_window``
    is its eager jitted wrapper; MeshWindowExec calls the body directly
    inside its per-device program."""
    sb = sort_batch(aug, list(orders))
    seg = W.sorted_segments(sb, part_idx, order_idx)
    out_cols = list(sb.columns[:nbase])
    for w, ii in zip(wexprs, input_idx):
        f = w.function
        if isinstance(f, RowNumber):
            data = W.row_number(seg).astype(jnp.int32)
            out_cols.append(DeviceColumn(
                jnp.where(seg.real, data, 0), seg.real, T.IntegerType()))
        elif isinstance(f, Rank):
            data = W.rank(seg).astype(jnp.int32)
            out_cols.append(DeviceColumn(
                jnp.where(seg.real, data, 0), seg.real, T.IntegerType()))
        elif isinstance(f, DenseRank):
            data = W.dense_rank(seg).astype(jnp.int32)
            out_cols.append(DeviceColumn(
                jnp.where(seg.real, data, 0), seg.real, T.IntegerType()))
        elif isinstance(f, (Lead, Lag)):
            # NOTE: Lag subclasses Lead — test Lag FIRST (isinstance of
            # Lead is true for both; the old order made lag read forward)
            off = -f.offset if isinstance(f, Lag) else f.offset
            col = sb.columns[ii]
            dd = dv = dl = None
            if f.default is not None:
                from spark_rapids_tpu.expr.core import Literal
                assert isinstance(f.default, Literal)
                if f.default.value is not None:
                    if col.is_string:
                        import numpy as _np
                        from spark_rapids_tpu.columnar.column import \
                            round_string_width
                        bs = str(f.default.value).encode("utf-8")
                        w = max(col.max_len,
                                round_string_width(max(len(bs), 1)))
                        row = _np.zeros(w, _np.uint8)
                        row[:len(bs)] = _np.frombuffer(bs, _np.uint8)
                        dd = jnp.broadcast_to(jnp.asarray(row),
                                              (sb.capacity, w))
                        dl = jnp.full(sb.capacity, len(bs), jnp.int32)
                    else:
                        dd = jnp.full(sb.capacity, f.default.value,
                                      col.data.dtype)
                    dv = jnp.ones(sb.capacity, jnp.bool_)
            data, validity, lengths = W.lead_lag(col, seg, off, dd, dv, dl)
            out_cols.append(DeviceColumn(data, validity, col.dtype, lengths))
        else:
            op = window_agg_op(f)
            frame = w.spec.resolved_frame()
            if op == "count_star":
                col = DeviceColumn(jnp.zeros(sb.capacity, jnp.int64),
                                   seg.real, T.LongType())
                data, validity, rtype = W.running_or_bounded_agg(
                    "count", col, seg, frame)
            else:
                col = sb.columns[ii]
                data, validity, rtype = W.running_or_bounded_agg(
                    op, col, seg, frame)
            zero = jnp.zeros((), data.dtype)
            out_cols.append(DeviceColumn(jnp.where(validity, data, zero),
                                         validity, rtype))
    return ColumnBatch(out_cols, sb.num_rows, schema)


_jit_window = guarded_jit(
    static_argnames=("orders", "part_idx", "order_idx", "input_idx",
                     "wexprs", "nbase", "schema"))(_window_body)

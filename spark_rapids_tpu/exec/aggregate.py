"""Hash-aggregate exec with partial/final/complete modes.

Reference: aggregate.scala (GpuHashAggregateExec, ``doExecuteColumnar``
:348-560): per input batch compute a groupby aggregate, then iteratively
concatenate with the running result and merge-aggregate; final projection
over the aggregation buffer.  The device kernel here is sort-based
(:mod:`spark_rapids_tpu.ops.segmented`, the TPU-idiomatic substitute for
cuDF's hash groupby — see SURVEY.md §7 hard parts).

Modes mirror Spark's aggregate modes:
* ``complete`` — one exec does update + cross-batch merge + result;
* ``partial``  — update only, emits the aggregation buffer (keys +
  intermediates) for an exchange;
* ``final``    — consumes buffer batches, merges across them, projects
  results.
"""
from __future__ import annotations

from typing import Iterator, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.exec.core import (ExecCtx, PlanNode,
                                        RequireSingleBatch, TargetSize)
from spark_rapids_tpu.expr.aggregates import AggregateFunction
from spark_rapids_tpu.expr.core import (Alias, BoundReference, Expression,
                                        bind, eval_device, eval_host,
                                        output_name)
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.ops import host_kernels as hk
from spark_rapids_tpu.ops import kernels as dk
from spark_rapids_tpu.ops.segmented import AggSpec, sorted_group_by

__all__ = ["HashAggregateExec"]


def _strip_alias(e: Expression) -> Expression:
    return e.children[0] if isinstance(e, Alias) else e


class HashAggregateExec(PlanNode):
    """Group-by aggregation.

    ``group_exprs``: grouping expressions (resolved against child schema).
    ``result_exprs``: output expressions over group keys and aggregate
    functions (e.g. ``(Sum(col("x")) / CountStar()).alias("r")``).
    """

    def __init__(self, group_exprs: Sequence[Expression],
                 result_exprs: Sequence[Expression], child: PlanNode,
                 mode: str = "complete"):
        if mode == "final":
            raise ValueError("use HashAggregateExec.final_from_partial()")
        assert mode in ("complete", "partial")
        super().__init__([child])
        from spark_rapids_tpu.expr.misc import reject_partition_aware
        reject_partition_aware(list(group_exprs) + list(result_exprs),
                               "aggregations")
        self.mode = mode
        child_schema = child.output_schema

        self._group_bound = [bind(_strip_alias(g), child_schema)
                             for g in group_exprs]
        for g in self._group_bound:
            if isinstance(g.dtype, T.ArrayType):
                raise ValueError("cannot group by an array column")
        self._group_names = [output_name(g) for g in group_exprs]
        self._result_raw = list(result_exprs)
        self._result_bound = [bind(r, child_schema) for r in self._result_raw]

        # collect distinct aggregate functions (structural identity)
        self._aggs: list[AggregateFunction] = []
        seen: dict[str, int] = {}
        for r in self._result_bound:
            for a in _collect_aggs(r):
                key = repr(a)
                if key not in seen:
                    seen[key] = len(self._aggs)
                    self._aggs.append(a)
        self._agg_index = seen
        # holistic aggregates (percentile) have NO mergeable
        # intermediate: the whole input must reduce in one pass, so a
        # partial/final split can never be planned over them
        self._holistic = any(getattr(a, "requires_complete", False)
                             for a in self._aggs)
        if self._holistic and mode == "partial":
            raise ValueError(
                "holistic aggregates (percentile) cannot run in partial "
                "mode; plan a complete aggregation")
        if self._holistic and any(
                op.startswith(("first", "last"))
                for a in self._aggs for op in a.update_ops):
            raise NotImplementedError(
                "percentile cannot be combined with first/last in one "
                "aggregation: the percentile value-sort would change "
                "which row first/last observe")

        # pre-projection layout: [group keys..., one col per DISTINCT
        # agg input] — p50(v) and p90(v) share one projected column
        # (also what lets multiple percentiles ride one value-sort)
        self._pre_exprs: list[Expression] = list(self._group_bound)
        self._agg_input_col: list[int | None] = []
        in_seen: dict[str, int] = {}
        for a in self._aggs:
            if a.input is None:
                self._agg_input_col.append(None)
                continue
            key = repr(a.input)
            if key not in in_seen:
                in_seen[key] = len(self._pre_exprs)
                self._pre_exprs.append(a.input)
            self._agg_input_col.append(in_seen[key])
        if not self._pre_exprs:
            # rows-only aggregation (e.g. bare COUNT(*)): a zero-column
            # batch would lose its row count, so project a dummy literal
            # (reference: JustRowsColumnarBatch, SpillableColumnarBatch.scala)
            from spark_rapids_tpu.expr.core import Literal
            self._pre_exprs.append(Literal(1, T.ByteType()))
        k = len(self._group_bound)
        self._pre_schema = T.Schema(
            [T.StructField(n, g.dtype, True)
             for n, g in zip(self._group_names, self._group_bound)]
            + [T.StructField(f"_agg_in_{i}", e.dtype, True)
               for i, e in enumerate(self._pre_exprs[k:])])

        # update specs + buffer layout
        self._update_specs: list[AggSpec] = []
        self._agg_offsets: list[list[int]] = []
        buf_fields = list(self._pre_schema.fields[:k])
        for a, ci in zip(self._aggs, self._agg_input_col):
            offs = []
            for op, it in zip(a.update_ops, a.intermediate_types()):
                offs.append(k + len(self._update_specs))
                self._update_specs.append(AggSpec(
                    op, ci if ci is not None else 0,
                    param=getattr(a, "q", None)))
                buf_fields.append(T.StructField(
                    f"_buf_{len(buf_fields) - k}", it, True))
            self._agg_offsets.append(offs)
        self._buffer_schema = T.Schema(buf_fields)

        # merge specs operate over buffer columns
        self._merge_specs: list[AggSpec] = []
        for a, offs in zip(self._aggs, self._agg_offsets):
            for op, off in zip(a.merge_ops, offs):
                self._merge_specs.append(AggSpec(op, off))

        # result projection over the buffer batch
        self._final_exprs = [self._to_buffer_space(r, b)
                             for r, b in zip(self._result_raw,
                                             self._result_bound)]
        self._output_schema = (
            self._buffer_schema if mode == "partial" else T.Schema(
                [T.StructField(output_name(r), b.dtype, True)
                 for r, b in zip(self._result_raw, self._final_exprs)]))

    # ------------------------------------------------------------------
    @classmethod
    def final_from_partial(cls, partial: "HashAggregateExec",
                           child: PlanNode) -> "HashAggregateExec":
        """Build the final-mode exec consuming ``partial``'s buffer output
        (typically through an exchange)."""
        self = object.__new__(cls)
        PlanNode.__init__(self, [child])
        self.mode = "final"
        for attr in ("_group_bound", "_group_names", "_result_raw",
                     "_result_bound", "_aggs", "_agg_index", "_holistic",
                     "_pre_exprs",
                     "_agg_input_col", "_pre_schema", "_update_specs",
                     "_agg_offsets", "_buffer_schema", "_merge_specs",
                     "_final_exprs"):
            setattr(self, attr, getattr(partial, attr))
        self._output_schema = T.Schema(
            [T.StructField(output_name(r), b.dtype, True)
             for r, b in zip(self._result_raw, self._final_exprs)])
        return self

    def _to_buffer_space(self, raw: Expression, bound: Expression) -> Expression:
        """Rewrite a bound result expression to evaluate over the buffer
        batch: aggs -> final_expr(offsets), group exprs -> key refs."""
        group_reprs = {repr(g): i for i, g in enumerate(self._group_bound)}

        def rewrite(node: Expression) -> Expression:
            if isinstance(node, AggregateFunction):
                i = self._agg_index[repr(node)]
                return self._aggs[i].final_expr(self._agg_offsets[i])
            r = repr(node)
            if r in group_reprs:
                i = group_reprs[r]
                f = self._buffer_schema.fields[i]
                return BoundReference(i, f.data_type, True, f.name)
            return node

        return _rewrite_topdown(bound, rewrite)

    # ------------------------------------------------------------------
    @property
    def output_schema(self) -> T.Schema:
        return self._output_schema

    @property
    def bound_exprs(self):
        return list(self._pre_exprs) + list(self._final_exprs)

    @property
    def output_batching(self):
        return RequireSingleBatch

    @property
    def children_coalesce_goal(self):
        # batch small scan output up to batchSizeBytes before aggregating
        # (fewer, larger segment-reduce dispatches; reference: the
        # aggregate's TargetSize child goal, GpuExec.scala:71-86).
        # TargetSize(0) resolves to spark.rapids.sql.batchSizeBytes at
        # planning.  Final mode reads shuffle output that the adaptive
        # reader already coalesced.
        if self.mode == "final":
            return [None]
        return [TargetSize(0)]

    def num_partitions(self, ctx: ExecCtx) -> int:
        # complete mode is a whole-input aggregation: collapse partitions
        # (partial/final run per partition; the exchange between them owns
        # cross-partition movement, as in Spark's planner).
        if self.mode == "complete":
            return 1
        return self.children[0].num_partitions(ctx)

    @property
    def output_ordering(self):
        """Group rows leave the segment machinery clustered by the key
        columns (sorted when the update sorted; in child arrangement
        when the presorted fast path kept it) — either way, equal keys
        are contiguous per batch."""
        k = len(self._group_bound)
        if not k:
            return None
        if self.mode == "partial":
            return list(self._pre_schema.names[:k])
        key_out: dict[int, str] = {}
        for raw, fe in zip(self._result_raw, self._final_exprs):
            fe = _strip_alias(fe)
            if isinstance(fe, BoundReference) and fe.index < k:
                key_out.setdefault(fe.index, output_name(raw))
        names = []
        for i in range(k):
            if i not in key_out:
                break
            names.append(key_out[i])
        return names or None

    def _child_presorted(self) -> bool:
        """True when every group key is a plain reference to a child
        column and the child's output_ordering already clusters those
        columns (as a prefix set) — the update's re-sort is then skipped
        (VERDICT r3 item 4: agg-over-agg re-sorted the inner
        aggregation's already-clustered output at every level)."""
        k = len(self._group_bound)
        if not k or self.mode == "final":
            return False
        ordering = self.children[0].output_ordering
        if not ordering or len(ordering) < k:
            return False
        child_names = self.children[0].output_schema.names
        # keys must match the child ordering prefix IN BOUND ORDER: a
        # set-match would keep the child's (permuted) arrangement while
        # output_ordering claims bound-key order, and a downstream
        # prefix consumer would then skip a sort it still needs
        for g, have in zip(self._group_bound, ordering):
            if not isinstance(g, BoundReference) \
                    or child_names[g.index] != have:
                return False
        return len({g.index for g in self._group_bound}) == k

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        child = self.children[0]
        if self.mode == "complete":
            from spark_rapids_tpu.exec.core import drain_partitions
            child_it = drain_partitions(ctx, child)
        else:
            child_it = child.partition_iter(ctx, pid)
        key_idx = list(range(len(self._group_bound)))
        if ctx.is_device:
            yield from self._run_device(ctx, child_it, key_idx)
        else:
            yield from self._run_host(child_it, key_idx)

    # -- device path (reference aggregate.scala:427-485 concat+merge loop) --
    #
    # Compilation discipline (XLA analog of the reference's zero-per-batch-
    # compilation hot loop, SURVEY §3.3): the per-batch update and the
    # n-way merge are each ONE jitted program; buffers are shrunk to
    # pow2 group-count buckets, so programs compile once per capacity
    # bucket, and the merge runs O(total/bound) times, not per batch.
    def _jit_fns(self):
        if not hasattr(self, "_jits"):
            key_idx = list(range(len(self._group_bound)))
            presorted = self._child_presorted() and not self._holistic

            def update(b):
                cols = [eval_device(e, b) for e in self._pre_exprs]
                pre = ColumnBatch(cols, b.num_rows, self._pre_schema)
                return _relabel_d(
                    sorted_group_by(pre, key_idx, self._update_specs,
                                    presorted=presorted),
                    self._buffer_schema)

            def merge(cat):
                return _relabel_d(
                    sorted_group_by(cat, key_idx, self._merge_specs),
                    self._buffer_schema)

            def final(run):
                cols = [eval_device(e, run) for e in self._final_exprs]
                return ColumnBatch(cols, run.num_rows, self._output_schema)

            import jax
            from spark_rapids_tpu.exec import compile_cache as cc
            key = cc.fragment_key(
                "agg", presorted, len(key_idx), tuple(self._pre_exprs),
                self._pre_schema, self._update_specs, self._merge_specs,
                self._buffer_schema, tuple(self._final_exprs),
                self._output_schema)
            # single atomic publication: concurrent partition workers must
            # never observe a partially-initialized triple (the cached
            # value is the complete immutable triple)
            self._jits = cc.get_or_build(key, lambda: (
                cc.instrument(jax.jit(update)),
                cc.instrument(jax.jit(merge)),
                cc.instrument(jax.jit(final))))
        return self._jits

    # pending partial buffers merge once their summed capacity crosses
    # this bound — peak concat storage stays ~2x the bound while the
    # n-way merge keeps the sort count at O(total/bound), not O(batches)
    _MERGE_PENDING_CAP = 1 << 23
    #: batches whose group counts sync to host in one stacked device_get
    _SYNC_CHUNK = 8

    def _run_device(self, ctx: ExecCtx, child_it, key_idx) \
            -> Iterator[ColumnBatch]:
        from spark_rapids_tpu.columnar.batch import round_capacity
        update_jit, merge_jit, final_jit = self._jit_fns()

        # Each incoming batch is reduced to its own group buffer and
        # SHRUNK to its group count; buffers then merge in one n-way
        # concat + segment-reduce.  The previous pairwise loop re-sorted
        # the whole running buffer per batch — k full sorts for k
        # batches — which dominated agg-heavy plans (q65's final
        # aggregates were ~5s each on SF1).  The reference's
        # concatenate-then-merge loop amortizes the same way
        # (aggregate.scala:427-485).
        if self._holistic:
            # no merge exists for holistic aggregates: concatenate the
            # raw input ONCE and reduce it in a single group-by pass
            # (Spark's ObjectHashAggregate similarly buffers per-group
            # raw values for Percentile)
            raw = list(child_it)
            if len(raw) > 1:
                child_it = [ctx.dispatch(dk.concat_batches, raw)]
            else:
                child_it = raw
        parts: list[ColumnBatch] = []
        total_cap = 0

        def merge_pending() -> None:
            nonlocal parts, total_cap
            if len(parts) <= 1:
                return
            # the concat is the path's peak allocation: run it under
            # dispatch so the DeviceSemaphore bounds occupancy and the
            # OOM-spill-retry hook covers it (review finding)
            cat = _relabel_d(ctx.dispatch(dk.concat_batches, parts),
                             self._buffer_schema)
            merged = ctx.dispatch(merge_jit, cat)
            ng = merged.host_num_rows()
            cap = round_capacity(max(int(ng), 1))
            merged = ctx.dispatch(dk.shrink_capacity, merged, cap)
            parts = [merged]
            total_cap = cap

        # Group-count syncs are CHUNKED: each host round trip over a
        # tunneled backend costs tens of ms of pure latency, so up to
        # _SYNC_CHUNK updated buffers are dispatched asynchronously and
        # their counts fetched in ONE device_get of a stacked vector
        # (one barrier per chunk, not per batch).  HBM backpressure:
        # a chunk holds at most _SYNC_CHUNK un-shrunk buffers.  Each
        # chunk entry retains its SOURCE batch (parked spillable, so it
        # pins no HBM): an OOM surfacing at the stacked sync — where
        # async backends report it — is recovered by re-running the
        # updates from the sources through the splitting retry scope,
        # and the cross-batch merge makes the extra partial buffers
        # semantically free.
        import jax as _jax
        import jax.numpy as _jnp
        from spark_rapids_tpu.memory.catalog import (SpillableColumnarBatch,
                                                     SpillPriority)

        def update_pairs(src) -> list:
            return ctx.dispatch_retry(update_jit, src, op="agg_update",
                                      pairs=True)

        def flush_chunk(chunk: list) -> None:
            nonlocal total_cap
            if not chunk:
                return

            def redo() -> None:
                new = []
                for src, part in chunk:
                    if src is None:     # final mode: no dispatch to redo
                        new.append((None, part))
                    else:
                        new.extend(update_pairs(src))
                chunk[:] = new

            def sync_counts():
                if len(chunk) == 1:
                    return [chunk[0][1].host_num_rows()]
                # enginelint: disable=RL003 (one stacked transfer for the whole chunk; this IS the batched sync)
                return list(_jax.device_get(ctx.dispatch(
                    _jnp.stack, [p.num_rows for _s, p in chunk])))

            ngs = ctx.retry_sync(sync_counts, redo=redo, op="agg_flush")
            for (src, part), ng in zip(chunk, ngs):
                ng = int(ng)
                if isinstance(src, SpillableColumnarBatch):
                    src.close()
                if ng == 0 and key_idx:
                    continue
                cap = round_capacity(max(ng, 1))
                part = ctx.dispatch(dk.shrink_capacity, part, cap)
                parts.append(part)
                total_cap += cap
                if total_cap >= self._MERGE_PENDING_CAP:
                    merge_pending()

        chunk: list = []
        for b in child_it:
            if self.mode == "final":
                chunk.append((None, _relabel_d(b, self._buffer_schema)))
            else:
                src = SpillableColumnarBatch(b, ctx.catalog,
                                             SpillPriority.READ_SHUFFLE)
                chunk.extend(update_pairs(src))
            if len(chunk) >= self._SYNC_CHUNK:
                flush_chunk(chunk)
                chunk = []
        flush_chunk(chunk)
        merge_pending()
        running = parts[0] if parts else None
        if running is None:
            if key_idx or self.mode == "partial":
                return  # no groups / nothing to emit
            # grand aggregate on empty input: default-values row
            # (reference aggregate.scala reduction default path :514+)
            from spark_rapids_tpu.exec.core import host_to_device
            empty = _empty_host(self._pre_schema)
            pre = host_to_device(empty)
            running = _relabel_d(
                sorted_group_by(pre, key_idx, self._update_specs),
                self._buffer_schema)
        if self.mode == "partial":
            yield running
        else:
            yield ctx.dispatch(final_jit, running)

    # -- host oracle path --------------------------------------------------
    def _run_host(self, child_it, key_idx) -> Iterator[HostBatch]:
        if self._holistic:
            # single-pass reduction over the concatenated raw input
            # (no mergeable intermediate exists)
            raw = list(child_it)
            hb = hk.host_concat(raw) if len(raw) > 1 else (
                raw[0] if raw else None)
            if hb is None:
                if key_idx:
                    return
                hb = _empty_host(self.children[0].output_schema)
            cols = [eval_host(e, hb) for e in self._pre_exprs]
            pre = HostBatch(cols, self._pre_schema)
            running = _relabel_h(
                hk.host_group_by(pre, key_idx, self._update_specs),
                self._buffer_schema)
            cols = [eval_host(e, running) for e in self._final_exprs]
            yield HostBatch(cols, self._output_schema)
            return
        parts: list[HostBatch] = []
        for b in child_it:
            if self.mode == "final":
                parts.append(_relabel_h(b, self._buffer_schema))
            else:
                cols = [eval_host(e, b) for e in self._pre_exprs]
                pre = HostBatch(cols, self._pre_schema)
                parts.append(_relabel_h(
                    hk.host_group_by(pre, key_idx, self._update_specs),
                    self._buffer_schema))
        if not parts:
            if key_idx or self.mode == "partial":
                return
            parts = [_relabel_h(
                hk.host_group_by(_empty_host(self._pre_schema), key_idx,
                                 self._update_specs), self._buffer_schema)]
        running = parts[0] if len(parts) == 1 else _relabel_h(
            hk.host_group_by(hk.host_concat(parts), key_idx,
                             self._merge_specs), self._buffer_schema)
        if self.mode == "partial":
            yield running
        else:
            cols = [eval_host(e, running) for e in self._final_exprs]
            yield HostBatch(cols, self._output_schema)

    def node_desc(self) -> str:
        return (f"HashAggregateExec[{self.mode}, keys={self._group_names}, "
                f"out={self._output_schema.names}]")


# ---------------------------------------------------------------------------

def _collect_aggs(e: Expression) -> list[AggregateFunction]:
    if isinstance(e, AggregateFunction):
        return [e]
    out: list[AggregateFunction] = []
    for c in e.children:
        out.extend(_collect_aggs(c))
    return out


def _rewrite_topdown(e: Expression, fn) -> Expression:
    new = fn(e)
    if new is not e:
        return new
    children = [_rewrite_topdown(c, fn) for c in e.children]
    if all(a is b for a, b in zip(children, e.children)):
        return e
    return e.with_new_children(children)


def _relabel_d(b: ColumnBatch, schema: T.Schema) -> ColumnBatch:
    from spark_rapids_tpu.columnar.column import DeviceColumn
    cols = [DeviceColumn(c.data, c.validity, f.data_type, c.lengths)
            for c, f in zip(b.columns, schema)]
    return ColumnBatch(cols, b.num_rows, schema)


def _relabel_h(b: HostBatch, schema: T.Schema) -> HostBatch:
    from spark_rapids_tpu.host.batch import HostColumn
    cols = [HostColumn(c.data, c.validity, f.data_type)
            for c, f in zip(b.columns, schema)]
    return HostBatch(cols, schema)


def _empty_host(schema: T.Schema) -> HostBatch:
    import numpy as np
    from spark_rapids_tpu.host.batch import HostColumn
    cols = []
    for f in schema:
        if isinstance(f.data_type, T.StringType):
            data = np.empty(0, dtype=object)
        else:
            data = np.zeros(0, dtype=f.data_type.np_dtype)
        cols.append(HostColumn(data, np.zeros(0, np.bool_), f.data_type))
    return HostBatch(cols, schema)

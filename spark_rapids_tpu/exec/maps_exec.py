"""Map decomposition: split map columns into array-column pairs.

Reference: the plugin runs GetMapValue/map_keys/map_values on device as
cuDF LIST-of-struct kernels (complexTypeExtractors.scala,
collectionOperations.scala).  This engine's device layout has no
two-buffer column, so the planner rewrites eligible plans to split each
map column at the scan boundary into two ordinary ARRAY columns — the
row's sorted keys and the aligned values — after which every existing
device kernel (filter/join/agg over extracted values) applies untouched
and the physical plan carries no MapType at all (plan/maps.py holds the
rewrite; explain shows the split exec instead of a GetMapValue
fallback).
"""
from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.host.batch import HostBatch, HostColumn

__all__ = ["MapDecomposeExec", "keys_name", "vals_name",
           "size_name", "decomposable", "hashed_decomposable",
           "key_hash64"]


def keys_name(map_col: str) -> str:
    return f"{map_col}__map_keys"


def vals_name(map_col: str) -> str:
    return f"{map_col}__map_vals"


def size_name(map_col: str) -> str:
    return f"{map_col}__map_size"


def decomposable(mt: T.DataType) -> bool:
    """Key AND value must be numeric/boolean: the split copies raw dict
    entries into typed array buffers, and only those python values ARE
    their storage encoding (dates/timestamps need the days/micros
    encodings, strings/nested have no device array element layout) —
    everything else keeps the raw host path."""
    if not isinstance(mt, T.MapType):
        return False
    return all((t.np_dtype is not None
                and not isinstance(t, (T.ArrayType, T.DateType,
                                       T.TimestampType)))
               for t in (mt.key_type, mt.value_type))


def _plain_value(t: T.DataType) -> bool:
    return (t.np_dtype is not None
            and not isinstance(t, (T.ArrayType, T.DateType,
                                   T.TimestampType, T.StringType)))


def hashed_decomposable(mt: T.DataType) -> bool:
    """STRING-key maps with numeric/boolean values decompose through a
    64-bit key hash: the keys array stores ``key_hash64(key)`` and the
    planner hashes each (literal) lookup key the same way, so
    ``m['weight']`` runs on device as an int64 MapLookup (reference
    runs GetMapValue on device for string keys too,
    complexTypeExtractors.scala).  ``map_keys`` would expose hashes,
    so such uses keep the raw host path (plan/maps.py tagging)."""
    if not isinstance(mt, T.MapType):
        return False
    return isinstance(mt.key_type, T.StringType) \
        and _plain_value(mt.value_type)


_HASH_CACHE: dict = {}


def key_hash64(s: str) -> int:
    """Stable 64-bit key hash (blake2b-8).  Distinct keys colliding
    within one map row would make the binary-search lookup ambiguous;
    the decompose exec detects that (probability ~2^-64 per pair) and
    refuses rather than answer wrong."""
    h = _HASH_CACHE.get(s)
    if h is None:
        import hashlib
        h = int.from_bytes(
            hashlib.blake2b(s.encode("utf-8"), digest_size=8).digest(),
            "little", signed=True)
        if len(_HASH_CACHE) < (1 << 20):
            _HASH_CACHE[s] = h
    return h


class MapDecomposeExec(PlanNode):
    """Replace each named map column with (sorted keys array, aligned
    values array).  Runs on the host right above the scan — the input
    still carries maps, so the tagger keeps THIS node host-side, and
    everything above it is map-free and device-eligible."""

    combines_batches = False

    def __init__(self, child: PlanNode, map_cols: Sequence[str]):
        super().__init__([child])
        self._maps = list(map_cols)
        fields = []
        for f in child.output_schema:
            if f.name in self._maps:
                mt = f.data_type
                assert decomposable(mt) or hashed_decomposable(mt), mt
                kt = T.LongType() if isinstance(mt.key_type, T.StringType) \
                    else mt.key_type
                fields.append(T.StructField(keys_name(f.name),
                                            T.ArrayType(kt), True))
                fields.append(T.StructField(vals_name(f.name),
                                            T.ArrayType(mt.value_type), True))
                # entries whose VALUE is null are dropped from the
                # arrays (device arrays have no element nulls; m[k] of
                # a null-valued entry and of a missing key are both
                # null, so lookups stay exact) — size(m) must still
                # count them, so the true entry count rides its own
                # column (-1 for null maps, the legacy sizeOfNull
                # convention Size() itself emits)
                fields.append(T.StructField(size_name(f.name),
                                            T.IntegerType(), False))
            else:
                fields.append(f)
        self._schema = T.Schema(fields)

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        assert not ctx.is_device, \
            "MapDecomposeExec reads raw maps: host-side only"
        for hb in self.children[0].partition_iter(ctx, pid):
            cols = []
            for f, c in zip(hb.schema, hb.columns):
                if f.name not in self._maps:
                    cols.append(c)
                    continue
                n = len(c.data)
                hashed = isinstance(f.data_type.key_type, T.StringType)
                keys = np.empty(n, dtype=object)
                vals = np.empty(n, dtype=object)
                sizes = np.full(n, -1, dtype=np.int32)
                for i in range(n):
                    if c.validity[i]:
                        d = c.data[i]
                        if hashed:
                            # collisions checked over ALL keys (a
                            # dropped null-valued entry colliding with
                            # a kept one would make its lookup return
                            # the kept value instead of null)
                            all_h = {key_hash64(k) for k in d}
                            if len(all_h) != len(d):
                                raise RuntimeError(
                                    "map key hash collision in "
                                    f"'{f.name}' — disable "
                                    "spark.rapids.sql.decomposeMaps")
                            items = sorted((key_hash64(k), v)
                                           for k, v in d.items()
                                           if v is not None)
                        else:
                            items = sorted((k, v) for k, v in d.items()
                                           if v is not None)
                        keys[i] = [k for k, _ in items]
                        vals[i] = [v for _, v in items]
                        sizes[i] = len(d)
                    else:
                        keys[i] = None
                        vals[i] = None
                validity = np.asarray(c.validity, np.bool_)
                mt = f.data_type
                kt = T.LongType() if hashed else mt.key_type
                cols.append(HostColumn(keys, validity.copy(),
                                       T.ArrayType(kt)))
                cols.append(HostColumn(vals, validity.copy(),
                                       T.ArrayType(mt.value_type)))
                cols.append(HostColumn(sizes, np.ones(n, np.bool_),
                                       T.IntegerType()))
            yield HostBatch(cols, self._schema)

    def node_desc(self) -> str:
        return f"MapDecomposeExec[{self._maps}]"

"""Expand exec: N projections per input batch (rollup / cube / grouping
sets).

Reference: GpuExpandExec (GpuExpandExec.scala:67) — evaluates a list of
projection lists against every input batch, emitting each input row once
per projection (Spark uses this to implement ROLLUP/CUBE/GROUPING SETS,
with nulled-out grouping columns plus a ``spark_grouping_id`` literal per
projection).  TPU design: one jitted program per projection, each
emitted as its own output batch (same capacity, static shapes) so
downstream aggregation keeps canonical capacities and peak device memory
stays at one projection's worth regardless of grouping-set count.
"""
from __future__ import annotations

from typing import Iterator, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.expr.core import (Expression, bind, eval_device,
                                        eval_host, output_name)
from spark_rapids_tpu.host.batch import HostBatch

__all__ = ["ExpandExec"]


class ExpandExec(PlanNode):
    """Evaluate ``projections`` (a list of same-arity expression lists)
    per input batch; output = one batch per (input batch, projection)."""

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 child: PlanNode):
        super().__init__([child])
        assert projections, "expand with no projections"
        arity = len(projections[0])
        assert all(len(p) == arity for p in projections), \
            "expand projections must have equal arity"
        cs = child.output_schema
        self._bound = [[bind(e, cs) for e in proj] for proj in projections]
        names = [output_name(e) for e in projections[0]]
        fields = []
        for i, name in enumerate(names):
            dts = {type(p[i].dtype) for p in self._bound}
            assert len(dts) == 1, \
                f"expand column {name} has mixed types across projections"
            fields.append(T.StructField(name, self._bound[0][i].dtype, True))
        self._schema = T.Schema(fields)

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def bound_exprs(self):
        return [e for proj in self._bound for e in proj]

    def _jit_fns(self):
        # one program PER projection, emitted one at a time (reference
        # GpuExpandExec emits per projection) so peak device memory is one
        # output batch, not len(projections) of them — a 4-key cube has 16
        if not hasattr(self, "_expand_jits"):
            from spark_rapids_tpu.exec import compile_cache as cc

            def make(proj):
                def one(b):
                    cols = [eval_device(e, b) for e in proj]
                    return ColumnBatch(cols, b.num_rows, self._schema)
                return cc.shared_jit(
                    cc.fragment_key("expand", tuple(proj), self._schema,
                                    self.children[0].output_schema),
                    one)

            self._expand_jits = [make(p) for p in self._bound]
        return self._expand_jits

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        child_it = self.children[0].partition_iter(ctx, pid)
        if ctx.is_device:
            fns = self._jit_fns()
            for b in child_it:
                for fn in fns:
                    yield ctx.dispatch(fn, b)
        else:
            for b in child_it:
                for proj in self._bound:
                    cols = [eval_host(e, b) for e in proj]
                    yield HostBatch(cols, self._schema)

    def node_desc(self) -> str:
        return (f"ExpandExec[{len(self._bound)} projections, "
                f"{self._schema.names}]")

"""Sort and coalesce execs.

Reference: GpuSortExec.scala:51 (cuDF ``Table.orderBy`` per batch;
``RequireSingleBatch`` goal for a total sort), GpuCoalesceBatches.scala
(AbstractGpuCoalesceIterator :132 — concatenates small batches up to a
``CoalesceGoal``).
"""
from __future__ import annotations

from typing import Iterator, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import (CoalesceGoal, ExecCtx, PlanNode,
                                        RequireSingleBatch, RequireSingleBatchT,
                                        TargetSize)
from spark_rapids_tpu.expr.core import Expression, bind
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.ops import host_kernels as hk
from spark_rapids_tpu.ops import kernels as dk
from spark_rapids_tpu.ops.sort import SortOrder, sort_batch

__all__ = ["SortExec", "CoalesceBatchesExec", "resolve_orders"]


def resolve_orders(orders: Sequence, schema: T.Schema) -> list[SortOrder]:
    """Accept SortOrder or (expr|name, ascending[, nulls_first]) tuples and
    resolve to column-index SortOrders. Sort keys must be plain columns
    (pre-project computed keys, as Spark's planner does)."""
    out: list[SortOrder] = []
    for o in orders:
        if isinstance(o, SortOrder):
            out.append(o)
            continue
        name, *rest = o if isinstance(o, tuple) else (o,)
        if isinstance(name, Expression):
            b = bind(name, schema)
            from spark_rapids_tpu.expr.core import BoundReference
            assert isinstance(b, BoundReference), \
                "sort keys must be column references; project first"
            idx = b.index
        else:
            idx = schema.index_of(name)
        asc = rest[0] if rest else True
        nf = rest[1] if len(rest) > 1 else None
        if isinstance(schema.fields[idx].data_type, T.ArrayType):
            raise ValueError("cannot sort by an array column")
        out.append(SortOrder(idx, asc, nf))
    return out


class SortExec(PlanNode):
    """Sort each partition. With ``global_sort`` the input is first
    coalesced to a single batch per partition (reference: GpuSortExec's
    RequireSingleBatch child goal for total ordering; cross-partition
    ordering is the exchange's job via range partitioning)."""

    def __init__(self, orders: Sequence, child: PlanNode,
                 global_sort: bool = False):
        super().__init__([child])
        self._orders = resolve_orders(orders, child.output_schema)
        self._global = global_sort

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    @property
    def children_coalesce_goal(self) -> list[CoalesceGoal | None]:
        return [RequireSingleBatch if self._global else None]

    @property
    def output_ordering(self):
        """Each emitted batch is lexicographically sorted by the sort
        keys — equal keys are contiguous regardless of direction."""
        return [self.output_schema.names[o.child_index]
                for o in self._orders]

    def num_partitions(self, ctx: ExecCtx) -> int:
        # a global sort is a TOTAL order: the output is one partition.
        # Sorting each input partition independently and letting a limit
        # read them in partition order silently breaks the order across
        # partitions (caught by q65/q68/q73/q79 at SF1: a sort below a
        # join kept the join's partitioning).  The reference establishes
        # total order via a range exchange + per-partition sort; here the
        # final sort collapses partitions (range-partitioned distributed
        # sort remains available explicitly via RangePartitioning).
        if self._global:
            return 1
        return self.children[0].num_partitions(ctx)

    def _jit_fn(self):
        if not hasattr(self, "_sort_jit"):
            from spark_rapids_tpu.exec import compile_cache as cc
            self._sort_jit = cc.shared_jit(
                cc.fragment_key("sort", tuple(self._orders),
                                self.children[0].output_schema),
                lambda b: sort_batch(b, self._orders))
        return self._sort_jit

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        from spark_rapids_tpu.exec.core import drain_partitions
        child = self.children[0]
        if self._global:
            # concurrent drain + spillable parking, not a serial loop
            # (review finding: completed partitions must be able to
            # spill while later ones are still producing)
            batches = list(drain_partitions(ctx, child))
        else:
            batches = list(child.partition_iter(ctx, pid))
        if not batches:
            return
        if ctx.is_device:
            b = batches[0] if len(batches) == 1 \
                else ctx.dispatch(dk.concat_batches, batches)
            # withRetryNoSplit (reference GpuSortExec): a sort's output
            # is a TOTAL order over its input — emitting independently
            # sorted halves would break it, so on OOM this scope only
            # spills and retries whole (no merge kernel exists to
            # recombine split outputs; see ops/sort.py)
            yield ctx.dispatch_retry(self._jit_fn(), b, split=False,
                                     op="sort")[0]
        else:
            b = batches[0] if len(batches) == 1 else hk.host_concat(batches)
            yield hk.host_sort(b, self._orders)

    def node_desc(self) -> str:
        return f"SortExec[{self._orders}]"


class CoalesceBatchesExec(PlanNode):
    """Concatenate small batches up to the goal (GpuCoalesceBatches)."""

    def __init__(self, goal: CoalesceGoal, child: PlanNode):
        super().__init__([child])
        self._goal = goal

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    @property
    def output_batching(self) -> CoalesceGoal:
        return self._goal

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        child_it = self.children[0].partition_iter(ctx, pid)
        if isinstance(self._goal, RequireSingleBatchT):
            batches = list(child_it)
            if not batches:
                return
            if len(batches) == 1:
                yield self._maybe_shrink(ctx, batches[0])
            elif ctx.is_device:
                yield self._maybe_shrink(ctx, dk.concat_batches(batches))
            else:
                yield hk.host_concat(batches)
            return
        assert isinstance(self._goal, TargetSize)
        target = self._goal.size
        pending: list = []
        pending_bytes = 0
        for b in child_it:
            sz = b.device_size_bytes() if ctx.is_device else _host_bytes(b)
            if pending and pending_bytes + sz > target:
                yield self._flush(ctx, pending)
                pending, pending_bytes = [], 0
            pending.append(b)
            pending_bytes += sz
        if pending:
            yield self._flush(ctx, pending)

    def _upstream_can_shrink(self) -> bool:
        """True when an operator below (this side of any exchange) can
        leave batches much emptier than their capacity — filters,
        limits, residual-condition joins.  Dense pipelines skip the
        per-batch row-count sync entirely: a blocking host round trip
        per coalesced batch would serialize the async dispatch pipeline
        for zero benefit (review finding)."""
        if not hasattr(self, "_shrink_possible"):
            from spark_rapids_tpu.exec.basic import (FilterExec,
                                                     GlobalLimitExec,
                                                     LocalLimitExec)
            from spark_rapids_tpu.exec.exchange import (
                AdaptiveShuffleReaderExec, ShuffleExchangeExec)
            from spark_rapids_tpu.exec.joins import JoinExec
            found = False

            def walk(n):
                nonlocal found
                if found or isinstance(n, (ShuffleExchangeExec,
                                           AdaptiveShuffleReaderExec)):
                    # exchange slices are already right-sized
                    return
                if isinstance(n, (FilterExec, LocalLimitExec,
                                  GlobalLimitExec)) or \
                        (isinstance(n, JoinExec)
                         and n._condition is not None):
                    found = True
                    return
                for c in n.children:
                    walk(c)

            walk(self.children[0])
            self._shrink_possible = found
        return self._shrink_possible

    def _maybe_shrink(self, ctx: ExecCtx, b):
        """Repack a sparse batch (selective upstream filter) to its
        pow2 row bucket: every downstream sort/segment program runs at
        batch CAPACITY, so a 4M-capacity batch holding 400k filtered
        rows would pay 8x its useful sort work (TPC-DS q28's
        count-distinct branches).  Costs one row-count sync + a slice
        program; only probed when the upstream subtree can actually
        leave batches sparse."""
        if not ctx.is_device or not self._upstream_can_shrink():
            return b
        from spark_rapids_tpu.columnar.batch import round_capacity
        n = b.host_num_rows()
        cap = round_capacity(max(n, 1))
        if cap > b.capacity // 2:
            return b
        return ctx.dispatch(dk.shrink_capacity, b, cap)

    def _flush(self, ctx: ExecCtx, batches: list):
        if len(batches) == 1:
            return self._maybe_shrink(ctx, batches[0])
        out = dk.concat_batches(batches) if ctx.is_device \
            else hk.host_concat(batches)
        return self._maybe_shrink(ctx, out)


def _host_bytes(b: HostBatch) -> int:
    total = 0
    for c in b.columns:
        if c.data.dtype == object:
            total += sum(len(x) for x in c.data if x is not None) + len(c.data)
        else:
            total += c.data.nbytes
        total += c.validity.nbytes
    return total

"""Process-wide result/fragment cache: the serving tier's reuse plane.

The reference amortizes repeated work inside ONE query through
ReuseExchange / ReuseSubquery and shared broadcast build sides
(GpuTransitionOverrides); a serving tier answering "heavy traffic from
millions of users" (ROADMAP north star) needs the cross-QUERY analog:
identical or overlapping queries arriving concurrently or back to back
must not recompute everything from the parquet files up.

Two entry kinds live in one LRU, both keyed so a hit is provably the
same computation:

* **result** — the full row set of one ``collect``.  Key =
  ``fragment_key("result", <structural plan part>, backend)`` (the
  compile cache's canonical fingerprint machinery, exec/compile_cache)
  x ``recovery.conf_fingerprint(conf)`` (results are only deterministic
  under the exact conf they were computed with) x the **input
  snapshot**: every leaf scan's ``FileScanExec.snapshot_fingerprint()``
  — (path, size, mtime_ns) per file — so mutating an input invalidates
  instead of serving stale rows.  Rows are stored as a pickled blob
  with a CRC32 verified on every hit (the ``cache.result.corrupt``
  fault point poisons the blob to prove the verify-drop-recompute
  path); a hit serves rows without minting an ExecCtx — zero executor
  dispatches, zero compiles.

* **fragment** — a shared scan's materialized device batches
  (io/scan.py ``share_output``), routed here instead of the per-query
  ``ExecCtx.cached`` so CONCURRENT queries over the same table at the
  same snapshot share one host-read + pack.  Entries are
  consumer-counted like the PR 2 parked entries: a consumer mid-drain
  pins its entry against eviction; an idle entry is plain LRU weight.

Plans whose identity cannot be proven are never cached: a leaf that is
not a ``FileScanExec`` has no snapshot, and a fingerprint carrying an
opaque-state serial (a UDF closure, slotted native state) is unique by
construction — ``result_key`` returns None and the query runs exactly
as before.

Memory: the cache is bounded by ``spark.rapids.sql.resultCache.maxBytes``
(LRU), and it registers with the PR 11 memory governor as the LOWEST
priority occupant — unpinned, rebuildable — so sustained device
pressure evicts cache entries before any query is load-shed and
``reclaim`` drops fragments before wounding a peer query's working set
(memory/governor.py ``register_cache``).

Single-flight: concurrent identical queries coalesce onto one in-flight
computation.  The wait is lifecycle-integrated — a waiter's
cancel/deadline aborts the WAIT (its own ``QueryLifecycle.check``),
never the computation the owner query owns; when the owner fails, a
waiter takes over and computes (its own admission, its own lifecycle).

Counters (obs/registry.py): ``result_cache_hits`` / ``_misses`` /
``_corrupt`` / ``_evictions`` / ``_coalesced`` /
``result_cache_fragment_hits`` / ``_fragment_misses`` plus the
``result_cache`` pull source (entries/bytes gauges).
"""
from __future__ import annotations

import os
import pickle
import threading
import zlib

from spark_rapids_tpu.conf import bool_conf, int_conf
from spark_rapids_tpu.exec.compile_cache import fingerprint, fragment_key
from spark_rapids_tpu.exec.recovery import conf_fingerprint
from spark_rapids_tpu.obs.registry import get_registry

__all__ = ["ResultCache", "get_result_cache", "maybe_cache",
           "invalidate_output_paths",
           "RESULT_CACHE_ENABLED", "RESULT_CACHE_MAX_BYTES"]

RESULT_CACHE_ENABLED = bool_conf(
    "spark.rapids.sql.resultCache.enabled", True,
    "Serve a repeated identical query (same structural plan, same "
    "effective conf, same input snapshot — file paths/sizes/mtimes) "
    "from the process-wide result cache instead of re-executing, and "
    "share scan materializations across concurrent queries at the same "
    "snapshot. Hits are CRC-verified; mutating any input file or any "
    "conf forces a full recompute. Entries are the memory governor's "
    "first eviction victims, before any query is shed. false restores "
    "execute-every-time behavior byte for byte.")

RESULT_CACHE_MAX_BYTES = int_conf(
    "spark.rapids.sql.resultCache.maxBytes", 256 << 20,
    "Upper bound on bytes the result/fragment cache may hold (LRU "
    "eviction; result entries count their pickled blob, fragment "
    "entries their device batch bytes). A single result larger than "
    "this is returned to its caller but never cached.")

#: fingerprint substrings that mean "state we could not canonicalize":
#: the compile cache poisons such state with a unique serial, so a key
#: built from it can never legitimately hit — refuse to cache instead
#: of filling the LRU with dead entries
_POISON = ("<opaque:", "<slots:", "<deep:")


def _plan_part(plan):
    """Structural identity of a logical plan: scans by their stable
    ``scan_fingerprint`` (NOT their mutable exec-node state — bucket
    caches and skip counters change across runs), every other node by
    class + canonicalized non-child attributes + recursed children."""
    from spark_rapids_tpu.plan import logical as L
    if isinstance(plan, L.Scan):
        return ("scan", plan.exec_node.scan_fingerprint())
    attrs = {k: v for k, v in vars(plan).items()
             if not isinstance(v, L.LogicalPlan)}
    return (type(plan).__name__, fingerprint(attrs),
            tuple(_plan_part(c) for c in plan.children))


def plan_snapshot(logical):
    """The input snapshot of a logical plan: every leaf scan's
    ``snapshot_fingerprint()``, or None when any leaf is not a
    file-backed scan (in-memory/local data has no provable snapshot
    identity) or a file vanished mid-key."""
    from spark_rapids_tpu.io.scan import FileScanExec
    from spark_rapids_tpu.plan import logical as L
    snaps = []

    def walk(p) -> bool:
        if isinstance(p, L.Scan):
            node = p.exec_node
            if not isinstance(node, FileScanExec):
                return False
            try:
                snaps.append(node.snapshot_fingerprint())
            except OSError:
                return False
            return True
        kids = p.children
        if not kids:
            return False
        return all(walk(c) for c in kids)

    if not walk(logical) or not snaps:
        return None
    return tuple(snaps)


class _Entry:
    __slots__ = ("key", "kind", "blob", "crc", "value", "nbytes",
                 "consumers")

    def __init__(self, key, kind: str, nbytes: int, blob: bytes = b"",
                 crc: int = 0, value=None):
        self.key = key
        self.kind = kind          # "result" | "fragment"
        self.blob = blob
        self.crc = crc
        self.value = value
        self.nbytes = nbytes
        self.consumers = 0        # active fragment drains (pin vs evict)


class ResultCache:
    """Bounded LRU of results and scan fragments with single-flight
    computation.  Thread-safe; every blocking wait is either
    lifecycle-sliced (cancel/deadline abort the wait) or bounded."""

    def __init__(self, max_bytes: int = RESULT_CACHE_MAX_BYTES.default):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: "dict[tuple, _Entry]" = {}   # insertion order = LRU
        self._inflight: "dict[tuple, threading.Event]" = {}
        self._bytes = 0
        get_registry().register_source("result_cache", self._source)

    # -- keys --------------------------------------------------------------

    def result_key(self, logical, backend: str, conf):
        """(plan fp, conf fp, snapshot) for one collect, or None when
        the query's identity cannot be proven (non-file leaves, opaque
        plan state) and it must execute normally."""
        snap = plan_snapshot(logical)
        if snap is None:
            return None
        part = _plan_part(logical)
        fp = fingerprint(part, backend)
        if any(m in fp for m in _POISON):
            return None
        return (fragment_key("result", part, backend),
                conf_fingerprint(conf), snap)

    # -- whole-query results -----------------------------------------------

    def get_or_compute(self, key, compute, lifecycle=None, faults=None):
        """Serve ``key`` from cache, or coalesce onto / become the one
        in-flight computation.  ``compute`` runs the full admission +
        execution path; a waiter whose owner fails takes over with its
        own ``compute`` (never inheriting the owner's failure)."""
        reg = get_registry()
        while True:
            owner = False
            blob = None
            with self._lock:
                e = self._entries.get(key)
                if e is not None:
                    if faults is not None:
                        act = faults.check("cache.result.corrupt",
                                           kind=e.kind)
                        if act is not None and e.blob:
                            # flip one seeded byte so the CRC verify
                            # below fails exactly like real corruption
                            poisoned = bytearray(e.blob)
                            poisoned[act.rng.randrange(
                                len(poisoned))] ^= 0x40
                            e.blob = bytes(poisoned)
                    if zlib.crc32(e.blob) != e.crc:
                        reg.inc("result_cache_corrupt")
                        self._drop_locked(key)
                        e = None
                    else:
                        self._touch_locked(key)
                        blob = e.blob
                if e is None:
                    ev = self._inflight.get(key)
                    if ev is None:
                        ev = self._inflight[key] = threading.Event()
                        owner = True
            if blob is not None:
                if lifecycle is not None:
                    lifecycle.check()
                reg.inc("result_cache_hits")
                return pickle.loads(blob)
            if owner:
                try:
                    rows = compute()
                except BaseException:
                    with self._lock:
                        self._inflight.pop(key, None)
                    ev.set()
                    raise
                out = pickle.dumps(rows, protocol=4)
                with self._lock:
                    self._store_locked(_Entry(key, "result", len(out),
                                              blob=out,
                                              crc=zlib.crc32(out)))
                    self._inflight.pop(key, None)
                ev.set()
                reg.inc("result_cache_misses")
                return rows
            # coalesced waiter: wait on the owner's event in slices so
            # OUR cancel/deadline aborts the wait — never the owner's
            # computation, which other queries may also be waiting on
            reg.inc("result_cache_coalesced")
            if lifecycle is not None:
                lifecycle.start()   # the wait IS this query's run
                while not ev.wait(0.05):
                    lifecycle.check()
            else:
                ev.wait()
            # loop: entry present -> served as a hit; owner failed ->
            # this waiter becomes the owner and computes for itself

    # -- shared scan fragments ---------------------------------------------

    def fragment_entry(self, key, builder, lifecycle=None) -> _Entry:
        """Single-flight materialization of a shared scan partition.
        Returns the entry with its consumer count already incremented;
        the caller MUST pair it with :meth:`fragment_release` after
        draining (a consumed entry is pinned against eviction, an idle
        one is plain LRU weight — the PR 2 consumer-count discipline,
        process-wide)."""
        reg = get_registry()
        while True:
            owner = False
            with self._lock:
                e = self._entries.get(key)
                if e is not None:
                    e.consumers += 1
                    self._touch_locked(key)
                else:
                    ev = self._inflight.get(key)
                    if ev is None:
                        ev = self._inflight[key] = threading.Event()
                        owner = True
            if e is not None:
                reg.inc("result_cache_fragment_hits")
                return e
            if owner:
                try:
                    val = builder()
                except BaseException:
                    with self._lock:
                        self._inflight.pop(key, None)
                    ev.set()
                    raise
                nbytes = 0
                for b in val:
                    sz = getattr(b, "device_size_bytes", None)
                    if sz is not None:
                        nbytes += sz()
                e = _Entry(key, "fragment", nbytes, value=val)
                with self._lock:
                    self._store_locked(e)
                    e.consumers += 1
                    self._inflight.pop(key, None)
                ev.set()
                reg.inc("result_cache_fragment_misses")
                return e
            if lifecycle is not None:
                while not ev.wait(0.05):
                    lifecycle.check()
            else:
                ev.wait()

    def fragment_release(self, entry: _Entry) -> None:
        with self._lock:
            if entry.consumers > 0:
                entry.consumers -= 1

    # -- memory ------------------------------------------------------------

    def evict(self, need_bytes: "int | None" = None,
              kind: "str | None" = None) -> int:
        """Drop idle entries, oldest first, until ``need_bytes`` are
        freed (None = drop everything idle).  ``kind`` restricts the
        sweep ("fragment" = device batches only — the governor's
        reclaim path, which needs HBM bytes, not host pickle blobs).
        The governor's pressure and reclaim paths call this BEFORE
        shedding or wounding any query — cache is the lowest-priority
        occupant by design."""
        reg = get_registry()
        freed = 0
        with self._lock:
            for key in list(self._entries):
                if need_bytes is not None and freed >= need_bytes:
                    break
                e = self._entries[key]
                if e.consumers > 0 or (kind is not None and e.kind != kind):
                    continue
                freed += e.nbytes
                self._drop_locked(key)
                reg.inc("result_cache_evictions")
        return freed

    def cached_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def device_bytes(self) -> int:
        """Bytes of DEVICE memory the cache holds (fragment entries
        only — result blobs are host pickles and never relieve HBM)."""
        with self._lock:
            return sum(e.nbytes for e in self._entries.values()
                       if e.kind == "fragment")

    def clear(self) -> None:
        """Test hook: drop every entry regardless of consumers."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def invalidate_paths(self, root: str) -> int:
        """Drop every entry whose key references a file under ``root``
        (a committed write job just replaced files there).  Input
        snapshots fingerprint (path, size, mtime_ns) — sufficient for
        external edits, but a commit's atomic renames can land inside
        the snapshot's mtime granularity, so the write plane invalidates
        explicitly.  Keys are nested tuples whose scan components carry
        absolute file paths; any string component under ``root`` marks
        the entry stale (both result keys, via their plan snapshot, and
        fragment keys, via the scan snapshot)."""
        root = os.path.abspath(root)
        prefix = root + os.sep

        def touches(obj) -> bool:
            if isinstance(obj, str):
                if obj == root or obj.startswith(prefix):
                    return True
                if os.sep in obj:  # relative scan path: resolve first
                    a = os.path.abspath(obj)
                    return a == root or a.startswith(prefix)
                return False
            if isinstance(obj, tuple):
                return any(touches(x) for x in obj)
            return False

        with self._lock:
            stale = [k for k in self._entries if touches(k)]
            for k in stale:
                self._drop_locked(k)
        if stale:
            get_registry().inc("result_cache_invalidated", len(stale))
        return len(stale)

    # -- internals (all under self._lock) ----------------------------------

    def _store_locked(self, e: _Entry) -> None:
        if e.nbytes > self.max_bytes:
            return      # serve the caller, never cache the oversized
        self._drop_locked(e.key)
        reg = get_registry()
        for key in list(self._entries):
            if self._bytes + e.nbytes <= self.max_bytes:
                break
            old = self._entries[key]
            if old.consumers > 0:
                continue
            self._drop_locked(key)
            reg.inc("result_cache_evictions")
        if self._bytes + e.nbytes > self.max_bytes:
            return      # everything resident is mid-drain; skip caching
        self._entries[e.key] = e
        self._bytes += e.nbytes

    def _drop_locked(self, key) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= e.nbytes

    def _touch_locked(self, key) -> None:
        self._entries[key] = self._entries.pop(key)

    def _source(self) -> dict:
        with self._lock:
            frags = [e for e in self._entries.values()
                     if e.kind == "fragment"]
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "fragment_entries": len(frags),
                "fragment_bytes": sum(e.nbytes for e in frags),
            }


_CACHE: "ResultCache | None" = None
_CACHE_LOCK = threading.Lock()


def get_result_cache() -> ResultCache:
    """The process-wide cache singleton, governor-wired on first use."""
    global _CACHE
    with _CACHE_LOCK:
        if _CACHE is None:
            _CACHE = ResultCache()
            from spark_rapids_tpu.memory.governor import get_governor
            get_governor().register_cache(_CACHE)
        return _CACHE


def maybe_cache(conf) -> "ResultCache | None":
    """The singleton when ``spark.rapids.sql.resultCache.enabled``,
    else None — every call site degrades to today's behavior on None."""
    settings = getattr(conf, "settings", None) or {}
    if not RESULT_CACHE_ENABLED.get(settings):
        return None
    cache = get_result_cache()
    cache.max_bytes = RESULT_CACHE_MAX_BYTES.get(settings)
    return cache


def invalidate_output_paths(path: str) -> int:
    """Write-plane hook: after a job commit replaces files under
    ``path``, drop every cached entry that scanned them.  A no-op when
    the cache was never instantiated (nothing can be stale)."""
    with _CACHE_LOCK:
        cache = _CACHE
    if cache is None:
        return 0
    return cache.invalidate_paths(path)

"""Lineage-based stage recovery: recompute lost map outputs in place.

Reference mapping (SURVEY §2.6): when a reduce task hits a terminal
fetch failure, Spark raises FetchFailedException carrying (shuffleId,
mapId) and the DAGScheduler resubmits the lost map stage — the lineage
recomputation model of RDDs (Zaharia et al., NSDI 2012).  The plugin
inherits that machinery (RapidsShuffleIterator surfaces transport
failures as FetchFailed); this standalone engine has no DAGScheduler,
so the equivalent loop lives here:

1. every ShuffleExchangeExec registers a :class:`ShuffleLineage` when
   it materializes — which child partition produced each map batch,
   whether the tiny-input coalesce applied, and a conf fingerprint
   binding the recorded lineage to the settings it ran under;
2. a reduce pull runs inside :func:`recovering_fetch`; a terminal
   ``MapOutputLostError`` (dead peer, corrupt spill read-back, slot
   invalidated mid-pull) names exactly the lost ``(shuffle_id,
   map_id)`` outputs;
3. recovery invalidates those outputs (bumping their epochs so a
   straggling write from the dead attempt is discarded), re-executes
   ONLY the child partitions that produced them, rewrites the outputs
   tagged with the new epochs, and resumes the pull where it stopped —
   nothing already delivered is re-fetched;
4. a per-stage attempt budget
   (``spark.rapids.shuffle.recovery.maxStageAttempts``) bounds the
   loop: outputs that keep dying surface ``StageRecoveryExhausted``
   instead of recomputing forever.

This is layer 3 of the fault-tolerance ladder (docs/tuning-guide.md
"Fault tolerance"): transient transport failures never get here
(shuffle/retry.py resumes them), OOMs never get here (memory/retry.py
splits them); only confirmed DATA LOSS drives recomputation.

Mesh-region programs (exec/mesh_region.py) recover at a coarser grain
than this per-map-output loop: a device slice lost mid-program takes
every op fused into the region with it (joins and windows included),
so the region re-executes whole from its host-cached leaf and build
batches and counts ONE ``stage_recompute`` regardless of how many ops
the program absorbed.  Chained regions re-shard from the upstream
region's host fallback, so a loss never cascades past one region.
"""
from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass
from typing import Any, Iterator

from spark_rapids_tpu.conf import ConfEntry, register, _bool
from spark_rapids_tpu.shuffle.errors import (MapOutputLostError,
                                             StageRecoveryExhausted)

__all__ = ["ShuffleLineage", "recovering_fetch", "conf_fingerprint",
           "StageRecoveryExhausted"]

RECOVERY_ENABLED = register(ConfEntry(
    "spark.rapids.shuffle.recovery.enabled", True,
    "Recompute lost map outputs from lineage instead of failing the "
    "query: a terminal shuffle-fetch loss (dead peer, corrupt spill "
    "read-back) invalidates exactly the lost (shuffle, map) outputs, "
    "re-executes their producing partitions, and resumes the pull "
    "(reference: FetchFailed -> DAGScheduler map-stage resubmission). "
    "Disabled, the same losses fail fast with an error naming the lost "
    "map outputs.", conv=_bool))
RECOVERY_MAX_ATTEMPTS = register(ConfEntry(
    "spark.rapids.shuffle.recovery.maxStageAttempts", 4,
    "Recovery attempts allowed per shuffle stage before giving up with "
    "StageRecoveryExhausted — map outputs that keep dying after this "
    "many recomputations indicate a persistent failure recomputation "
    "cannot outrun (reference spark.stage.maxConsecutiveAttempts).",
    conv=int))


def conf_fingerprint(conf) -> str:
    """Stable digest of the effective settings.  Stamped onto each
    exchange at plan time (plan/overrides.py) and recorded in its
    lineage: recomputation is only deterministic under the exact conf
    the original map ran with, so the pairing is recorded, auditable,
    and asserted at recompute time."""
    settings = getattr(conf, "settings", None)
    if settings is None:
        settings = dict(conf) if conf else {}
    h = hashlib.sha1()
    for k in sorted(settings, key=str):
        h.update(f"{k}={settings[k]};".encode())
    return h.hexdigest()


@dataclass
class ShuffleLineage:
    """How one shuffle's map outputs were produced — enough to re-run
    any subset of them deterministically.

    ``map_src`` maps each flat map-batch index (the transport's map_id)
    to the child partition that produced it; re-draining that child
    partition yields the same batch sequence, so the k-th produced
    batch refills the k-th flat index recorded for that partition.
    """

    exchange: Any            # the ShuffleExchangeExec (owns partitioning)
    coalesced: bool          # tiny-input rewrite applied on attempt 0
    num_parts: int           # reduce partition count the maps split into
    map_src: dict            # flat map_id -> child partition id
    conf_fp: str | None = None

    def recompute(self, ctx, transport, epochs: dict[int, int]) -> int:
        """Re-execute the child partitions owning the given map ids and
        rewrite their outputs tagged with the post-invalidation epochs.
        Returns the number of map outputs actually rewritten."""
        if self.conf_fp is not None:
            now = conf_fingerprint(ctx.conf)
            if now != self.conf_fp:
                raise RuntimeError(
                    f"shuffle {self.exchange.shuffle_id}: conf changed "
                    f"since the map stage ran ({self.conf_fp[:12]} -> "
                    f"{now[:12]}); lineage recomputation would not be "
                    "deterministic")
        flat_by_cpid: dict[int, list[int]] = {}
        for bi in sorted(self.map_src):
            flat_by_cpid.setdefault(self.map_src[bi], []).append(bi)
        wanted = set(epochs)
        child = self.exchange.children[0]
        # uninstrumented iter: a recovery re-drain must not inflate the
        # child's output metrics a second time
        impl = type(child).partition_iter
        impl = getattr(impl, "__wrapped__", impl)
        done = 0
        for cpid in sorted({self.map_src[bi] for bi in wanted}):
            # recompute re-drains whole child partitions; check between
            # them so a cancel mid-recovery stops at the next boundary
            ctx.check_cancel()
            flat = flat_by_cpid[cpid]
            for k, b in enumerate(impl(child, ctx, cpid)):
                if k >= len(flat):
                    break  # nondeterministic child grew; extra output
                    # has no recorded slot and must not be invented
                bi = flat[k]
                if bi not in wanted:
                    continue
                self.exchange._write_map_batch(
                    ctx, transport, bi, b, self.coalesced,
                    self.num_parts, epoch=epochs[bi])
                done += 1
        return done


class _RecoveryState:
    """Per-execution recovery bookkeeping, shared by every concurrent
    reduce pull: one lock per shuffle serializes its recoveries, and the
    attempt counters enforce the per-stage budget."""

    def __init__(self):
        self._lock = threading.Lock()
        self.attempts: dict = {}
        self._shuffle_locks: dict = {}

    def lock_for(self, shuffle_id) -> threading.Lock:
        with self._lock:
            return self._shuffle_locks.setdefault(shuffle_id,
                                                  threading.Lock())


def recovering_fetch(ctx, exchange, transport, pid: int, lo: int,
                     hi: int | None) -> Iterator:
    """Pull one reduce partition's map-batch slice through the stage-
    recovery loop: terminal losses invalidate + recompute + resume at
    the first undelivered batch (epoch tagging in the store guarantees
    the resumed stream never mixes attempts)."""
    delivered = 0
    while True:
        # cancellation point: a cancelled query must not start another
        # recovery round (only MapOutputLostError re-enters the loop;
        # the terminal lifecycle errors propagate straight out)
        ctx.check_cancel()
        try:
            for b in transport.fetch_partition(
                    exchange.shuffle_id, pid, lo + delivered, hi):
                delivered += 1
                yield b
            return
        except MapOutputLostError as err:
            _recover(ctx, transport, err, exchange=exchange)


def _recover(ctx, transport, err: MapOutputLostError,
             exchange=None) -> None:
    """Handle one observed loss: invalidate + recompute the lost map
    outputs, or raise when recovery is disabled, has no lineage, or the
    stage's attempt budget ran out."""
    ctx.check_cancel()
    settings = ctx.conf.settings
    if not RECOVERY_ENABLED.get(settings):
        raise err
    lineage = ctx.lineage_for(err.shuffle_id)
    if lineage is None:
        # nothing recorded (remote-only shuffle id, host path): terminal
        raise err
    state = ctx.cached(("stage_recovery_state",), _RecoveryState)
    with state.lock_for(err.shuffle_id):
        # a concurrent pull may have recovered these outputs while we
        # waited: only map ids whose epoch has NOT advanced past what
        # this reader observed are still lost
        still_lost = {m: e for m, e in err.lost.items()
                      if transport.map_epoch(err.shuffle_id, m) <= e}
        if still_lost and getattr(err, "observed_empty", False):
            # an empty slot can be OBSERVED between a recovery's
            # invalidation and its rewrite — at the same epoch the
            # rewrite carries, so the epoch test above cannot rule it
            # out.  We hold the shuffle's recovery lock, so any prior
            # recovery has fully written back: a present output means
            # this reader's loss was already repaired.  Re-invalidating
            # it would cascade (each round nulls the slots again and
            # reopens the same window for another concurrent reader)
            # until the attempt budget exhausts on a healthy shuffle.
            present = getattr(transport, "map_output_present", None)
            if present is not None:
                still_lost = {
                    m: e for m, e in still_lost.items()
                    if not present(err.shuffle_id, err.part_id, m)}
        if not still_lost:
            return
        budget = RECOVERY_MAX_ATTEMPTS.get(settings)
        used = state.attempts.get(err.shuffle_id, 0)
        if used >= budget:
            raise StageRecoveryExhausted(err.shuffle_id, used,
                                         still_lost) from err
        state.attempts[err.shuffle_id] = used + 1
        t0 = time.perf_counter()
        # the recovery span parents every map-rewrite event emitted by
        # _write_map_batch during the recompute (same thread), so a
        # trace distinguishes recovered outputs from the original stage
        with ctx.trace_span("stage.recovery", "recovery",
                            shuffle=str(err.shuffle_id),
                            attempt=used + 1,
                            lost_maps=sorted(still_lost)) as sp:
            new_epochs = transport.invalidate_map_outputs(err.shuffle_id,
                                                          still_lost)
            done = lineage.recompute(ctx, transport, new_epochs)
            if sp is not None:
                sp.annotate(recomputed=done)
        wall = time.perf_counter() - t0
        m = ctx.catalog.metrics
        m["stage_recomputes"] = m.get("stage_recomputes", 0) + 1
        m["map_outputs_recomputed"] = \
            m.get("map_outputs_recomputed", 0) + done
        m["recovery_wall_s"] = m.get("recovery_wall_s", 0.0) + wall
        # also attribute recovery to the exchange NODE so EXPLAIN
        # ANALYZE shows nonzero recovery metrics on the affected plan
        # node, not just a global counter
        node = exchange if exchange is not None \
            else getattr(lineage, "exchange", None)
        if node is not None and ctx.metrics_enabled:
            nm = ctx.metrics_for(node)
            nm.add("stageRecoveries", 1)
            nm.add("mapOutputsRecomputed", done)
            nm.add("recoveryTime", wall)

"""Backend transition exec (reference GpuTransitionOverrides inserts
GpuRowToColumnarExec / GpuColumnarToRowExec / HostColumnarToGpu,
GpuTransitionOverrides.scala:249-266).

In this engine both backends are columnar, so a transition is a
host<->device batch conversion around a subtree executing on the other
backend.
"""
from __future__ import annotations

from dataclasses import replace
from typing import Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import (ExecCtx, PlanNode, device_to_host,
                                        host_to_device)

__all__ = ["BackendSwitchExec"]


class BackendSwitchExec(PlanNode):
    """Run the child subtree on ``inner_backend``; convert its output
    batches to the enclosing context's backend."""

    combines_batches = False

    def __init__(self, child: PlanNode, inner_backend: str):
        super().__init__([child])
        assert inner_backend in ("device", "host")
        self.inner_backend = inner_backend

    @property
    def output_schema(self) -> T.Schema:
        return self.children[0].output_schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self.children[0].num_partitions(self._inner(ctx))

    def _inner(self, ctx: ExecCtx) -> ExecCtx:
        if ctx.backend == self.inner_backend:
            return ctx
        return replace(ctx, backend=self.inner_backend)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        inner = self._inner(ctx)
        for b in self.children[0].partition_iter(inner, pid):
            if inner.backend == ctx.backend:
                yield b
            elif ctx.backend == "host":
                yield device_to_host(b)
            else:
                yield host_to_device(b)

    def node_desc(self) -> str:
        return f"BackendSwitchExec[->{self.inner_backend}]"

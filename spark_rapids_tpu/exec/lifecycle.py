"""Query lifecycle control plane: deadlines, cooperative cancellation,
admission control.

Reference mapping: the plugin leans on Spark's task-kill machinery —
``TaskContext.isInterrupted`` polled inside long loops, and
GpuSemaphore releasing the device when a task is killed
(GpuSemaphore.scala:74-126) — so one runaway task cannot wedge the GPU
for the queries queued behind it.  This standalone engine has no Spark
scheduler to inherit that from, so the equivalent plane lives here:

* :class:`QueryLifecycle` — a per-query handle minted in ``ExecCtx``
  alongside the query id.  It carries a cancellation
  ``threading.Event`` plus a monotonic deadline
  (``spark.rapids.sql.queryTimeout`` or ``collect(timeout=...)``) and
  moves through ``ADMITTED -> RUNNING -> {FINISHED, FAILED, CANCELLED,
  DEADLINE_EXCEEDED}``.  Cancellation is **cooperative**: the engine
  calls :meth:`QueryLifecycle.check` at its chokepoints (every
  ``ctx.dispatch``/``dispatch_retry`` entry, every drain batch
  boundary, the shuffle retry ladder's backoff waits, the recovery
  recompute loop, spill I/O, the pandas-UDF slot queue) and the first
  check after a cancel/deadline raises a **terminal** error.

* :class:`QueryCancelled` / :class:`QueryDeadlineExceeded` — terminal
  taxonomy in the ``shuffle/errors.py`` style: ``terminal = True`` is
  a class attribute so every retry ladder (OOM split-and-retry in
  memory/retry.py, the shuffle fetch ladder, stage recovery) can
  refuse to swallow them with one ``getattr(ex, "terminal", False)``
  check and no import.

* :class:`AdmissionController` — session-level admission bounding
  concurrent queries (``spark.rapids.sql.admission.*``).  Beyond the
  queue bound (or queue wait timeout, or after shutdown began) new
  queries are load-shed with :class:`QueryRejected` instead of piling
  onto the DeviceSemaphore and worker pool.  Admission is
  **weighted-fair across tenants** (``collect(tenant=...)`` or the
  ``spark.rapids.sql.tenant`` default): each tenant owns a FIFO queue
  and a virtual-time stride — the next admitted query comes from the
  backlogged tenant with the smallest virtual time, which converges on
  ``tenantWeights`` shares under saturation while a SINGLE tenant
  degenerates to exactly the old FIFO token deque.  Queue bounds and
  per-tenant ``tenantMaxConcurrent`` caps apply per tenant, so one
  storming tenant sheds only itself.  When the cross-query memory
  governor is enabled the session also wires its pressure hook here:
  sustained device occupancy above the shed watermark rejects NEW
  queries rather than admitting them into an OOM-retry storm — but
  only for tenants AT OR ABOVE their weighted share of the running
  set, so the noisy tenant absorbs the shed, not its neighbors
  (memory/governor.py; the governor first evicts the result cache,
  its lowest-priority occupant, before any query is shed).  A query
  cancelled while still QUEUED releases its queue slot and surfaces
  ``QueryCancelled`` (counted once by the cancel itself) — never
  ``queries_rejected``.

Post-cancel invariants (asserted by tests/test_lifecycle.py): the
DeviceSemaphore is back at full capacity, the spill directory is
empty, parked spillable batches are closed, and the peer's server
sessions for the dead query are dropped — cancellation unwinds through
the same ``finally`` blocks as success, it never leaks by design.

Dependency discipline: stdlib + conf + obs.registry only, so hot
modules may import this at module level without dragging jax in.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from spark_rapids_tpu.conf import ConfEntry, register
from spark_rapids_tpu.obs.registry import get_registry

__all__ = [
    "QueryLifecycle", "AdmissionController", "QueryLifecycleError",
    "QueryCancelled", "QueryDeadlineExceeded", "QueryRejected",
    "SQL_TENANT", "parse_tenant_map",
    "ADMITTED", "RUNNING", "FINISHED", "FAILED", "CANCELLED",
    "DEADLINE_EXCEEDED",
]

QUERY_TIMEOUT = register(ConfEntry(
    "spark.rapids.sql.queryTimeout", 0.0,
    "Per-query deadline in seconds (0 disables). Measured on the "
    "monotonic clock from query start; once exceeded, the next "
    "cooperative cancellation point (dispatch entry, drain batch "
    "boundary, shuffle backoff wait, spill I/O, UDF slot acquire) "
    "raises the terminal QueryDeadlineExceeded and the query unwinds, "
    "releasing the device semaphore and spill files on the way out. "
    "DataFrame.collect(timeout=...) overrides it per call (the "
    "tighter of the two wins).", conv=float))
ADMISSION_MAX_CONCURRENT = register(ConfEntry(
    "spark.rapids.sql.admission.maxConcurrentQueries", 0,
    "Queries allowed to run concurrently per session (0 = unbounded). "
    "Excess queries wait FIFO in the admission queue instead of piling "
    "onto the device semaphore and drain worker pool; size it near the "
    "device concurrency (spark.rapids.sql.concurrentDeviceTasks) so "
    "admitted queries actually progress (reference: GpuSemaphore "
    "bounding concurrent tasks on the GPU).", conv=int))
ADMISSION_MAX_QUEUED = register(ConfEntry(
    "spark.rapids.sql.admission.maxQueuedQueries", 16,
    "Queries allowed to WAIT for admission beyond the concurrent "
    "bound. Arrivals past this are load-shed immediately with "
    "QueryRejected — under sustained overload a bounded queue keeps "
    "latency finite instead of growing it without limit.", conv=int))
ADMISSION_QUEUE_TIMEOUT = register(ConfEntry(
    "spark.rapids.sql.admission.queueTimeoutSeconds", 30.0,
    "Longest a query may wait in the admission queue before it is "
    "rejected with QueryRejected (0 = wait forever). Keeps a wedged "
    "run from silently stalling everything queued behind it.",
    conv=float))
SQL_TENANT = register(ConfEntry(
    "spark.rapids.sql.tenant", "default",
    "Tenant name queries run under when DataFrame.collect(tenant=...) "
    "does not name one. Tenants are the unit of weighted-fair "
    "admission, per-tenant queue bounds/concurrency caps, and "
    "per-tenant memory-pressure shedding — one noisy tenant cannot "
    "starve the rest. A single tenant (the default) makes admission "
    "behave exactly like the plain FIFO queue."))
ADMISSION_TENANT_WEIGHTS = register(ConfEntry(
    "spark.rapids.sql.admission.tenantWeights", "",
    "Comma-separated tenant:weight pairs (e.g. 'etl:3,dashboards:1'; "
    "unlisted tenants weigh 1). Under saturation each backlogged "
    "tenant is admitted in proportion to its weight via virtual-time "
    "stride scheduling; an idle tenant accrues no credit, so it "
    "cannot burst past its share after sitting out."))
ADMISSION_TENANT_MAX_CONCURRENT = register(ConfEntry(
    "spark.rapids.sql.admission.tenantMaxConcurrent", "",
    "Comma-separated tenant:N pairs capping how many of a tenant's "
    "queries may run concurrently (unlisted/0 = only the global "
    "maxConcurrentQueries bound applies). A capped tenant's surplus "
    "waits in ITS queue; other tenants admit past it — per-tenant "
    "caps never cause cross-tenant head-of-line blocking."))
ADMISSION_DEADLINE_ORDERING = register(ConfEntry(
    "spark.rapids.sql.admission.deadlineOrdering", False,
    "Order each tenant's admission queue earliest-deadline-first "
    "(queries carrying collect(timeout=)/queryTimeout deadlines jump "
    "ahead of unbounded ones) instead of strict FIFO. Off by default: "
    "FIFO within a tenant preserves the pre-tenant admission order "
    "byte for byte.", conv=lambda v: str(v).lower() in
    ("true", "1", "yes")))

# -- states ----------------------------------------------------------------

ADMITTED = "ADMITTED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
DEADLINE_EXCEEDED = "DEADLINE_EXCEEDED"

#: states a query never leaves
TERMINAL_STATES = frozenset({FINISHED, FAILED, CANCELLED,
                             DEADLINE_EXCEEDED})


# -- terminal taxonomy (shuffle/errors.py style) ---------------------------

class QueryLifecycleError(RuntimeError):
    """Base of the lifecycle taxonomy.  ``terminal`` mirrors the
    shuffle/errors.py convention: retry ladders check
    ``getattr(ex, "terminal", False)`` and re-raise instead of
    retrying — a cancelled query must not be split, backed off, or
    lineage-recomputed back to life."""

    terminal: bool = True

    def __init__(self, query_id: str, msg: str):
        super().__init__(msg)
        self.query_id = query_id


class QueryCancelled(QueryLifecycleError):
    """The query was cancelled (session.cancel / cancel_all / early
    consumer exit) and a cooperative checkpoint observed it."""

    def __init__(self, query_id: str, reason: str = "cancelled"):
        super().__init__(query_id,
                         f"query {query_id} cancelled: {reason}")
        self.reason = reason


class QueryDeadlineExceeded(QueryLifecycleError):
    """The query ran past its deadline (spark.rapids.sql.queryTimeout
    or collect(timeout=...))."""

    def __init__(self, query_id: str, timeout: float):
        super().__init__(query_id,
                         f"query {query_id} exceeded its deadline "
                         f"({timeout:g}s)")
        self.timeout = timeout


class QueryRejected(QueryLifecycleError):
    """Load-shed at admission: the session is shutting down, the
    admission queue is full, or the queue wait timed out.  The query
    never started, so there is nothing to unwind."""


class TargetedShed(str):
    """A pressure-hook reason that already NAMES its victim: the hook
    returned it for this tenant specifically (the control plane's SLO
    shed), so admission must reject without the over-share spare.  A
    plain-``str`` reason keeps the global-pressure semantics — a
    tenant running below its weighted share is spared, because the
    pressure is someone else's doing.  Without this distinction a
    targeted shed can never hold: the moment the victim's running
    queries drain, its active count is below share and every new
    arrival is spared straight back in."""


# -- per-query handle ------------------------------------------------------

class QueryLifecycle:
    """State machine + cancellation event + monotonic deadline for one
    query.  Thread-safe: the session cancels from its thread while
    drain workers call :meth:`check` from theirs.

    The cancellation event is the single broadcast channel: ``cancel``
    and a tripped deadline both set it, so every blocked
    ``event.wait(pause)`` (shuffle backoff, UDF slot poll) wakes
    promptly and the next :meth:`check` raises the terminal error.
    """

    def __init__(self, query_id: str, timeout: "float | None" = None,
                 tenant: str = "default"):
        self.query_id = query_id
        self.timeout = timeout if timeout and timeout > 0 else None
        self.tenant = tenant
        self.cancel_event = threading.Event()
        self._lock = threading.Lock()
        self._state = ADMITTED
        self._started_at: "float | None" = None
        self._deadline: "float | None" = None
        self._cancel_reason = "cancelled"
        # stamped by AdmissionController.admit on the admitted path;
        # the control loop's per-tenant SLOs are end-to-end (queue wait
        # + wall), so admission latency must ride along with the
        # lifecycle to the terminal observation
        self.queue_wait_s: "float | None" = None
        # set by control-enabled sessions only: emits the
        # query.tenant.<t>.e2e_seconds histogram at the terminal
        # transition.  Off by default so a static engine's counter set
        # stays byte-identical with the control plane disabled.
        self.observe_e2e = False
        # free-form execution annotations (e.g. cluster resume facts
        # from a recovered driver) surfaced on /queries and in history
        # records; empty for the overwhelming majority of queries
        self.annotations: dict = {}

    @classmethod
    def from_conf(cls, query_id: str, conf, timeout: "float | None" = None,
                  tenant: "str | None" = None) -> "QueryLifecycle":
        """Effective deadline = the tighter of the conf queryTimeout
        and the per-call ``timeout``; tenant defaults from
        ``spark.rapids.sql.tenant``."""
        settings = getattr(conf, "settings", None) or {}
        conf_tmo = QUERY_TIMEOUT.get(settings)
        cands = [t for t in (conf_tmo, timeout) if t and t > 0]
        return cls(query_id, timeout=min(cands) if cands else None,
                   tenant=tenant or SQL_TENANT.get(settings))

    # -- transitions -------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def start(self) -> None:
        """ADMITTED -> RUNNING; the deadline clock starts here, not at
        admission, so queue wait does not eat the query's budget."""
        with self._lock:
            if self._state == ADMITTED:
                self._state = RUNNING
                self._started_at = time.monotonic()
                if self.timeout is not None:
                    self._deadline = self._started_at + self.timeout

    def _observe_wall(self) -> None:
        """Record the query's wall time into the latency histograms at
        its FIRST terminal transition (queries cancelled while still
        queued never started — no wall to record)."""
        started = self._started_at
        if started is None:
            return
        wall = time.monotonic() - started
        reg = get_registry()
        reg.observe("query.wall_seconds", wall)
        reg.observe(f"query.tenant.{self.tenant}.wall_seconds", wall)
        if self.observe_e2e:
            reg.observe(f"query.tenant.{self.tenant}.e2e_seconds",
                        wall + (self.queue_wait_s or 0.0))

    def finish(self) -> bool:
        """RUNNING -> FINISHED (no-op once terminal)."""
        with self._lock:
            if self._state in TERMINAL_STATES:
                return False
            self._state = FINISHED
        self._observe_wall()
        return True

    def fail(self) -> bool:
        """RUNNING -> FAILED on a non-lifecycle error (no-op once
        terminal)."""
        with self._lock:
            if self._state in TERMINAL_STATES:
                return False
            self._state = FAILED
        self._observe_wall()
        return True

    def cancel(self, reason: str = "cancelled") -> bool:
        """Request cooperative cancellation.  Idempotent: only the
        first call transitions (and counts queries_cancelled); a query
        already finished/failed/deadline-exceeded is left alone and
        ``False`` is returned."""
        with self._lock:
            if self._state in TERMINAL_STATES:
                return False
            self._state = CANCELLED
            self._cancel_reason = reason
        self.cancel_event.set()
        get_registry().inc("queries_cancelled")
        self._observe_wall()
        return True

    def _expire(self) -> bool:
        with self._lock:
            if self._state in TERMINAL_STATES:
                return False
            self._state = DEADLINE_EXCEEDED
        self.cancel_event.set()
        get_registry().inc("queries_deadline_exceeded")
        self._observe_wall()
        return True

    # -- cooperative checkpoints -------------------------------------------

    def remaining(self) -> "float | None":
        """Seconds to the deadline (None when no deadline; never
        negative)."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def check(self) -> None:
        """The cancellation point.  Raises :class:`QueryCancelled` or
        :class:`QueryDeadlineExceeded` once the query is cancelled or
        past its deadline; otherwise returns immediately.  Cheap on
        the happy path (one Event read + one clock read)."""
        if not self.cancel_event.is_set():
            if self._deadline is None or \
                    time.monotonic() < self._deadline:
                return
            self._expire()
        state = self._state
        if state == DEADLINE_EXCEEDED:
            raise QueryDeadlineExceeded(self.query_id,
                                        self.timeout or 0.0)
        raise QueryCancelled(self.query_id, self._cancel_reason)

    def wait(self, seconds: float) -> None:
        """Interruptible sleep: waits up to ``seconds`` (capped at the
        time left to the deadline) on the cancel event, then
        :meth:`check`.  Replaces ``time.sleep`` in retry backoff so a
        cancel or deadline aborts the ladder mid-pause instead of
        after it."""
        self.check()
        rem = self.remaining()
        pause = seconds if rem is None else min(seconds, rem)
        if pause > 0:
            self.cancel_event.wait(pause)
        self.check()


# -- session-level admission -----------------------------------------------

def parse_tenant_map(spec: str, conv=float) -> dict:
    """'a:3,b:1' -> {'a': 3.0, 'b': 1.0} (tenantWeights /
    tenantMaxConcurrent grammar; blanks ignored, bad pairs raise)."""
    out: dict = {}
    for pair in (spec or "").split(","):
        pair = pair.strip()
        if not pair:
            continue
        name, sep, val = pair.rpartition(":")
        if not sep or not name.strip():
            raise ValueError(f"bad tenant map entry {pair!r}: "
                             "want 'tenant:value'")
        out[name.strip()] = conv(val.strip())
    return out


class _TenantState:
    """One tenant's admission book-keeping: its FIFO/EDF wait queue,
    running count, and virtual-time stride (1/weight per admission)."""

    __slots__ = ("name", "weight", "max_concurrent", "active", "vtime",
                 "queue")

    def __init__(self, name: str, weight: float = 1.0,
                 max_concurrent: int = 0):
        self.name = name
        self.weight = weight if weight > 0 else 1.0
        self.max_concurrent = max_concurrent
        self.active = 0
        self.vtime = 0.0
        self.queue: deque = deque()


class _Waiter:
    __slots__ = ("tenant", "seq", "deadline_key")

    def __init__(self, tenant: _TenantState, seq: int,
                 deadline_key: float):
        self.tenant = tenant
        self.seq = seq
        self.deadline_key = deadline_key


class AdmissionController:
    """Weighted-fair admission: at most ``max_concurrent`` queries run
    and at most ``max_queued`` wait PER TENANT; the rest are load-shed
    with :class:`QueryRejected`.  One condition variable guards every
    counter.  Each tenant keeps its own FIFO queue; when a slot frees,
    the backlogged tenant with the smallest virtual time admits its
    head, and admitting advances that tenant's virtual time by
    1/weight — stride scheduling, so saturated tenants converge on
    ``tenantWeights`` shares while a single tenant reduces to exactly
    the old FIFO token deque (a waiter only proceeds when it is the
    deterministic selection, so a late arrival can never overtake a
    same-tenant query that queued first)."""

    def __init__(self, max_concurrent: int = 0, max_queued: int = 16,
                 queue_timeout: float = 30.0,
                 tenant_weights: "dict | None" = None,
                 tenant_max_concurrent: "dict | None" = None,
                 deadline_ordering: bool = False):
        self.max_concurrent = max_concurrent
        self.max_queued = max_queued
        self.queue_timeout = queue_timeout
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_max_concurrent = dict(tenant_max_concurrent or {})
        self.deadline_ordering = deadline_ordering
        self._cond = threading.Condition()
        self._tenants: "dict[str, _TenantState]" = {}
        self._active = 0
        self._seq = 0
        self._vclock = 0.0
        self._shutdown = False
        #: audit trail of admissions in order — (tenant, query_id) —
        #: so fairness is observable, not just statistical (the CI
        #: serving gate asserts weighted order against this)
        self.admission_log: deque = deque(maxlen=1024)
        # memory-pressure shed hook (memory/governor.py, wired by the
        # session when the governor is enabled): a callable returning a
        # reason string when NEW admissions should be load-shed —
        # sustained occupancy above the shed watermark — or None.
        # Late-bound attribute, not an import: this module stays
        # stdlib + conf + obs so hot modules can import it freely
        self.pressure_hook = None
        # serving-tier fault registry (faults.py, wired by the session
        # when spark.rapids.test.faults is set) for the
        # admission.tenant.storm injection point — late-bound for the
        # same dependency reason as pressure_hook
        self.faults = None

    @classmethod
    def from_conf(cls, conf) -> "AdmissionController":
        settings = getattr(conf, "settings", None) or {}
        return cls(
            max_concurrent=ADMISSION_MAX_CONCURRENT.get(settings),
            max_queued=ADMISSION_MAX_QUEUED.get(settings),
            queue_timeout=ADMISSION_QUEUE_TIMEOUT.get(settings),
            tenant_weights=parse_tenant_map(
                ADMISSION_TENANT_WEIGHTS.get(settings)),
            tenant_max_concurrent=parse_tenant_map(
                ADMISSION_TENANT_MAX_CONCURRENT.get(settings), conv=int),
            deadline_ordering=ADMISSION_DEADLINE_ORDERING.get(settings))

    @property
    def active(self) -> int:
        with self._cond:
            return self._active

    @property
    def queued(self) -> int:
        with self._cond:
            return sum(len(t.queue) for t in self._tenants.values())

    @property
    def shutting_down(self) -> bool:
        return self._shutdown

    def tenant_stats(self) -> dict:
        """{tenant: {active, queued, weight, vtime}} — the fairness
        ledger (bench observability block, chaos assertions)."""
        with self._cond:
            return {t.name: {"active": t.active, "queued": len(t.queue),
                             "weight": t.weight, "vtime": t.vtime}
                    for t in self._tenants.values()}

    # -- internals (under self._cond) --------------------------------------

    def _tenant_locked(self, name: str) -> _TenantState:
        st = self._tenants.get(name)
        if st is None:
            st = _TenantState(
                name, weight=self.tenant_weights.get(name, 1.0),
                max_concurrent=int(
                    self.tenant_max_concurrent.get(name, 0)))
            self._tenants[name] = st
        return st

    def _head_locked(self, st: _TenantState) -> "_Waiter | None":
        if not st.queue:
            return None
        if not self.deadline_ordering:
            return st.queue[0]
        return min(st.queue, key=lambda w: (w.deadline_key, w.seq))

    def _select_locked(self) -> "_Waiter | None":
        """The deterministic next admission: among tenants with
        waiters and per-tenant headroom, the smallest (vtime, head
        seq).  Tenants at their own cap are skipped — a capped
        tenant's backlog never blocks its neighbors."""
        best = None
        best_key = None
        for st in self._tenants.values():
            if st.max_concurrent > 0 and st.active >= st.max_concurrent:
                continue
            head = self._head_locked(st)
            if head is None:
                continue
            key = (st.vtime, head.seq)
            if best_key is None or key < best_key:
                best, best_key = head, key
        return best

    def _admitted_locked(self, st: _TenantState, query_id: str) -> None:
        self._active += 1
        st.active += 1
        # stride bookkeeping: service starts at max(own vtime, the
        # global virtual clock) so an idle tenant re-enters at "now"
        # with no hoarded credit, then advances by 1/weight
        start = max(st.vtime, self._vclock)
        st.vtime = start + 1.0 / st.weight
        self._vclock = start
        self.admission_log.append((st.name, query_id))
        reg = get_registry()
        reg.inc("queries_admitted")
        reg.inc(f"admission.tenant.{st.name}.admitted")

    def _tenant_over_share(self, tenant: str) -> bool:
        """Is this tenant at/above its weighted share of the running
        set?  The per-tenant pressure-shed predicate: with a single
        tenant this is always True (identical to the old
        shed-everyone behavior); a tenant running BELOW its share is
        spared — the pressure is someone else's doing."""
        with self._cond:
            st = self._tenant_locked(tenant)
            total = self._active
            if total <= 0:
                return True
            sum_w = sum(t.weight for t in self._tenants.values()
                        if t.active > 0 or t is st)
            return st.active * sum_w >= total * st.weight

    def _reject(self, reg, tenant: str, query_id: str,
                why: str) -> "QueryRejected":
        reg.inc("queries_rejected")
        reg.inc(f"admission.tenant.{tenant}.rejected")
        return QueryRejected(query_id,
                             f"query {query_id} rejected: {why}")

    def admit(self, query_id: str = "?", timeout: "float | None" = None,
              tenant: str = "default",
              lifecycle: "QueryLifecycle | None" = None) -> None:
        """Block until admitted.  Raises :class:`QueryRejected` when
        the session is shutting down, the tenant's wait queue is full,
        the queue wait exceeds ``timeout`` (default: the
        queueTimeoutSeconds conf; 0 waits forever), or the memory
        governor's pressure hook reports sustained overload AND this
        tenant is at/above its weighted share.  With ``lifecycle``,
        a cancel landing while still queued releases the queue slot
        and raises the terminal :class:`QueryCancelled` instead —
        counted once as ``queries_cancelled`` by the cancel itself,
        never as a rejection."""
        reg = get_registry()
        t_admit = time.monotonic()
        tmo = self.queue_timeout if timeout is None else timeout
        faults = self.faults
        if faults is not None:
            act = faults.check("admission.tenant.storm", tenant=tenant,
                               query_id=query_id)
            if act is not None:
                # the tenant's traffic storm saturated its own queue:
                # shed THIS arrival exactly like a full tenant queue
                raise self._reject(
                    reg, tenant, query_id,
                    f"injected admission storm on tenant {tenant!r}")
        hook = self.pressure_hook
        if hook is not None:
            # checked OUTSIDE the condition (the hook takes the
            # governor's own lock) and before queueing: a query shed
            # for memory pressure never occupied a queue slot.  Only
            # the over-share tenant absorbs the shed.  The hook sees
            # the tenant so a tenant-scoped policy (the control
            # plane's SLO shed) can target exactly one tenant while
            # returning None for its neighbors.
            reason = hook(tenant)
            if reason:
                if isinstance(reason, TargetedShed) or \
                        self._tenant_over_share(tenant):
                    raise self._reject(reg, tenant, query_id, reason)
                reg.inc("admission_pressure_spared")
                reg.inc(f"admission.tenant.{tenant}.pressure_spared")
        with self._cond:
            st = self._tenant_locked(tenant)
            if self._shutdown:
                raise self._reject(reg, tenant, query_id,
                                   "session is shutting down")
            if self.max_concurrent <= 0:
                self._admitted_locked(st, query_id)
                waited = time.monotonic() - t_admit
                reg.observe("admission.queue_wait_seconds", waited)
                if lifecycle is not None:
                    lifecycle.queue_wait_s = waited
                return
            if self._active < self.max_concurrent \
                    and not any(t.queue for t in self._tenants.values()) \
                    and (st.max_concurrent <= 0
                         or st.active < st.max_concurrent):
                self._admitted_locked(st, query_id)
                waited = time.monotonic() - t_admit
                reg.observe("admission.queue_wait_seconds", waited)
                if lifecycle is not None:
                    lifecycle.queue_wait_s = waited
                return
            if len(st.queue) >= self.max_queued:
                raise self._reject(
                    reg, tenant, query_id,
                    f"admission queue full for tenant {tenant!r} "
                    f"({len(st.queue)} >= "
                    f"maxQueuedQueries={self.max_queued})")
            self._seq += 1
            dkey = float("inf")
            if lifecycle is not None and lifecycle.timeout:
                dkey = time.monotonic() + lifecycle.timeout
            me = _Waiter(st, self._seq, dkey)
            st.queue.append(me)
            deadline = time.monotonic() + tmo if tmo and tmo > 0 \
                else None
            admitted = False
            try:
                while True:
                    if self._shutdown:
                        raise self._reject(reg, tenant, query_id,
                                           "session is shutting down")
                    if lifecycle is not None:
                        # cancel-while-queued: surface the terminal
                        # lifecycle error; the finally below frees the
                        # queue slot, and queries_cancelled was already
                        # counted exactly once by cancel() itself
                        lifecycle.check()
                    if self._active < self.max_concurrent and \
                            self._select_locked() is me:
                        st.queue.remove(me)
                        self._admitted_locked(st, query_id)
                        admitted = True
                        waited = time.monotonic() - t_admit
                        reg.observe("admission.queue_wait_seconds",
                                    waited)
                        if lifecycle is not None:
                            lifecycle.queue_wait_s = waited
                        return
                    rem = None if deadline is None \
                        else deadline - time.monotonic()
                    if rem is not None and rem <= 0:
                        raise self._reject(
                            reg, tenant, query_id,
                            f"waited {tmo:g}s in the admission queue "
                            "(queueTimeoutSeconds)")
                    # a condition wait cannot observe the lifecycle's
                    # cancel event, so cancellable waiters poll in
                    # bounded slices
                    if lifecycle is not None:
                        rem = 0.05 if rem is None else min(rem, 0.05)
                    self._cond.wait(rem)
            finally:
                if not admitted:
                    try:
                        st.queue.remove(me)
                    except ValueError:
                        pass
                    # the selection may have changed: wake the queue
                    self._cond.notify_all()

    def set_max_concurrent(self, n: int) -> None:
        """Retune the global cap at runtime (the control plane's AIMD
        actuation).  Raising it wakes the queue so newly-legal waiters
        admit immediately; lowering it never evicts running queries —
        the active set just drains below the new cap before anyone
        else admits."""
        with self._cond:
            self.max_concurrent = int(n)
            self._cond.notify_all()

    def release(self, tenant: str = "default") -> None:
        """One admitted query finished (success, failure, or cancel):
        free its slot — global and per-tenant — and wake the queue."""
        with self._cond:
            if self._active > 0:
                self._active -= 1
            st = self._tenants.get(tenant)
            if st is not None and st.active > 0:
                st.active -= 1
            self._cond.notify_all()

    def begin_shutdown(self) -> None:
        """Stop admitting: every queued waiter and every future
        ``admit`` raises :class:`QueryRejected`.  Already-admitted
        queries are unaffected (the session drains or cancels them)."""
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

"""Query lifecycle control plane: deadlines, cooperative cancellation,
admission control.

Reference mapping: the plugin leans on Spark's task-kill machinery —
``TaskContext.isInterrupted`` polled inside long loops, and
GpuSemaphore releasing the device when a task is killed
(GpuSemaphore.scala:74-126) — so one runaway task cannot wedge the GPU
for the queries queued behind it.  This standalone engine has no Spark
scheduler to inherit that from, so the equivalent plane lives here:

* :class:`QueryLifecycle` — a per-query handle minted in ``ExecCtx``
  alongside the query id.  It carries a cancellation
  ``threading.Event`` plus a monotonic deadline
  (``spark.rapids.sql.queryTimeout`` or ``collect(timeout=...)``) and
  moves through ``ADMITTED -> RUNNING -> {FINISHED, FAILED, CANCELLED,
  DEADLINE_EXCEEDED}``.  Cancellation is **cooperative**: the engine
  calls :meth:`QueryLifecycle.check` at its chokepoints (every
  ``ctx.dispatch``/``dispatch_retry`` entry, every drain batch
  boundary, the shuffle retry ladder's backoff waits, the recovery
  recompute loop, spill I/O, the pandas-UDF slot queue) and the first
  check after a cancel/deadline raises a **terminal** error.

* :class:`QueryCancelled` / :class:`QueryDeadlineExceeded` — terminal
  taxonomy in the ``shuffle/errors.py`` style: ``terminal = True`` is
  a class attribute so every retry ladder (OOM split-and-retry in
  memory/retry.py, the shuffle fetch ladder, stage recovery) can
  refuse to swallow them with one ``getattr(ex, "terminal", False)``
  check and no import.

* :class:`AdmissionController` — session-level FIFO admission bounding
  concurrent queries (``spark.rapids.sql.admission.*``).  Beyond the
  queue bound (or queue wait timeout, or after shutdown began) new
  queries are load-shed with :class:`QueryRejected` instead of piling
  onto the DeviceSemaphore and worker pool.  When the cross-query
  memory governor is enabled the session also wires its pressure hook
  here: sustained device occupancy above the shed watermark rejects
  NEW queries rather than admitting them into an OOM-retry storm
  (memory/governor.py).

Post-cancel invariants (asserted by tests/test_lifecycle.py): the
DeviceSemaphore is back at full capacity, the spill directory is
empty, parked spillable batches are closed, and the peer's server
sessions for the dead query are dropped — cancellation unwinds through
the same ``finally`` blocks as success, it never leaks by design.

Dependency discipline: stdlib + conf + obs.registry only, so hot
modules may import this at module level without dragging jax in.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from spark_rapids_tpu.conf import ConfEntry, register
from spark_rapids_tpu.obs.registry import get_registry

__all__ = [
    "QueryLifecycle", "AdmissionController", "QueryLifecycleError",
    "QueryCancelled", "QueryDeadlineExceeded", "QueryRejected",
    "ADMITTED", "RUNNING", "FINISHED", "FAILED", "CANCELLED",
    "DEADLINE_EXCEEDED",
]

QUERY_TIMEOUT = register(ConfEntry(
    "spark.rapids.sql.queryTimeout", 0.0,
    "Per-query deadline in seconds (0 disables). Measured on the "
    "monotonic clock from query start; once exceeded, the next "
    "cooperative cancellation point (dispatch entry, drain batch "
    "boundary, shuffle backoff wait, spill I/O, UDF slot acquire) "
    "raises the terminal QueryDeadlineExceeded and the query unwinds, "
    "releasing the device semaphore and spill files on the way out. "
    "DataFrame.collect(timeout=...) overrides it per call (the "
    "tighter of the two wins).", conv=float))
ADMISSION_MAX_CONCURRENT = register(ConfEntry(
    "spark.rapids.sql.admission.maxConcurrentQueries", 0,
    "Queries allowed to run concurrently per session (0 = unbounded). "
    "Excess queries wait FIFO in the admission queue instead of piling "
    "onto the device semaphore and drain worker pool; size it near the "
    "device concurrency (spark.rapids.sql.concurrentDeviceTasks) so "
    "admitted queries actually progress (reference: GpuSemaphore "
    "bounding concurrent tasks on the GPU).", conv=int))
ADMISSION_MAX_QUEUED = register(ConfEntry(
    "spark.rapids.sql.admission.maxQueuedQueries", 16,
    "Queries allowed to WAIT for admission beyond the concurrent "
    "bound. Arrivals past this are load-shed immediately with "
    "QueryRejected — under sustained overload a bounded queue keeps "
    "latency finite instead of growing it without limit.", conv=int))
ADMISSION_QUEUE_TIMEOUT = register(ConfEntry(
    "spark.rapids.sql.admission.queueTimeoutSeconds", 30.0,
    "Longest a query may wait in the admission queue before it is "
    "rejected with QueryRejected (0 = wait forever). Keeps a wedged "
    "run from silently stalling everything queued behind it.",
    conv=float))

# -- states ----------------------------------------------------------------

ADMITTED = "ADMITTED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
DEADLINE_EXCEEDED = "DEADLINE_EXCEEDED"

#: states a query never leaves
TERMINAL_STATES = frozenset({FINISHED, FAILED, CANCELLED,
                             DEADLINE_EXCEEDED})


# -- terminal taxonomy (shuffle/errors.py style) ---------------------------

class QueryLifecycleError(RuntimeError):
    """Base of the lifecycle taxonomy.  ``terminal`` mirrors the
    shuffle/errors.py convention: retry ladders check
    ``getattr(ex, "terminal", False)`` and re-raise instead of
    retrying — a cancelled query must not be split, backed off, or
    lineage-recomputed back to life."""

    terminal: bool = True

    def __init__(self, query_id: str, msg: str):
        super().__init__(msg)
        self.query_id = query_id


class QueryCancelled(QueryLifecycleError):
    """The query was cancelled (session.cancel / cancel_all / early
    consumer exit) and a cooperative checkpoint observed it."""

    def __init__(self, query_id: str, reason: str = "cancelled"):
        super().__init__(query_id,
                         f"query {query_id} cancelled: {reason}")
        self.reason = reason


class QueryDeadlineExceeded(QueryLifecycleError):
    """The query ran past its deadline (spark.rapids.sql.queryTimeout
    or collect(timeout=...))."""

    def __init__(self, query_id: str, timeout: float):
        super().__init__(query_id,
                         f"query {query_id} exceeded its deadline "
                         f"({timeout:g}s)")
        self.timeout = timeout


class QueryRejected(QueryLifecycleError):
    """Load-shed at admission: the session is shutting down, the
    admission queue is full, or the queue wait timed out.  The query
    never started, so there is nothing to unwind."""


# -- per-query handle ------------------------------------------------------

class QueryLifecycle:
    """State machine + cancellation event + monotonic deadline for one
    query.  Thread-safe: the session cancels from its thread while
    drain workers call :meth:`check` from theirs.

    The cancellation event is the single broadcast channel: ``cancel``
    and a tripped deadline both set it, so every blocked
    ``event.wait(pause)`` (shuffle backoff, UDF slot poll) wakes
    promptly and the next :meth:`check` raises the terminal error.
    """

    def __init__(self, query_id: str, timeout: "float | None" = None):
        self.query_id = query_id
        self.timeout = timeout if timeout and timeout > 0 else None
        self.cancel_event = threading.Event()
        self._lock = threading.Lock()
        self._state = ADMITTED
        self._started_at: "float | None" = None
        self._deadline: "float | None" = None
        self._cancel_reason = "cancelled"

    @classmethod
    def from_conf(cls, query_id: str, conf,
                  timeout: "float | None" = None) -> "QueryLifecycle":
        """Effective deadline = the tighter of the conf queryTimeout
        and the per-call ``timeout``."""
        settings = getattr(conf, "settings", None) or {}
        conf_tmo = QUERY_TIMEOUT.get(settings)
        cands = [t for t in (conf_tmo, timeout) if t and t > 0]
        return cls(query_id, timeout=min(cands) if cands else None)

    # -- transitions -------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def start(self) -> None:
        """ADMITTED -> RUNNING; the deadline clock starts here, not at
        admission, so queue wait does not eat the query's budget."""
        with self._lock:
            if self._state == ADMITTED:
                self._state = RUNNING
                self._started_at = time.monotonic()
                if self.timeout is not None:
                    self._deadline = self._started_at + self.timeout

    def finish(self) -> bool:
        """RUNNING -> FINISHED (no-op once terminal)."""
        with self._lock:
            if self._state in TERMINAL_STATES:
                return False
            self._state = FINISHED
            return True

    def fail(self) -> bool:
        """RUNNING -> FAILED on a non-lifecycle error (no-op once
        terminal)."""
        with self._lock:
            if self._state in TERMINAL_STATES:
                return False
            self._state = FAILED
            return True

    def cancel(self, reason: str = "cancelled") -> bool:
        """Request cooperative cancellation.  Idempotent: only the
        first call transitions (and counts queries_cancelled); a query
        already finished/failed/deadline-exceeded is left alone and
        ``False`` is returned."""
        with self._lock:
            if self._state in TERMINAL_STATES:
                return False
            self._state = CANCELLED
            self._cancel_reason = reason
        self.cancel_event.set()
        get_registry().inc("queries_cancelled")
        return True

    def _expire(self) -> bool:
        with self._lock:
            if self._state in TERMINAL_STATES:
                return False
            self._state = DEADLINE_EXCEEDED
        self.cancel_event.set()
        get_registry().inc("queries_deadline_exceeded")
        return True

    # -- cooperative checkpoints -------------------------------------------

    def remaining(self) -> "float | None":
        """Seconds to the deadline (None when no deadline; never
        negative)."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def check(self) -> None:
        """The cancellation point.  Raises :class:`QueryCancelled` or
        :class:`QueryDeadlineExceeded` once the query is cancelled or
        past its deadline; otherwise returns immediately.  Cheap on
        the happy path (one Event read + one clock read)."""
        if not self.cancel_event.is_set():
            if self._deadline is None or \
                    time.monotonic() < self._deadline:
                return
            self._expire()
        state = self._state
        if state == DEADLINE_EXCEEDED:
            raise QueryDeadlineExceeded(self.query_id,
                                        self.timeout or 0.0)
        raise QueryCancelled(self.query_id, self._cancel_reason)

    def wait(self, seconds: float) -> None:
        """Interruptible sleep: waits up to ``seconds`` (capped at the
        time left to the deadline) on the cancel event, then
        :meth:`check`.  Replaces ``time.sleep`` in retry backoff so a
        cancel or deadline aborts the ladder mid-pause instead of
        after it."""
        self.check()
        rem = self.remaining()
        pause = seconds if rem is None else min(seconds, rem)
        if pause > 0:
            self.cancel_event.wait(pause)
        self.check()


# -- session-level admission -----------------------------------------------

class AdmissionController:
    """FIFO admission: at most ``max_concurrent`` queries run, at most
    ``max_queued`` wait, the rest are load-shed with
    :class:`QueryRejected`.  A single condition variable guards both
    counters; FIFO order is enforced by a token deque — a waiter only
    proceeds when its token reaches the head, so a late arrival can
    never overtake a query that queued first."""

    def __init__(self, max_concurrent: int = 0, max_queued: int = 16,
                 queue_timeout: float = 30.0):
        self.max_concurrent = max_concurrent
        self.max_queued = max_queued
        self.queue_timeout = queue_timeout
        self._cond = threading.Condition()
        self._active = 0
        self._queue: deque = deque()
        self._shutdown = False
        # memory-pressure shed hook (memory/governor.py, wired by the
        # session when the governor is enabled): a callable returning a
        # reason string when NEW admissions should be load-shed —
        # sustained occupancy above the shed watermark — or None.
        # Late-bound attribute, not an import: this module stays
        # stdlib + conf + obs so hot modules can import it freely
        self.pressure_hook = None

    @classmethod
    def from_conf(cls, conf) -> "AdmissionController":
        settings = getattr(conf, "settings", None) or {}
        return cls(
            max_concurrent=ADMISSION_MAX_CONCURRENT.get(settings),
            max_queued=ADMISSION_MAX_QUEUED.get(settings),
            queue_timeout=ADMISSION_QUEUE_TIMEOUT.get(settings))

    @property
    def active(self) -> int:
        with self._cond:
            return self._active

    @property
    def queued(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def shutting_down(self) -> bool:
        return self._shutdown

    def admit(self, query_id: str = "?",
              timeout: "float | None" = None) -> None:
        """Block until admitted (FIFO).  Raises :class:`QueryRejected`
        when the session is shutting down, the wait queue is full, the
        queue wait exceeds ``timeout`` (default: the
        queueTimeoutSeconds conf; 0 waits forever), or the memory
        governor's pressure hook reports sustained overload."""
        reg = get_registry()
        tmo = self.queue_timeout if timeout is None else timeout
        token = object()
        hook = self.pressure_hook
        if hook is not None:
            # checked OUTSIDE the condition (the hook takes the
            # governor's own lock) and before queueing: a query shed
            # for memory pressure never occupied a queue slot
            reason = hook()
            if reason:
                reg.inc("queries_rejected")
                raise QueryRejected(
                    query_id,
                    f"query {query_id} rejected: {reason}")
        with self._cond:
            if self._shutdown:
                reg.inc("queries_rejected")
                raise QueryRejected(
                    query_id, f"query {query_id} rejected: session is "
                    "shutting down")
            if self.max_concurrent <= 0:
                self._active += 1
                reg.inc("queries_admitted")
                return
            if self._active < self.max_concurrent and not self._queue:
                self._active += 1
                reg.inc("queries_admitted")
                return
            if len(self._queue) >= self.max_queued:
                reg.inc("queries_rejected")
                raise QueryRejected(
                    query_id, f"query {query_id} rejected: admission "
                    f"queue full ({len(self._queue)} >= "
                    f"maxQueuedQueries={self.max_queued})")
            self._queue.append(token)
            deadline = time.monotonic() + tmo if tmo and tmo > 0 \
                else None
            try:
                while True:
                    if self._shutdown:
                        raise QueryRejected(
                            query_id, f"query {query_id} rejected: "
                            "session is shutting down")
                    if self._queue[0] is token and \
                            self._active < self.max_concurrent:
                        self._queue.popleft()
                        self._active += 1
                        reg.inc("queries_admitted")
                        return
                    rem = None if deadline is None \
                        else deadline - time.monotonic()
                    if rem is not None and rem <= 0:
                        raise QueryRejected(
                            query_id, f"query {query_id} rejected: "
                            f"waited {tmo:g}s in the admission queue "
                            "(queueTimeoutSeconds)")
                    self._cond.wait(rem)
            except QueryRejected:
                reg.inc("queries_rejected")
                try:
                    self._queue.remove(token)
                except ValueError:
                    pass
                # the head token may have changed: wake the queue
                self._cond.notify_all()
                raise

    def release(self) -> None:
        """One admitted query finished (success, failure, or cancel):
        free its slot and wake the queue head."""
        with self._cond:
            if self._active > 0:
                self._active -= 1
            self._cond.notify_all()

    def begin_shutdown(self) -> None:
        """Stop admitting: every queued waiter and every future
        ``admit`` raises :class:`QueryRejected`.  Already-admitted
        queries are unaffected (the session drains or cancels them)."""
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

"""df.cache(): materialized columnar caching.

Reference: the spark310 shim's ParquetCachedBatchSerializer
(shims/spark310/.../ParquetCachedBatchSerializer.scala, SURVEY §5.4)
implements ``df.cache()`` as compressed columnar blobs written by the
GPU and rebuilt on read.  Here a cached DataFrame materializes its plan
ONCE (on first use, on the plan's tagged backend) into codec-compressed
Arrow IPC blobs held on the host — backend-independent, compact, and
re-uploaded H2D per execution on the device path — then serves every
subsequent execution as a leaf scan.  ``unpersist()`` frees the blobs.
"""
from __future__ import annotations

import threading
from typing import Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import ConfEntry, register
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode

__all__ = ["CachedScanExec"]

CACHE_CODEC = register(ConfEntry(
    "spark.rapids.sql.cache.compression.codec", "lz4",
    "Codec for df.cache() columnar blobs: none, lz4 or zstd (reference "
    "ParquetCachedBatchSerializer stores compressed columnar parquet "
    "blobs).",
    check=lambda v: v in ("none", "lz4", "zstd"),
    check_doc="must be none|lz4|zstd"))


class CachedScanExec(PlanNode):
    """Leaf serving a materialized (cached) query result."""

    def __init__(self, source: PlanNode, source_backend: str, conf):
        super().__init__([])
        self._source = source
        self._source_backend = source_backend
        self._conf = conf
        from spark_rapids_tpu.shuffle.compression import get_codec
        self._codec_name = conf.get(CACHE_CODEC)
        self._codec = get_codec(self._codec_name)
        self._lock = threading.Lock()
        # per partition: list of (blob, raw_size) compressed Arrow IPC
        self._blobs: list[list[tuple[bytes, int]]] | None = None
        self._nparts: int | None = None
        # last partition count handed to a planner; a re-materialization
        # that produces a DIFFERENT count is refused loudly (a consumer
        # iterating the old count would silently miss or duplicate rows)
        self._advertised: int | None = None
        self.metrics = {"cached_bytes": 0, "raw_bytes": 0}

    @property
    def output_schema(self) -> T.Schema:
        return self._source.output_schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        # planning calls num_partitions (e.g. _lower_aggregate); it must
        # NOT force materialization.  The count is the SOURCE's count on
        # the MATERIALIZATION backend — never the serving ctx's backend:
        # a mesh exec reports different counts per backend, and serving
        # host-first with the host count while blobs were built with the
        # device count silently dropped partitions (review repro)
        with self._lock:
            if self._blobs is not None:
                self._advertised = max(1, len(self._blobs))
                return self._advertised
            if self._nparts is None:
                with ExecCtx(backend=self._source_backend,
                             conf=self._conf) as mctx:
                    self._nparts = max(
                        1, self._source.num_partitions(mctx))
            self._advertised = self._nparts
            return self._nparts

    # -- materialization ----------------------------------------------
    def _ensure(self) -> None:
        with self._lock:
            if self._blobs is not None:
                return
            from spark_rapids_tpu.shuffle.serializer import serialize_batch
            blobs: list[list[tuple[bytes, int]]] = []
            raw_total = comp_total = 0
            with ExecCtx(backend=self._source_backend,
                         conf=self._conf) as ctx:
                for pid in range(self._source.num_partitions(ctx)):
                    part: list[tuple[bytes, int]] = []
                    for b in self._source.partition_iter(ctx, pid):
                        # both batch kinds expose to_arrow(); the
                        # serializer D2Hs device batches itself
                        raw = serialize_batch(b)
                        raw_total += len(raw)
                        if self._codec is not None:
                            blob = self._codec.compress(raw)
                        else:
                            blob = raw
                        comp_total += len(blob)
                        part.append((blob, len(raw)))
                    blobs.append(part)
            if self._advertised is not None and \
                    max(1, len(blobs)) != self._advertised:
                raise RuntimeError(
                    f"cache re-materialized with {len(blobs)} partitions "
                    f"but a plan was built against {self._advertised}; "
                    "a consumer would silently miss rows — re-plan the "
                    "query after unpersist()")
            # metrics assigned only on SUCCESS: a failed materialization
            # must not leave partial counts that a retry double-counts
            self._blobs = blobs
            self.metrics["raw_bytes"] = raw_total
            self.metrics["cached_bytes"] = comp_total

    def unpersist(self) -> None:
        """Free the cached blobs; the next use re-materializes
        (reference: unpersist drops the cached RDD blocks)."""
        with self._lock:
            self._blobs = None
            # a re-materialization may yield a different partition count;
            # a stale cached count would let consumers index past the
            # new blob list
            self._nparts = None
            self.metrics["cached_bytes"] = 0
            self.metrics["raw_bytes"] = 0

    @property
    def is_materialized(self) -> bool:
        return self._blobs is not None

    # -- serving -------------------------------------------------------
    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        from spark_rapids_tpu.io.scan import _arrow_to_host
        from spark_rapids_tpu.shuffle.serializer import deserialize_batch
        # snapshot under the lock, re-materializing if a concurrent
        # unpersist() raced in between: yielding an empty partition
        # would be silently wrong results, not just a crash
        # enginelint: disable=RL004 (re-runs only when a concurrent unpersist() raced; _ensure() either succeeds or raises)
        while True:
            self._ensure()
            with self._lock:
                if self._blobs is not None:
                    if pid >= len(self._blobs):
                        # backstop: count changes across re-materialize
                        # are refused loudly in _ensure(); reaching here
                        # means the consumer's pid never existed
                        raise IndexError(
                            f"cache partition {pid} out of range "
                            f"({len(self._blobs)} materialized)")
                    part = list(self._blobs[pid])
                    break
        for blob, raw_size in part:
            raw = self._codec.decompress(blob, raw_size) \
                if self._codec is not None else blob
            if ctx.is_device:
                yield deserialize_batch(raw, device=True)
            else:
                yield _arrow_to_host(deserialize_batch(raw, device=False),
                                     self.output_schema)

    def node_desc(self) -> str:
        state = "materialized" if self.is_materialized else "lazy"
        return f"CachedScanExec[{state}, codec={self._codec_name}]"

"""Join execs over the sort-merge device kernel.

Reference join surface (SURVEY.md §2.4): GpuShuffledHashJoinExec /
GpuBroadcastHashJoinExec (GpuHashJoin.doJoin, shims/spark300/
GpuHashJoin.scala:193-249), GpuBroadcastNestedLoopJoinExec and
GpuCartesianProductExec (crossJoin + condition filter), with
GpuSortMergeJoinMeta replacing SMJ by shuffled hash join.  Here one
`JoinExec` covers the equi-join types over ops/join.py's sort-merge
kernel, and `CrossJoinExec` the nested-loop/cartesian shape; right outer
runs as a side-swapped left outer (the reference's build-side flip).

Conditions: like the reference's tagJoin (GpuHashJoin.scala:30-45), a
residual non-equi condition is only allowed on inner/cross joins, where
it is applied as a post-join filter.
"""
from __future__ import annotations

from functools import partial
from typing import Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode, RequireSingleBatch
from spark_rapids_tpu.expr.core import (BoundReference, Expression, bind,
                                        eval_device, eval_host)
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.ops import kernels as dk
from spark_rapids_tpu.ops import host_kernels as hk
from spark_rapids_tpu.ops.join import (JOIN_TYPES, gather_join_output,
                                       join_indices_from_probe, join_probe)

__all__ = ["JoinExec", "CrossJoinExec"]


@partial(jax.jit, static_argnames=("lkeys", "rkeys", "join_type"))
def _jit_probe(lb, rb, lkeys, rkeys, join_type):
    """Heavy phase (all sorts): compiled once per (capacities, keys)."""
    probe_arrays, total = join_probe(lb, rb, lkeys, rkeys, join_type)
    # drop the None placeholder for non-full joins (pytree-stable output)
    if probe_arrays[-1] is None:
        probe_arrays = probe_arrays[:-1]
    return probe_arrays, total


@partial(jax.jit, static_argnames=("cl", "join_type", "out_cap",
                                   "include_right", "schema"))
def _jit_gather(lb, rb, probe_arrays, cl, join_type, out_cap, include_right,
                schema):
    """Light phase (gathers only): re-specialized per output capacity."""
    if join_type != "full":
        probe_arrays = probe_arrays + (None,)
    plan = join_indices_from_probe(cl, probe_arrays, join_type, out_cap)
    return gather_join_output(lb, rb, *plan, schema, include_right)


def _nullable_schema(s: T.Schema) -> list[T.StructField]:
    return [T.StructField(f.name, f.data_type, True) for f in s]


class JoinExec(PlanNode):
    """Equi-join: inner | left | right | full | semi | anti.

    ``left_keys``/``right_keys`` are expressions over the respective
    child schemas (the planner has already inserted casts so each pair
    has equal types).  Key expressions are appended as projected columns
    before the kernel and dropped from the output, so non-trivial keys
    (e.g. casts) join correctly.
    """

    def __init__(self, left: PlanNode, right: PlanNode,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str, condition: Expression | None = None):
        if join_type == "right":
            # run as side-swapped left join; output reordered in
            # partition_iter (reference build-side flip)
            self._swapped = True
            left, right = right, left
            left_keys, right_keys = right_keys, left_keys
            join_type = "left"
        else:
            self._swapped = False
        assert join_type in JOIN_TYPES and join_type != "cross", join_type
        if condition is not None and join_type != "inner":
            raise ValueError(
                f"non-equi condition not supported for {join_type} join "
                "(reference tagJoin, GpuHashJoin.scala:30-45)")
        super().__init__([left, right])
        self.join_type = join_type
        self._lkeys_b = [bind(k, left.output_schema) for k in left_keys]
        self._rkeys_b = [bind(k, right.output_schema) for k in right_keys]
        assert len(self._lkeys_b) == len(self._rkeys_b) and self._lkeys_b
        for a, b in zip(self._lkeys_b, self._rkeys_b):
            if type(a.dtype) is not type(b.dtype):
                raise ValueError(f"join key type mismatch: {a.dtype} vs "
                                 f"{b.dtype} (planner must insert casts)")
        self.include_right = join_type not in ("semi", "anti")

        lf = list(left.output_schema.fields)
        rf = list(right.output_schema.fields)
        if join_type == "full":
            lf, rf = _nullable_schema(left.output_schema), \
                _nullable_schema(right.output_schema)
        elif join_type == "left":
            rf = _nullable_schema(right.output_schema)
        joined = lf + rf if self.include_right else lf
        if self._swapped and self.include_right:
            joined = joined[len(lf):] + joined[:len(lf)]
        self._schema = T.Schema(joined)

        self._condition = condition
        if condition is not None:
            cond_schema = T.Schema(list(left.output_schema.fields)
                                   + list(right.output_schema.fields))
            self._cond_b = bind(condition, cond_schema)

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def output_batching(self):
        return RequireSingleBatch

    def num_partitions(self, ctx: ExecCtx) -> int:
        return 1

    # ------------------------------------------------------------------
    def _augment_device(self, batch: ColumnBatch, keys) -> tuple:
        """Append evaluated key columns; return (batch', key_indices)."""
        n = batch.num_columns
        cols = list(batch.columns)
        fields = list(batch.schema.fields)
        idx = []
        for i, k in enumerate(keys):
            if isinstance(k, BoundReference):
                idx.append(k.index)
                continue
            v = eval_device(k, batch)
            cols.append(v)
            fields.append(T.StructField(f"_jk{i}", k.dtype, True))
            idx.append(len(cols) - 1)
        return ColumnBatch(cols, batch.num_rows, T.Schema(fields)), tuple(idx)

    def _augment_host(self, batch: HostBatch, keys) -> tuple:
        cols = list(batch.columns)
        fields = list(batch.schema.fields)
        idx = []
        for i, k in enumerate(keys):
            if isinstance(k, BoundReference):
                idx.append(k.index)
                continue
            v = eval_host(k, batch)
            cols.append(v)
            fields.append(T.StructField(f"_jk{i}", k.dtype, True))
            idx.append(len(cols) - 1)
        return HostBatch(cols, T.Schema(fields)), tuple(idx)

    def _materialize(self, ctx: ExecCtx, which: int):
        batches = []
        child = self.children[which]
        for pid in range(child.num_partitions(ctx)):
            batches.extend(child.partition_iter(ctx, pid))
        if ctx.is_device:
            if not batches:
                from spark_rapids_tpu.exec.core import host_to_device
                return host_to_device(HostBatch.empty(child.output_schema))
            return dk.concat_batches(batches) if len(batches) > 1 \
                else batches[0]
        if not batches:
            return HostBatch.empty(child.output_schema)
        return hk.host_concat(batches)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        lb = self._materialize(ctx, 0)
        rb = self._materialize(ctx, 1)
        if ctx.is_device:
            yield from self._run_device(ctx, lb, rb)
        else:
            yield from self._run_host(ctx, lb, rb)

    # ------------------------------------------------------------------
    def _run_device(self, ctx: ExecCtx, lb: ColumnBatch, rb: ColumnBatch):
        lb2, lkeys = self._augment_device(lb, self._lkeys_b)
        rb2, rkeys = self._augment_device(rb, self._rkeys_b)
        probe_arrays, total_dev = _jit_probe(
            lb2, rb2, lkeys, rkeys, self.join_type)
        total = int(jax.device_get(total_dev))
        out_cap = round_capacity(max(total, 1))
        # kernel output: ALL left cols (incl appended keys) + right cols
        kf = (list(lb2.schema.fields)
              + (list(rb2.schema.fields) if self.include_right else []))
        out = _jit_gather(lb2, rb2, probe_arrays, lb2.capacity,
                          self.join_type, out_cap, self.include_right,
                          T.Schema(kf))
        out = self._project_out(out, lb, rb, lb2, rb2, device=True)
        if self._condition is not None:
            c = eval_device(self._cond_b, out)
            out = dk.compact(out, c.data & c.validity)
        if self._swapped and self.include_right:
            out = self._reorder_device(out, lb.num_columns)
        yield ColumnBatch(out.columns, out.num_rows, self._schema)

    def _run_host(self, ctx: ExecCtx, lb: HostBatch, rb: HostBatch):
        lb2, lkeys = self._augment_host(lb, self._lkeys_b)
        rb2, rkeys = self._augment_host(rb, self._rkeys_b)
        li, ri, lt, rt = hk.host_join(lb2, rb2, list(lkeys), list(rkeys),
                                      self.join_type)
        kf = (list(lb2.schema.fields)
              + (list(rb2.schema.fields) if self.include_right else []))
        out = hk.host_join_output(lb2, rb2, li, ri, lt, rt, T.Schema(kf),
                                  self.include_right)
        out = self._project_out(out, lb, rb, lb2, rb2, device=False)
        if self._condition is not None:
            c = eval_host(self._cond_b, out)
            out = hk.host_filter(out, c.data.astype(np.bool_) & c.validity)
        cols = list(out.columns)
        if self._swapped and self.include_right:
            nl = lb.num_columns
            cols = cols[nl:] + cols[:nl]
        yield HostBatch(cols, self._schema)

    def _project_out(self, out, lb, rb, lb2, rb2, device: bool):
        """Drop appended key columns from the kernel output."""
        keep = list(range(lb.num_columns))
        if self.include_right:
            keep += [lb2.num_columns + i for i in range(rb.num_columns)]
        cols = [out.columns[i] for i in keep]
        fields = [out.schema.fields[i] for i in keep]
        if device:
            return ColumnBatch(cols, out.num_rows, T.Schema(fields))
        return HostBatch(cols, T.Schema(fields))

    def _reorder_device(self, out: ColumnBatch, nl: int) -> ColumnBatch:
        cols = list(out.columns)
        cols = cols[nl:] + cols[:nl]
        return ColumnBatch(cols, out.num_rows, self._schema)

    def node_desc(self) -> str:
        jt = "right" if self._swapped else self.join_type
        return f"JoinExec[{jt}, keys={len(self._lkeys_b)}]"


class CrossJoinExec(JoinExec):
    """Cartesian product with optional condition (reference
    GpuCartesianProductExec / GpuBroadcastNestedLoopJoinExec)."""

    def __init__(self, left: PlanNode, right: PlanNode,
                 condition: Expression | None = None):
        PlanNode.__init__(self, [left, right])
        self._swapped = False
        self.join_type = "cross"
        self._lkeys_b = []
        self._rkeys_b = []
        self.include_right = True
        self._schema = T.Schema(list(left.output_schema.fields)
                                + list(right.output_schema.fields))
        self._condition = condition
        if condition is not None:
            self._cond_b = bind(condition, self._schema)

    def node_desc(self) -> str:
        return "CrossJoinExec" + (
            "[cond]" if self._condition is not None else "")

"""Join execs over the sort-merge device kernel.

Reference join surface (SURVEY.md §2.4): GpuShuffledHashJoinExec /
GpuBroadcastHashJoinExec (GpuHashJoin.doJoin, shims/spark300/
GpuHashJoin.scala:193-249), GpuBroadcastNestedLoopJoinExec and
GpuCartesianProductExec (crossJoin + condition filter), with
GpuSortMergeJoinMeta replacing SMJ by shuffled hash join.  Here one
`JoinExec` covers the equi-join types over ops/join.py's sort-merge
kernel, and `CrossJoinExec` the nested-loop/cartesian shape; right outer
runs as a side-swapped left outer (the reference's build-side flip).

Conditions: like the reference's tagJoin (GpuHashJoin.scala:30-45), a
residual non-equi condition is only allowed on inner/cross joins, where
it is applied as a post-join filter.
"""
from __future__ import annotations

from functools import partial
from typing import Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.compile_cache import guarded_jit
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.expr.core import (BoundReference, Expression, bind,
                                        eval_device, eval_host)
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.ops import kernels as dk
from spark_rapids_tpu.ops import host_kernels as hk
from spark_rapids_tpu.ops.join import (JOIN_TYPES, build_prepare_fast,
                                       gather_join_output,
                                       join_indices_from_probe, join_probe,
                                       matched_build_rows, probe_fast)

__all__ = ["JoinExec", "CrossJoinExec", "BroadcastHashJoinExec"]


@guarded_jit(static_argnames=("lkeys", "rkeys", "join_type"))
def _jit_probe(lb, rb, lkeys, rkeys, join_type):
    """Heavy rank-path phase (all sorts): compiled once per capacity pair."""
    probe_arrays, total = join_probe(lb, rb, lkeys, rkeys, join_type)
    # drop the None placeholder for non-full joins (pytree-stable output)
    if probe_arrays[-1] is None:
        probe_arrays = probe_arrays[:-1]
    return probe_arrays, total


@guarded_jit(static_argnames=("rkey",))
def _jit_build_prep(rb, rkey):
    return build_prepare_fast(rb, rkey)


@guarded_jit(static_argnames=("lkey", "join_type"))
def _jit_probe_fast(lb, prep, lkey, join_type):
    probe_arrays, total = probe_fast(lb, lkey, *prep, join_type)
    return probe_arrays[:-1], total  # drop the None placeholder


@guarded_jit(static_argnames=("cl", "join_type", "out_cap",
                                   "include_right", "schema",
                                   "track_matched"))
def _jit_gather(lb, rb, probe_arrays, cl, join_type, out_cap, include_right,
                schema, track_matched=False):
    """Light phase (gathers only): re-specialized per output capacity."""
    if len(probe_arrays) == 4:
        probe_arrays = probe_arrays + (None,)
    plan = join_indices_from_probe(cl, probe_arrays, join_type, out_cap)
    out = gather_join_output(lb, rb, *plan, schema, include_right)
    if track_matched:
        li, ri, l_take, r_take, total = plan
        return out, matched_build_rows(ri, r_take, rb.capacity)
    return out


def _nullable_schema(s: T.Schema) -> list[T.StructField]:
    return [T.StructField(f.name, f.data_type, True) for f in s]


class JoinExec(PlanNode):
    """Equi-join: inner | left | right | full | semi | anti.

    ``left_keys``/``right_keys`` are expressions over the respective
    child schemas (the planner has already inserted casts so each pair
    has equal types).  Key expressions are appended as projected columns
    before the kernel and dropped from the output, so non-trivial keys
    (e.g. casts) join correctly.
    """

    #: stream batches whose probe totals sync to host in one stacked
    #: device_get (see _run_device_stream)
    _SYNC_CHUNK = 8

    def __init__(self, left: PlanNode, right: PlanNode,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str, condition: Expression | None = None):
        user_join_type = join_type  # pre-swap, for user-facing errors
        if join_type == "right":
            # run as side-swapped left join; output reordered in
            # partition_iter (reference build-side flip)
            self._swapped = True
            left, right = right, left
            left_keys, right_keys = right_keys, left_keys
            join_type = "left"
        else:
            self._swapped = False
        assert join_type in JOIN_TYPES and join_type != "cross", join_type
        if condition is not None and join_type != "inner":
            raise ValueError(
                f"non-equi condition not supported for {user_join_type} "
                "join (reference tagJoin, GpuHashJoin.scala:30-45)")
        super().__init__([left, right])
        from spark_rapids_tpu.expr.misc import reject_partition_aware
        reject_partition_aware(list(left_keys) + list(right_keys)
                               + [condition], "join keys/conditions")
        self.join_type = join_type
        self._lkeys_b = [bind(k, left.output_schema) for k in left_keys]
        self._rkeys_b = [bind(k, right.output_schema) for k in right_keys]
        assert len(self._lkeys_b) == len(self._rkeys_b) and self._lkeys_b
        for a, b in zip(self._lkeys_b, self._rkeys_b):
            if type(a.dtype) is not type(b.dtype):
                raise ValueError(f"join key type mismatch: {a.dtype} vs "
                                 f"{b.dtype} (planner must insert casts)")
            if isinstance(a.dtype, T.ArrayType):
                raise ValueError("cannot join on an array column")
        self.include_right = join_type not in ("semi", "anti")

        lf = list(left.output_schema.fields)
        rf = list(right.output_schema.fields)
        if join_type == "full":
            lf, rf = _nullable_schema(left.output_schema), \
                _nullable_schema(right.output_schema)
        elif join_type == "left":
            rf = _nullable_schema(right.output_schema)
        joined = lf + rf if self.include_right else lf
        if self._swapped and self.include_right:
            joined = joined[len(lf):] + joined[:len(lf)]
        self._schema = T.Schema(joined)

        self._condition = condition
        if condition is not None:
            cond_schema = T.Schema(list(left.output_schema.fields)
                                   + list(right.output_schema.fields))
            self._cond_b = bind(condition, cond_schema)

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def bound_exprs(self):
        out = list(self._lkeys_b) + list(self._rkeys_b)
        if self._condition is not None:
            out.append(self._cond_b)
        return out

    def num_partitions(self, ctx: ExecCtx) -> int:
        # stream-side partitioning is preserved (per-left-row join types);
        # full outer needs one pass to emit unmatched build rows at the end
        if self.join_type == "full":
            return 1
        return self.children[0].num_partitions(ctx)

    # ------------------------------------------------------------------
    def _augment_device(self, batch: ColumnBatch, keys) -> tuple:
        """Append evaluated key columns; return (batch', key_indices).

        Also traced inside mesh-region programs (MeshJoinExec._region_step
        runs it under shard_map): must stay free of host syncs and of
        control flow on traced values."""
        n = batch.num_columns
        cols = list(batch.columns)
        fields = list(batch.schema.fields)
        idx = []
        for i, k in enumerate(keys):
            if isinstance(k, BoundReference):
                idx.append(k.index)
                continue
            v = eval_device(k, batch)
            cols.append(v)
            fields.append(T.StructField(f"_jk{i}", k.dtype, True))
            idx.append(len(cols) - 1)
        return ColumnBatch(cols, batch.num_rows, T.Schema(fields)), tuple(idx)

    def _augment_host(self, batch: HostBatch, keys) -> tuple:
        cols = list(batch.columns)
        fields = list(batch.schema.fields)
        idx = []
        for i, k in enumerate(keys):
            if isinstance(k, BoundReference):
                idx.append(k.index)
                continue
            v = eval_host(k, batch)
            cols.append(v)
            fields.append(T.StructField(f"_jk{i}", k.dtype, True))
            idx.append(len(cols) - 1)
        return HostBatch(cols, T.Schema(fields)), tuple(idx)

    def _materialize(self, ctx: ExecCtx, which: int):
        from spark_rapids_tpu.exec.core import drain_partitions
        child = self.children[which]
        batches = list(drain_partitions(ctx, child))
        if ctx.is_device:
            if not batches:
                from spark_rapids_tpu.exec.core import host_to_device
                return host_to_device(HostBatch.empty(child.output_schema))
            return dk.concat_batches(batches) if len(batches) > 1 \
                else batches[0]
        if not batches:
            return HostBatch.empty(child.output_schema)
        return hk.host_concat(batches)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        if ctx.is_device:
            yield from self._run_device_stream(ctx, pid)
        else:
            rb = ctx.cached((id(self), "host_build"),
                            lambda: self._materialize(ctx, 1))
            child = self.children[0]
            pids = range(child.num_partitions(ctx)) \
                if self.join_type == "full" else [pid]
            batches = [b for p in pids for b in child.partition_iter(ctx, p)]
            lb = hk.host_concat(batches) if batches \
                else HostBatch.empty(child.output_schema)
            yield from self._run_host(ctx, lb, rb)

    # ------------------------------------------------------------------
    # Device path: build side prepared once (sorted keys for the fast
    # searchsorted probe, reference GpuHashJoin's build-side table,
    # GpuHashJoin.scala:193-249), then the stream side is joined PER
    # BATCH — no whole-side concat, no per-batch sort on the fast path.
    def _use_fast_path(self) -> bool:
        if len(self._lkeys_b) != 1:
            return False
        lt, rt = self._lkeys_b[0].dtype, self._rkeys_b[0].dtype
        return (not lt.fractional and not rt.fractional
                and not isinstance(lt, (T.StringType, T.BooleanType))
                and not isinstance(rt, (T.StringType, T.BooleanType))
                and type(lt) is type(rt))

    def _build_device(self, ctx: ExecCtx):
        def build():
            rb = self._materialize(ctx, 1)
            rb2, rkeys = self._augment_device(rb, self._rkeys_b)
            prep = _jit_build_prep(rb2, rkeys[0]) \
                if self.join_type != "cross" and self._use_fast_path() \
                else None
            return rb2, rkeys, prep
        return ctx.cached((id(self), "build"), build)

    def _stream_batches(self, ctx: ExecCtx, pid: int):
        """Stream-side batches for one output partition (hook:
        MeshJoinExec serves device-placed shards instead)."""
        child = self.children[0]
        pids = range(child.num_partitions(ctx)) \
            if self.join_type == "full" else [pid]
        for lpid in pids:
            yield from child.partition_iter(ctx, lpid)

    def _device_build(self, ctx: ExecCtx, pid: int):
        """(build batch, key idx, prep) for one output partition (hook:
        MeshJoinExec replicates the build side onto partition devices)."""
        return self._build_device(ctx)

    def _run_device_stream(self, ctx: ExecCtx, pid: int):
        rb2, rkeys, prep = self._device_build(ctx, pid)
        jt = self.join_type
        stream_jt = "left" if jt == "full" else jt
        n_right_raw = len(self.children[1].output_schema.fields)
        kf = (list(self._stream_aug_fields())
              + (list(rb2.schema.fields) if self.include_right else []))
        kf_schema = T.Schema(kf)
        matched = None

        # Probe totals sync in CHUNKS: each stream batch's match count
        # must reach the host to pick the static gather capacity, but a
        # host round trip over a tunneled backend costs tens of ms of
        # pure latency — so up to _SYNC_CHUNK probes are dispatched
        # asynchronously and their totals fetched in ONE device_get of
        # a stacked vector (one barrier per chunk, not per batch).
        # Each pending entry retains its stream batch: an OOM surfacing
        # at the stacked sync (where async backends report it) is
        # recovered by re-probing from the retained batches through the
        # splitting retry scope — a split stream batch just produces
        # two gathers instead of one.
        def probe(piece):
            lb2, lkeys = self._augment_device(piece, self._lkeys_b)
            if prep is not None:
                probe_arrays, total_dev = _jit_probe_fast(
                    lb2, prep, lkeys[0], stream_jt)
            else:
                probe_arrays, total_dev = _jit_probe(
                    lb2, rb2, lkeys, rkeys, stream_jt)
            return lb2, total_dev, probe_arrays

        def probe_entries(lb) -> list:
            return [(piece, l2, td, pa) for piece, (l2, td, pa)
                    in ctx.dispatch_retry(probe, lb, op="join_probe",
                                          pairs=True)]

        def flush(pending):
            nonlocal matched
            if not pending:
                return

            def redo() -> None:
                pending[:] = [e for p in pending
                              for e in probe_entries(p[0])]

            def sync_totals():
                if len(pending) == 1:
                    # enginelint: disable=RL003 (single-entry fast path; one scalar sync)
                    return [int(jax.device_get(pending[0][2]))]
                # enginelint: disable=RL003 (stacked transfer for all pending probes; this IS the batched sync)
                return [int(t) for t in jax.device_get(ctx.dispatch(
                    jnp.stack, [p[2] for p in pending]))]

            totals = ctx.retry_sync(sync_totals, redo=redo,
                                    op="join_flush")
            for (lb, lb2, _td, probe_arrays), total in zip(pending, totals):
                if total == 0:
                    if jt == "full" and matched is None:
                        matched = jnp.zeros(rb2.capacity, jnp.bool_)
                    continue
                out_cap = round_capacity(max(total, 1))
                if jt == "full":
                    out, bm = ctx.dispatch(
                        _jit_gather, lb2, rb2, probe_arrays, lb2.capacity,
                        stream_jt, out_cap, self.include_right, kf_schema,
                        track_matched=True)
                    matched = bm if matched is None else matched | bm
                else:
                    out = ctx.dispatch(
                        _jit_gather, lb2, rb2, probe_arrays, lb2.capacity,
                        stream_jt, out_cap, self.include_right, kf_schema)
                out = self._project_out(
                    out, lb.num_columns, lb2.num_columns, n_right_raw,
                    device=True)
                if self._condition is not None:
                    out = self._condition_jit()(out)
                if self._swapped and self.include_right:
                    out = self._reorder_device(out, lb.num_columns)
                yield ColumnBatch(out.columns, out.num_rows, self._schema)

        pending = []
        for lb in self._stream_batches(ctx, pid):
            pending.extend(probe_entries(lb))
            if len(pending) >= self._SYNC_CHUNK:
                yield from flush(pending)
                pending = []
        yield from flush(pending)
        if jt == "full":
            if matched is None:
                matched = jnp.zeros(rb2.capacity, jnp.bool_)
            tail = self._unmatched_right_jit()(rb2, matched)
            if tail.host_num_rows() > 0:
                yield tail

    def _stream_aug_fields(self):
        """Fields of an augmented stream batch (left schema + appended
        non-BoundReference key columns)."""
        fields = list(self.children[0].output_schema.fields)
        for i, k in enumerate(self._lkeys_b):
            if not isinstance(k, BoundReference):
                fields.append(T.StructField(f"_jk{i}", k.dtype, True))
        return fields

    def _condition_jit(self):
        if not hasattr(self, "_cond_jit"):
            from spark_rapids_tpu.exec import compile_cache as cc

            def filt(out):
                c = eval_device(self._cond_b, out)
                return dk.compact(out, c.data & c.validity)
            self._cond_jit = cc.shared_jit(
                cc.fragment_key("join_cond", self._cond_b), filt)
        return self._cond_jit

    def _unmatched_right_jit(self):
        """Full outer tail: build rows never matched by any stream batch,
        null-extended on the left (reference fullJoin's right coverage)."""
        if not hasattr(self, "_unmatched_jit"):
            left_fields = list(self.children[0].output_schema.fields)
            right_schema = self.children[1].output_schema
            n_right = len(right_schema.fields)

            def fn(rb2, matched):
                keep = rb2.row_mask() & ~matched
                rraw = ColumnBatch(rb2.columns[:n_right], rb2.num_rows,
                                   right_schema)
                rc = dk.compact(rraw, keep)
                cap = rb2.capacity
                null_cols = []
                for f in left_fields:
                    validity = jnp.zeros(cap, jnp.bool_)
                    if isinstance(f.data_type,
                                  (T.StringType, T.ArrayType)):
                        elem = np.uint8 if isinstance(
                            f.data_type, T.StringType) \
                            else f.data_type.np_dtype
                        null_cols.append(DeviceColumn(
                            jnp.zeros((cap, 1), elem), validity,
                            f.data_type, jnp.zeros(cap, jnp.int32)))
                    else:
                        null_cols.append(DeviceColumn(
                            jnp.zeros(cap, f.data_type.np_dtype), validity,
                            f.data_type))
                return ColumnBatch(null_cols + list(rc.columns),
                                   rc.num_rows, self._schema)

            from spark_rapids_tpu.exec import compile_cache as cc
            self._unmatched_jit = cc.shared_jit(
                cc.fragment_key("join_unmatched", left_fields, right_schema,
                                self._schema), fn)
        return self._unmatched_jit

    def _run_host(self, ctx: ExecCtx, lb: HostBatch, rb: HostBatch):
        lb2, lkeys = self._augment_host(lb, self._lkeys_b)
        rb2, rkeys = self._augment_host(rb, self._rkeys_b)
        li, ri, lt, rt = hk.host_join(lb2, rb2, list(lkeys), list(rkeys),
                                      self.join_type)
        kf = (list(lb2.schema.fields)
              + (list(rb2.schema.fields) if self.include_right else []))
        out = hk.host_join_output(lb2, rb2, li, ri, lt, rt, T.Schema(kf),
                                  self.include_right)
        out = self._project_out(out, lb.num_columns, lb2.num_columns,
                                rb.num_columns, device=False)
        if self._condition is not None:
            c = eval_host(self._cond_b, out)
            out = hk.host_filter(out, c.data.astype(np.bool_) & c.validity)
        cols = list(out.columns)
        if self._swapped and self.include_right:
            nl = lb.num_columns
            cols = cols[nl:] + cols[:nl]
        yield HostBatch(cols, self._schema)

    def _project_out(self, out, n_left_raw: int, n_left_aug: int,
                     n_right_raw: int, device: bool):
        """Drop appended key columns from the kernel output.

        The device branch is traced inside mesh-region programs; keep the
        column selection static (pure python ints, no traced values)."""
        keep = list(range(n_left_raw))
        if self.include_right:
            keep += [n_left_aug + i for i in range(n_right_raw)]
        cols = [out.columns[i] for i in keep]
        fields = [out.schema.fields[i] for i in keep]
        if device:
            return ColumnBatch(cols, out.num_rows, T.Schema(fields))
        return HostBatch(cols, T.Schema(fields))

    def _reorder_device(self, out: ColumnBatch, nl: int) -> ColumnBatch:
        cols = list(out.columns)
        cols = cols[nl:] + cols[:nl]
        return ColumnBatch(cols, out.num_rows, self._schema)

    def node_desc(self) -> str:
        jt = "right" if self._swapped else self.join_type
        return f"JoinExec[{jt}, keys={len(self._lkeys_b)}]"


class BroadcastHashJoinExec(JoinExec):
    """Broadcast-build equi-join: the build child is a single-partition
    node (BroadcastExchangeExec) materialized whole, the stream side is
    probed per batch with no shuffle (reference GpuBroadcastHashJoinExec).

    Execution is exactly JoinExec's device/host paths — the build side's
    ``_materialize`` drains one broadcast partition instead of a shuffled
    exchange.  Exists as its own class so AQE's shuffle-join -> broadcast
    switch is visible in EXPLAIN (ANALYZE) and so plan fingerprints stay
    honest about the strategy that actually ran."""

    @classmethod
    def from_shuffled(cls, join: JoinExec, probe: PlanNode,
                      build: PlanNode) -> "BroadcastHashJoinExec":
        """Re-strategize an existing JoinExec around (probe, build)
        children without re-binding: key expressions were bound against
        the child SCHEMAS, which the new children preserve — so the
        compile-cache fragment keys (join_cond/join_unmatched) and the
        guarded-jit structural keys are byte-identical to the static
        plan's, and a warm rerun of the re-planned query compiles
        nothing."""
        nj = object.__new__(cls)
        nj.__dict__.update(join.__dict__)
        # lazily-built jit wrappers close over the originating node; let
        # the new node rebuild its own (same fragment keys -> cache hits)
        nj.__dict__.pop("_cond_jit", None)
        nj.__dict__.pop("_unmatched_jit", None)
        nj.children = (probe, build)
        return nj

    def node_desc(self) -> str:
        jt = "right" if self._swapped else self.join_type
        return f"BroadcastHashJoinExec[{jt}, keys={len(self._lkeys_b)}]"


class CrossJoinExec(JoinExec):
    """Cartesian product with optional condition (reference
    GpuCartesianProductExec / GpuBroadcastNestedLoopJoinExec)."""

    def __init__(self, left: PlanNode, right: PlanNode,
                 condition: Expression | None = None):
        PlanNode.__init__(self, [left, right])
        self._swapped = False
        self.join_type = "cross"
        self._lkeys_b = []
        self._rkeys_b = []
        self.include_right = True
        self._schema = T.Schema(list(left.output_schema.fields)
                                + list(right.output_schema.fields))
        self._condition = condition
        if condition is not None:
            self._cond_b = bind(condition, self._schema)

    def node_desc(self) -> str:
        return "CrossJoinExec" + (
            "[cond]" if self._condition is not None else "")

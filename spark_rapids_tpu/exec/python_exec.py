"""Arrow-eval Python (pandas) UDF exec.

Reference: GpuArrowEvalPythonExec (GpuArrowEvalPythonExec.scala:46-456)
streams device batches as Arrow IPC to external python workers running
pandas scalar UDFs, reads Arrow results back to the device, with
PythonWorkerSemaphore capping concurrent workers.  This engine is
already a python process, so the data plane degenerates to an in-process
Arrow conversion: device batch -> pandas Series -> vectorized UDF ->
device column; the semaphore survives as a concurrency bound
(spark.rapids.python.concurrentPythonWorkers) because pandas UDFs run on
drain worker threads.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Iterator, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.conf import ConfEntry, register
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.expr.core import Expression, bind
from spark_rapids_tpu.host.batch import HostBatch, HostColumn
from spark_rapids_tpu.ops import host_kernels as hk

__all__ = ["PandasUDF", "pandas_udf", "ArrowEvalPythonExec",
           "PandasAggUDF", "pandas_agg_udf", "MapInPandasExec",
           "FlatMapGroupsInPandasExec", "AggregateInPandasExec",
           "FlatMapCoGroupsInPandasExec"]

CONCURRENT_PYTHON = register(ConfEntry(
    "spark.rapids.python.concurrentPythonWorkers", 2,
    "Concurrent pandas-UDF evaluations (reference PythonWorkerSemaphore,"
    " PythonWorkerSemaphore.scala:42-100).", conv=int))

_sem_lock = threading.Lock()
_sems: dict[int, threading.BoundedSemaphore] = {}


def _py_semaphore(n: int) -> threading.BoundedSemaphore:
    with _sem_lock:
        if n not in _sems:
            _sems[n] = threading.BoundedSemaphore(n)
        return _sems[n]


_slot_tls = threading.local()


@contextmanager
def _udf_slot(sem: threading.BoundedSemaphore, lifecycle=None):
    """Per-thread REENTRANT semaphore hold: a chain of streaming pandas
    execs in one thread (map_in_pandas over map_in_pandas) pulls child
    batches while the downstream UDF slot is held — counting each level
    against the semaphore would self-deadlock once the chain is longer
    than the permit count, so the whole chain consumes ONE worker slot
    (the reference's semaphore also counts python WORKERS, not plan
    depth — PythonWorkerSemaphore.scala:42-100).

    ``lifecycle`` (the query's exec/lifecycle.py handle) makes the
    acquire a cancellation point: a cancelled query never queues new
    UDF evaluations behind the concurrentPythonWorkers semaphore, and
    one already waiting wakes at the next poll instead of after the
    UDF ahead of it finishes."""
    depth = getattr(_slot_tls, "depth", 0)
    if depth == 0:
        if lifecycle is None:
            sem.acquire()
        else:
            lifecycle.check()
            while not sem.acquire(timeout=0.05):
                lifecycle.check()
    _slot_tls.depth = depth + 1
    try:
        yield
    finally:
        _slot_tls.depth = depth
        if depth == 0:
            sem.release()


class PandasUDF(Expression):
    """Vectorized python UDF over pandas Series — planned into an
    ArrowEvalPythonExec, never evaluated inline (like WindowExpression)."""

    sql_name = "PandasUDF"

    def __init__(self, fn: Callable, children: Sequence[Expression],
                 return_type: T.DataType):
        self.fn = fn
        self.children = tuple(children)
        self.return_type = return_type

    def with_new_children(self, children):
        return PandasUDF(self.fn, children, self.return_type)

    @property
    def dtype(self):
        return self.return_type

    @property
    def nullable(self):
        return True

    def _eval(self, vals, ctx):
        raise ValueError(
            "PandasUDF must be planned by ArrowEvalPythonExec "
            "(use it directly inside select())")

    def __repr__(self):
        name = getattr(self.fn, "__name__", "<lambda>")
        return f"PandasUDF({name}, {', '.join(map(repr, self.children))})"


def _host_col_to_series(v, exact_int=False):
    """HostColumn -> pandas Series with nulls surfaced as None/NaN
    (numeric columns upcast to float64 only when nulls are present).

    exact_int: nullable INTEGRAL columns use pandas' nullable Int64
    instead of the float64 upcast — int64 values >= 2**53 are not
    representable in float64, so group keys routed through float would
    merge distinct keys and round-trip lossily.  Used for group-key
    columns; UDF inputs keep the float64 convention (Spark's own Arrow
    path hands pandas UDFs float64 for nullable ints)."""
    import pandas as pd
    if isinstance(v.dtype, T.StringType):
        return pd.Series(v.data)
    if not np.all(v.validity) and v.dtype.numeric:
        if exact_int and v.dtype.integral:
            s = pd.Series(v.data, dtype="Int64")
            s[~np.asarray(v.validity)] = pd.NA
            return s
        data = v.data.astype("float64")
    else:
        data = v.data
    s = pd.Series(data)
    if not np.all(v.validity):
        s[~np.asarray(v.validity)] = None
    return s


def pandas_udf(fn: Callable, return_type: T.DataType | None = None):
    """``df.select(pandas_udf(lambda s: s * 2)(col("a")))`` — ``fn``
    receives pandas Series and returns a Series/array of the same
    length."""

    def apply(*cols):
        return PandasUDF(fn, list(cols), return_type or T.DoubleType())

    return apply


class ArrowEvalPythonExec(PlanNode):
    """Append one column per pandas UDF to each child batch.

    The child batch crosses D2H as Arrow, the UDFs run vectorized over
    pandas Series, and results transfer back H2D (reference
    GpuArrowPythonRunner's writeArrowIPCChunked round trip :376-432)."""

    def __init__(self, udfs: Sequence, child: PlanNode):
        super().__init__([child])
        self._udfs = []  # (name, PandasUDF with bound children)
        cs = child.output_schema
        fields = list(cs.fields)
        for name, u in udfs:
            bound = [bind(c, cs) for c in u.children]
            self._udfs.append((name, PandasUDF(u.fn, bound, u.return_type)))
            fields.append(T.StructField(name, u.return_type, True))
        self._schema = T.Schema(fields)

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def bound_exprs(self):
        # PandasUDF itself is exec-planned; expose only its INPUT
        # expressions for tagging
        return [c for _, u in self._udfs for c in u.children]

    def _series_inputs(self, hb: HostBatch, u: PandasUDF):
        from spark_rapids_tpu.expr.core import eval_host
        return [_host_col_to_series(eval_host(c, hb)) for c in u.children]

    def _apply_udfs(self, hb: HostBatch, ctx: ExecCtx) -> HostBatch:
        import pandas as pd
        sem = _py_semaphore(ctx.conf.get(CONCURRENT_PYTHON))
        cols = list(hb.columns)
        for name, u in self._udfs:
            with _udf_slot(sem, ctx.lifecycle):
                result = u.fn(*self._series_inputs(hb, u))
            r = pd.Series(result)
            if len(r) != hb.num_rows:
                raise ValueError(
                    f"pandas UDF {name} returned {len(r)} rows for "
                    f"{hb.num_rows} input rows")
            validity = ~r.isna().to_numpy()
            if isinstance(u.return_type, T.StringType):
                data = np.array([None if not v else str(x)
                                 for x, v in zip(r, validity)], dtype=object)
            else:
                data = r.fillna(0).to_numpy().astype(u.return_type.np_dtype)
            cols.append(HostColumn(data, validity, u.return_type))
        return HostBatch(cols, self._schema)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        from spark_rapids_tpu.exec.core import device_to_host, host_to_device
        for b in self.children[0].partition_iter(ctx, pid):
            if ctx.is_device:
                hb = device_to_host(b)
                out = self._apply_udfs(hb, ctx)
                yield host_to_device(out)
            else:
                yield self._apply_udfs(b, ctx)

    def node_desc(self) -> str:
        return (f"ArrowEvalPythonExec[{[n for n, _ in self._udfs]}]")


# ---------------------------------------------------------------------------
# pandas exec family: iterator / grouped / cogrouped / aggregating variants
# (reference sql-plugin .../execution/python/: GpuMapInPandasExec.scala:141,
# GpuFlatMapGroupsInPandasExec.scala:180, GpuAggregateInPandasExec.scala:198,
# GpuFlatMapCoGroupsInPandasExec.scala:167 — all stream device batches over
# the Arrow boundary to pandas workers; here the worker is in-process and
# the semaphore bounds concurrent UDF evaluation the same way)
# ---------------------------------------------------------------------------

def _to_pandas(hb: HostBatch, exact_keys: "list[str] | None" = None):
    """Arrow-convention pandas frame (nullable ints with nulls become
    float64, what Spark's Arrow path hands pandas UDFs) — except the
    ``exact_keys`` columns, which convert as nullable Int64: GROUPS are
    formed from these frames here (Spark forms them JVM-side, exactly),
    and a float64 round trip merges distinct int64 keys >= 2**53
    (advisor r4 / review finding)."""
    pdf = hb.to_arrow().to_pandas()
    for k in exact_keys or ():
        f = hb.schema.field(k)
        if f.data_type.integral:
            pdf[k] = _host_col_to_series(
                hb.columns[hb.schema.index_of(k)], exact_int=True)
    return pdf


def _from_pandas(pdf, schema: T.Schema, what: str) -> HostBatch:
    """Validate + convert a UDF's output DataFrame against the declared
    schema: labeled columns match by NAME, unlabeled (RangeIndex) by
    position — Spark's assignment rules for mapInPandas/applyInPandas."""
    import pandas as pd
    import pyarrow as pa
    if not isinstance(pdf, pd.DataFrame):
        raise TypeError(f"{what} must produce pandas DataFrames, got "
                        f"{type(pdf).__name__}")
    names = list(schema.names)
    if all(isinstance(c, int) for c in pdf.columns):
        if len(pdf.columns) != len(names):
            raise ValueError(
                f"{what} returned {len(pdf.columns)} unlabeled columns "
                f"for schema {names}")
        pdf = pdf.set_axis(names, axis=1)
    else:
        missing = [n for n in names if n not in pdf.columns]
        if missing:
            raise ValueError(f"{what} output is missing columns {missing} "
                             f"(has {list(pdf.columns)})")
        pdf = pdf[names]
    arrays = [pa.array(pdf[n], type=T.to_arrow(f.data_type),
                       from_pandas=True) for n, f in zip(names, schema)]
    rb = pa.RecordBatch.from_arrays(arrays, schema=schema.to_arrow())
    return HostBatch.from_arrow(rb)


def _host_batches(node: PlanNode, ctx: ExecCtx, pid: int):
    from spark_rapids_tpu.exec.core import device_to_host
    for b in node.partition_iter(ctx, pid):
        yield device_to_host(b) if ctx.is_device else b


def _emit(hb: HostBatch, ctx: ExecCtx):
    from spark_rapids_tpu.exec.core import host_to_device
    return host_to_device(hb) if ctx.is_device else hb


class MapInPandasExec(PlanNode):
    """df.map_in_pandas(fn, schema): ``fn`` receives an ITERATOR of
    pandas DataFrames (one partition's batches) and yields DataFrames
    conforming to ``schema`` — output row count is unconstrained
    (reference GpuMapInPandasExec.scala:60-141)."""

    def __init__(self, fn: Callable, out_schema: T.Schema, child: PlanNode):
        super().__init__([child])
        self._fn = fn
        self._schema = out_schema

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        sem = _py_semaphore(ctx.conf.get(CONCURRENT_PYTHON))
        # materialize the partition's inputs BEFORE taking a worker
        # slot: next(it) runs arbitrary UDF code, and if it also pulled
        # un-executed upstream stages (a shuffle drain on other worker
        # threads, themselves competing for permits) a held permit
        # could deadlock the pool — with a plain list the pull is pure
        # python (review finding; FlatMapGroups/Aggregate already
        # materialize their partition the same way)
        pdfs = [_to_pandas(hb) for hb in
                _host_batches(self.children[0], ctx, pid)]
        it = self._fn(iter(pdfs))
        while True:
            # slot held only around the UDF body (runs inside next()
            # for generator UDFs); reentrant so chained pandas execs in
            # one thread consume a single worker slot
            with _udf_slot(sem, ctx.lifecycle):
                try:
                    out = next(it)
                except StopIteration:
                    return
            hb = _from_pandas(out, self._schema, "map_in_pandas")
            if hb.num_rows:
                yield _emit(hb, ctx)

    def node_desc(self) -> str:
        name = getattr(self._fn, "__name__", "<lambda>")
        return f"MapInPandasExec[{name}]"


def _group_frames(pdf, key_names: list):
    """Per-group sub-frames, null keys kept as their own groups and
    group order deterministic (sorted, nulls last — pandas sort=True)."""
    return pdf.groupby(list(key_names), dropna=False, sort=True)


class FlatMapGroupsInPandasExec(PlanNode):
    """group_by(keys).apply_in_pandas(fn, schema): ``fn`` receives each
    group as one pandas DataFrame (ALL child columns, keys included) and
    returns a DataFrame conforming to ``schema``.  The planner inserts a
    hash exchange on the keys so each group lands wholly in one
    partition (reference GpuFlatMapGroupsInPandasExec.scala:75
    requiredChildDistribution = ClusteredDistribution)."""

    def __init__(self, key_names: Sequence[str], fn: Callable,
                 out_schema: T.Schema, child: PlanNode):
        super().__init__([child])
        self._keys = list(key_names)
        self._fn = fn
        self._schema = out_schema

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        batches = list(_host_batches(self.children[0], ctx, pid))
        if not batches:
            return
        pdf = _to_pandas(HostBatch.concat(batches), exact_keys=self._keys)
        if not len(pdf):
            return
        sem = _py_semaphore(ctx.conf.get(CONCURRENT_PYTHON))
        for _, g in _group_frames(pdf, self._keys):
            with _udf_slot(sem, ctx.lifecycle):
                out = self._fn(g.reset_index(drop=True))
            hb = _from_pandas(out, self._schema, "apply_in_pandas")
            if hb.num_rows:
                yield _emit(hb, ctx)

    def node_desc(self) -> str:
        return f"FlatMapGroupsInPandasExec[keys={self._keys}]"


class PandasAggUDF(Expression):
    """Grouped-aggregate pandas UDF: Series in, ONE scalar out per
    group — planned into AggregateInPandasExec, never evaluated inline
    (reference GpuAggregateInPandasExec's PythonUDAF plan)."""

    sql_name = "PandasAggUDF"

    def __init__(self, fn: Callable, children: Sequence[Expression],
                 return_type: T.DataType):
        self.fn = fn
        self.children = tuple(children)
        self.return_type = return_type

    def with_new_children(self, children):
        return PandasAggUDF(self.fn, children, self.return_type)

    @property
    def dtype(self):
        return self.return_type

    @property
    def nullable(self):
        return True

    def _eval(self, vals, ctx):
        raise ValueError("PandasAggUDF must be planned by "
                         "AggregateInPandasExec (use it in group_by("
                         ").agg())")

    def __repr__(self):
        name = getattr(self.fn, "__name__", "<lambda>")
        return f"PandasAggUDF({name}, {', '.join(map(repr, self.children))})"


def pandas_agg_udf(fn: Callable, return_type: T.DataType | None = None):
    """``df.group_by("k").agg(pandas_agg_udf(lambda s: s.mean())(col("v"))
    .alias("m"))`` — ``fn`` receives pandas Series and returns one
    scalar per group."""

    def apply(*cols):
        return PandasAggUDF(fn, list(cols), return_type or T.DoubleType())

    return apply


class AggregateInPandasExec(PlanNode):
    """One output row per group: key columns + one column per pandas
    aggregate UDF (Series -> scalar).  A black-box aggregate cannot be
    split partial/final, so the planner clusters rows by key first
    (reference GpuAggregateInPandasExec.scala:63-198)."""

    def __init__(self, key_names: Sequence[str], udfs: Sequence,
                 child: PlanNode):
        super().__init__([child])
        self._keys = list(key_names)
        cs = child.output_schema
        self._udfs = []  # (name, PandasAggUDF bound to child schema)
        fields = [cs.field(k) for k in self._keys]
        for name, u in udfs:
            bound = [bind(c, cs) for c in u.children]
            self._udfs.append((name, PandasAggUDF(u.fn, bound,
                                                  u.return_type)))
            fields.append(T.StructField(name, u.return_type, True))
        self._schema = T.Schema(fields)

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def bound_exprs(self):
        return [c for _, u in self._udfs for c in u.children]

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        import pandas as pd
        from spark_rapids_tpu.expr.core import eval_host
        batches = list(_host_batches(self.children[0], ctx, pid))
        if not batches:
            if self._keys:
                return
            # keyless grand aggregate over empty input still produces
            # ONE row — the UDF sees empty Series (Spark global
            # aggregation semantics; this engine's HashAggregateExec
            # default-values row does the same)
            batches = [HostBatch.empty(self.children[0].output_schema)]
        hb = HostBatch.concat(batches)
        if not hb.num_rows and self._keys:
            return
        # key columns + each UDF's evaluated input series, side by side
        # (keys convert individually — a whole-batch _to_pandas would
        # pay for every non-key column just to read the keys)
        frame = {}
        for k in self._keys:
            frame[k] = _host_col_to_series(
                hb.columns[hb.schema.index_of(k)], exact_int=True)
        in_names: list[list[str]] = []
        for ui, (name, u) in enumerate(self._udfs):
            cols = []
            for ci, c in enumerate(u.children):
                s = _host_col_to_series(eval_host(c, hb))
                cn = f"_in_{ui}_{ci}"
                frame[cn] = s
                cols.append(cn)
            in_names.append(cols)
        pdf = pd.DataFrame(frame, index=range(hb.num_rows))
        sem = _py_semaphore(ctx.conf.get(CONCURRENT_PYTHON))
        rows: dict[str, list] = {n: [] for n in self._schema.names}
        if self._keys:
            groups = _group_frames(pdf, self._keys)
        else:
            groups = [((), pdf)]
        for key, g in groups:
            if not isinstance(key, tuple):
                key = (key,)
            for k, kv in zip(self._keys, key):
                rows[k].append(None if pd.isna(kv) else kv)
            for (name, u), cols in zip(self._udfs, in_names):
                with _udf_slot(sem, ctx.lifecycle):
                    r = u.fn(*[g[c] for c in cols])
                rows[name].append(None if r is None or
                                  (np.isscalar(r) and pd.isna(r)) else r)
        # integral output columns build as nullable Int64: a plain
        # pd.Series over ints + None coerces to float64, which merges
        # int64 key values >= 2**53 (advisor r4 — the group keys were
        # exact all the way here, only to collapse in this constructor)
        def out_series(n):
            f = self._schema.field(n)
            if f.data_type.integral and any(v is None for v in rows[n]):
                return pd.Series(rows[n], dtype="Int64")
            return pd.Series(rows[n])
        out = pd.DataFrame({n: out_series(n) for n in
                            self._schema.names})
        hb_out = _from_pandas(out, self._schema, "pandas agg")
        if hb_out.num_rows:
            yield _emit(hb_out, ctx)

    def node_desc(self) -> str:
        return (f"AggregateInPandasExec[keys={self._keys}, "
                f"aggs={[n for n, _ in self._udfs]}]")


def _null_safe_key(key) -> tuple:
    """Normalize a group-key tuple so null keys compare equal across the
    two cogrouped sides (NaN != NaN would otherwise split them)."""
    import pandas as pd
    if not isinstance(key, tuple):
        key = (key,)
    return tuple("\x00<null>" if pd.isna(k) else k for k in key)


class FlatMapCoGroupsInPandasExec(PlanNode):
    """df1.group_by(k).cogroup(df2.group_by(k)).apply_in_pandas(fn,
    schema): ``fn(left_pdf, right_pdf)`` once per key present on EITHER
    side; the absent side arrives as an empty DataFrame with its full
    column set (reference GpuFlatMapCoGroupsInPandasExec.scala:70-167,
    requiredChildDistribution clusters both children on their keys)."""

    def __init__(self, left_keys: Sequence[str], right_keys: Sequence[str],
                 fn: Callable, out_schema: T.Schema, left: PlanNode,
                 right: PlanNode):
        super().__init__([left, right])
        self._lkeys = list(left_keys)
        self._rkeys = list(right_keys)
        self._fn = fn
        self._schema = out_schema

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self.children[0].num_partitions(ctx)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        def side_groups(node, keys):
            batches = list(_host_batches(node, ctx, pid))
            empty = _to_pandas(HostBatch.empty(node.output_schema))
            if not batches:
                return {}, empty
            pdf = _to_pandas(HostBatch.concat(batches), exact_keys=keys)
            if not len(pdf):
                return {}, empty
            return {_null_safe_key(k): g.reset_index(drop=True)
                    for k, g in _group_frames(pdf, keys)}, empty

        lgroups, lempty = side_groups(self.children[0], self._lkeys)
        rgroups, rempty = side_groups(self.children[1], self._rkeys)
        keys = sorted(set(lgroups) | set(rgroups), key=repr)
        sem = _py_semaphore(ctx.conf.get(CONCURRENT_PYTHON))
        for k in keys:
            # absent side gets a fresh copy: UDFs commonly mutate their
            # input in place, and a shared empty frame would leak those
            # mutations into later calls (review finding)
            lg = lgroups.get(k)
            rg = rgroups.get(k)
            with _udf_slot(sem, ctx.lifecycle):
                out = self._fn(lg if lg is not None else lempty.copy(),
                               rg if rg is not None else rempty.copy())
            hb = _from_pandas(out, self._schema, "cogroup apply_in_pandas")
            if hb.num_rows:
                yield _emit(hb, ctx)

    def node_desc(self) -> str:
        return (f"FlatMapCoGroupsInPandasExec[{self._lkeys} x "
                f"{self._rkeys}]")


class PandasWindowUDF(Expression):
    """Window-aggregate pandas UDF: evaluated over each row's window
    frame (Series slice in, ONE scalar out per row) — planned into
    WindowInPandasExec, never evaluated inline (reference
    GpuWindowInPandasExec's PythonUDF-in-WindowExpression plan,
    shims/spark300/.../GpuWindowInPandasExec.scala:1-408)."""

    sql_name = "PandasWindowUDF"

    def __init__(self, fn: Callable, children: Sequence[Expression],
                 return_type: T.DataType):
        self.fn = fn
        self.children = tuple(children)
        self.return_type = return_type

    def with_new_children(self, children):
        return PandasWindowUDF(self.fn, children, self.return_type)

    @property
    def dtype(self):
        return self.return_type

    @property
    def nullable(self):
        return True

    def _eval(self, vals, ctx):
        raise ValueError("PandasWindowUDF must be planned by "
                         "WindowInPandasExec (use .over(window_spec))")

    def over(self, spec):
        """``udf(col).over(window_spec)`` — Spark's pandas-UDF-over-
        window surface (WindowInPandasExec plan)."""
        from spark_rapids_tpu.expr.window import WindowExpression
        return WindowExpression(self, spec)

    def __repr__(self):
        name = getattr(self.fn, "__name__", "<lambda>")
        return f"PandasWindowUDF({name}, {', '.join(map(repr, self.children))})"


def pandas_window_udf(fn: Callable, return_type: T.DataType | None = None):
    """``pandas_window_udf(lambda s: s.mean())(col("v")).over(spec)`` —
    ``fn`` receives each row's frame as pandas Series and returns one
    scalar for that row (Spark's GROUPED_AGG pandas UDF over a window)."""

    def apply(*cols):
        return PandasWindowUDF(fn, list(cols), return_type or T.DoubleType())

    return apply


class WindowInPandasExec(PlanNode):
    """Append one column per pandas window UDF expression.

    The reference streams (window-bound columns + UDF inputs) to Python
    workers, which evaluate the UDF over each row's slice
    (GpuWindowInPandasExec.scala:107-180 computeWindowBoundHelpers and
    :234-330 bounds-column projection).  Here the same shape runs
    in-process: per partition group, compute each row's [lower, upper)
    frame indices from the shared WindowSpec, then call the UDF with the
    input Series sliced to that frame.  Like the reference
    (requiredChildDistribution, :88-97) the planner clusters rows by the
    partition keys first; an empty partition-by collapses to a single
    group with the reference's own performance warning semantics.
    """

    def __init__(self, window_exprs: Sequence[Expression], child: PlanNode,
                 keys_partitioned: bool = False):
        super().__init__([child])
        from spark_rapids_tpu.expr.core import Alias, output_name
        from spark_rapids_tpu.expr.window import WindowExpression
        self._keys_partitioned = bool(keys_partitioned)
        self._names = [output_name(e) for e in window_exprs]
        self._wexprs = []
        for e in window_exprs:
            if isinstance(e, Alias):
                e = e.children[0]
            assert isinstance(e, WindowExpression), e
            assert isinstance(e.function, PandasWindowUDF), e.function
            self._wexprs.append(e)
        spec0 = self._wexprs[0].spec
        for e in self._wexprs[1:]:
            if e.spec != spec0:
                raise ValueError("one WindowInPandasExec handles one "
                                 "WindowSpec; split plans per spec")
        self.spec = spec0
        cs = child.output_schema
        self._part_b = [bind(p, cs) for p in self.spec.partition_by]
        self._order_b = [(bind(o[0], cs), o[1] if len(o) > 1 else True,
                          o[2] if len(o) > 2 else None)
                         for o in self.spec.order_by]
        self._udfs = [PandasWindowUDF(w.function.fn,
                                      [bind(c, cs)
                                       for c in w.function.children],
                                      w.function.return_type)
                      for w in self._wexprs]
        self._schema = T.Schema(
            list(cs.fields)
            + [T.StructField(n, u.return_type, True)
               for n, u in zip(self._names, self._udfs)])

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def bound_exprs(self):
        return ([e for e in self._part_b] + [e for e, _, _ in self._order_b]
                + [c for u in self._udfs for c in u.children])

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self.children[0].num_partitions(ctx) \
            if self._keys_partitioned else 1

    @staticmethod
    def _bounds(gn: int, peer_start: np.ndarray, peer_end: np.ndarray,
                frame) -> tuple[np.ndarray, np.ndarray]:
        """[lower, upper) frame rows for one group (group-local).
        ``peer_start``/``peer_end``: each row's order-peer group extent
        (Spark's default ordered frame is RANGE UNBOUNDED..CURRENT ROW =
        peers included; GpuWindowExpression's frame resolution)."""
        i = np.arange(gn)
        from spark_rapids_tpu.ops.window import CURRENT_ROW, UNBOUNDED
        if frame.mode == "rows":
            lo = np.zeros(gn, np.int64) if frame.lower is UNBOUNDED \
                else np.clip(i + frame.lower, 0, gn)
            hi = np.full(gn, gn, np.int64) if frame.upper is UNBOUNDED \
                else np.clip(i + frame.upper + 1, 0, gn)
        else:  # range: UNBOUNDED/CURRENT_ROW only (planner contract)
            lo = peer_start if frame.lower is CURRENT_ROW \
                else np.zeros(gn, np.int64)
            hi = peer_end if frame.upper is CURRENT_ROW \
                else np.full(gn, gn, np.int64)
        return lo, hi

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        import pandas as pd
        from spark_rapids_tpu.expr.core import eval_host
        from spark_rapids_tpu.ops.sort import SortOrder
        child = self.children[0]
        if self._keys_partitioned:
            batches = list(_host_batches(child, ctx, pid))
        else:
            batches = [b for p in range(child.num_partitions(ctx))
                       for b in _host_batches(child, ctx, p)]
        if not batches:
            return
        hb = HostBatch.concat(batches)
        n = hb.num_rows
        if not n:
            return
        # sort rows by (partition keys, order keys) — required child
        # ordering, reference GpuWindowInPandasExec.scala:99-100
        key_cols = [eval_host(e, hb) for e in self._part_b] \
            + [eval_host(e, hb) for e, _, _ in self._order_b]
        tmp = HostBatch(key_cols, T.Schema(
            [T.StructField(f"k{i}", c.dtype, True)
             for i, c in enumerate(key_cols)]))
        orders = [SortOrder(i, True, True)
                  for i in range(len(self._part_b))] \
            + [SortOrder(len(self._part_b) + i, asc, nf)
               for i, (_, asc, nf) in enumerate(self._order_b)]
        perm = hk.host_sort_permutation(tmp, orders)
        hb = hk.host_take(hb, perm)

        def change_flags(cols):
            """bool[n] over the SORTED batch: row differs from its
            predecessor on any of ``cols`` (row 0 True; per-column
            factorize codes, so no composite product to overflow;
            nulls are one group, Spark window key semantics)."""
            ch = np.zeros(n, bool)
            if n:
                ch[0] = True
            for c in cols:
                s = _host_col_to_series(c.take(perm), exact_int=True)
                code = pd.factorize(s, use_na_sentinel=False)[0]
                ch[1:] |= code[1:] != code[:-1]
            return ch

        gchange = change_flags(key_cols[:len(self._part_b)])
        ochange_g = change_flags(key_cols[len(self._part_b):])
        seg_starts = np.flatnonzero(gchange)
        seg_ends = np.concatenate([seg_starts[1:], [n]])

        in_series = [[_host_col_to_series(eval_host(c, hb))
                      for c in u.children] for u in self._udfs]
        sem = _py_semaphore(ctx.conf.get(CONCURRENT_PYTHON))
        out_vals: list[list] = [[None] * n for _ in self._udfs]
        for s0, s1 in zip(seg_starts, seg_ends):
            gn = s1 - s0
            ochange = ochange_g[s0:s1].copy()
            if gn:
                ochange[0] = True
            peer_id = np.cumsum(ochange) - 1
            # each row's order-peer group extent [start, end), group-local
            pstarts = np.flatnonzero(ochange)
            peer_start = pstarts[peer_id]
            peer_end = np.concatenate([pstarts[1:], [gn]])[peer_id]
            for ui, (w, u) in enumerate(zip(self._wexprs, self._udfs)):
                lo, hi = self._bounds(gn, peer_start, peer_end,
                                      w.spec.resolved_frame())
                series = [s.iloc[s0:s1].reset_index(drop=True)
                          for s in in_series[ui]]
                vals = out_vals[ui]
                with _udf_slot(sem, ctx.lifecycle):
                    for i in range(gn):
                        r = u.fn(*[s.iloc[lo[i]:hi[i]] for s in series])
                        vals[s0 + i] = None if r is None or (
                            np.isscalar(r) and pd.isna(r)) else r
        out_cols = list(hb.columns)
        for (name, u), vals in zip(zip(self._names, self._udfs), out_vals):
            f = self._schema.field(name)
            if f.data_type.integral and any(v is None for v in vals):
                s = pd.Series(vals, dtype="Int64")
            else:
                s = pd.Series(vals)
            hcol = _from_pandas(pd.DataFrame({name: s}),
                                T.Schema([f]), "pandas window").columns[0]
            out_cols.append(hcol)
        yield _emit(HostBatch(out_cols, self._schema), ctx)

    def node_desc(self) -> str:
        return (f"WindowInPandasExec[{self._names}, "
                f"part={len(self._part_b)}, order={len(self._order_b)}]")

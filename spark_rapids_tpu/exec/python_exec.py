"""Arrow-eval Python (pandas) UDF exec.

Reference: GpuArrowEvalPythonExec (GpuArrowEvalPythonExec.scala:46-456)
streams device batches as Arrow IPC to external python workers running
pandas scalar UDFs, reads Arrow results back to the device, with
PythonWorkerSemaphore capping concurrent workers.  This engine is
already a python process, so the data plane degenerates to an in-process
Arrow conversion: device batch -> pandas Series -> vectorized UDF ->
device column; the semaphore survives as a concurrency bound
(spark.rapids.python.concurrentPythonWorkers) because pandas UDFs run on
drain worker threads.
"""
from __future__ import annotations

import threading
from typing import Callable, Iterator, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.conf import ConfEntry, register
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.expr.core import Expression, bind
from spark_rapids_tpu.host.batch import HostBatch, HostColumn

__all__ = ["PandasUDF", "pandas_udf", "ArrowEvalPythonExec"]

CONCURRENT_PYTHON = register(ConfEntry(
    "spark.rapids.python.concurrentPythonWorkers", 2,
    "Concurrent pandas-UDF evaluations (reference PythonWorkerSemaphore,"
    " PythonWorkerSemaphore.scala:42-100).", conv=int))

_sem_lock = threading.Lock()
_sems: dict[int, threading.BoundedSemaphore] = {}


def _py_semaphore(n: int) -> threading.BoundedSemaphore:
    with _sem_lock:
        if n not in _sems:
            _sems[n] = threading.BoundedSemaphore(n)
        return _sems[n]


class PandasUDF(Expression):
    """Vectorized python UDF over pandas Series — planned into an
    ArrowEvalPythonExec, never evaluated inline (like WindowExpression)."""

    sql_name = "PandasUDF"

    def __init__(self, fn: Callable, children: Sequence[Expression],
                 return_type: T.DataType):
        self.fn = fn
        self.children = tuple(children)
        self.return_type = return_type

    def with_new_children(self, children):
        return PandasUDF(self.fn, children, self.return_type)

    @property
    def dtype(self):
        return self.return_type

    @property
    def nullable(self):
        return True

    def _eval(self, vals, ctx):
        raise ValueError(
            "PandasUDF must be planned by ArrowEvalPythonExec "
            "(use it directly inside select())")

    def __repr__(self):
        name = getattr(self.fn, "__name__", "<lambda>")
        return f"PandasUDF({name}, {', '.join(map(repr, self.children))})"


def pandas_udf(fn: Callable, return_type: T.DataType | None = None):
    """``df.select(pandas_udf(lambda s: s * 2)(col("a")))`` — ``fn``
    receives pandas Series and returns a Series/array of the same
    length."""

    def apply(*cols):
        return PandasUDF(fn, list(cols), return_type or T.DoubleType())

    return apply


class ArrowEvalPythonExec(PlanNode):
    """Append one column per pandas UDF to each child batch.

    The child batch crosses D2H as Arrow, the UDFs run vectorized over
    pandas Series, and results transfer back H2D (reference
    GpuArrowPythonRunner's writeArrowIPCChunked round trip :376-432)."""

    def __init__(self, udfs: Sequence, child: PlanNode):
        super().__init__([child])
        self._udfs = []  # (name, PandasUDF with bound children)
        cs = child.output_schema
        fields = list(cs.fields)
        for name, u in udfs:
            bound = [bind(c, cs) for c in u.children]
            self._udfs.append((name, PandasUDF(u.fn, bound, u.return_type)))
            fields.append(T.StructField(name, u.return_type, True))
        self._schema = T.Schema(fields)

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    @property
    def bound_exprs(self):
        # PandasUDF itself is exec-planned; expose only its INPUT
        # expressions for tagging
        return [c for _, u in self._udfs for c in u.children]

    def _series_inputs(self, hb: HostBatch, u: PandasUDF):
        import pandas as pd
        from spark_rapids_tpu.expr.core import eval_host
        out = []
        for c in u.children:
            v = eval_host(c, hb)
            if isinstance(v.dtype, T.StringType):
                out.append(pd.Series(v.data))
            else:
                data = v.data.astype("float64") if not np.all(v.validity) \
                    and v.dtype.numeric else v.data
                s = pd.Series(data)
                if not np.all(v.validity):
                    s[~v.validity] = None
                out.append(s)
        return out

    def _apply_udfs(self, hb: HostBatch, ctx: ExecCtx) -> HostBatch:
        import pandas as pd
        sem = _py_semaphore(ctx.conf.get(CONCURRENT_PYTHON))
        cols = list(hb.columns)
        for name, u in self._udfs:
            with sem:
                result = u.fn(*self._series_inputs(hb, u))
            r = pd.Series(result)
            if len(r) != hb.num_rows:
                raise ValueError(
                    f"pandas UDF {name} returned {len(r)} rows for "
                    f"{hb.num_rows} input rows")
            validity = ~r.isna().to_numpy()
            if isinstance(u.return_type, T.StringType):
                data = np.array([None if not v else str(x)
                                 for x, v in zip(r, validity)], dtype=object)
            else:
                data = r.fillna(0).to_numpy().astype(u.return_type.np_dtype)
            cols.append(HostColumn(data, validity, u.return_type))
        return HostBatch(cols, self._schema)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        from spark_rapids_tpu.exec.core import device_to_host, host_to_device
        for b in self.children[0].partition_iter(ctx, pid):
            if ctx.is_device:
                hb = device_to_host(b)
                out = self._apply_udfs(hb, ctx)
                yield host_to_device(out)
            else:
                yield self._apply_udfs(b, ctx)

    def node_desc(self) -> str:
        return (f"ArrowEvalPythonExec[{[n for n, _ in self._udfs]}]")

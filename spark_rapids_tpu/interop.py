"""ML interop: export query results as device arrays / tensors.

Reference: ColumnarRdd + InternalColumnarRddConverter
(ColumnarRdd.scala:42-49, InternalColumnarRddConverter.scala:470) expose
``RDD[cudf.Table]`` from a DataFrame so ML libraries (XGBoost,
docs/ml-integration.md:8-11) consume GPU-resident data without a
host round trip.  The TPU analog exports the engine's device batches:

* :func:`device_batches` — per-partition ``ColumnBatch`` iterator, data
  staying in HBM (the direct ColumnarRdd analog);
* :func:`to_jax` — one dict of jax arrays (+ validity masks), trimmed
  to the logical row count, ready for jit-compiled ML code;
* :func:`to_torch` — CPU torch tensors via numpy handoff (torch in this
  image is CPU-only; a device round trip is inherent);
* :func:`from_jax` — the reverse: jax/numpy arrays -> DataFrame
  (InternalColumnarRddConverter's batch-import direction).

String columns export as (byte-matrix, lengths) pairs in
``device_batches`` and are materialized as python lists by ``to_jax``
only on request — ML consumers overwhelmingly take numeric features.
"""
from __future__ import annotations

from typing import Iterator

from spark_rapids_tpu import types as T

__all__ = ["device_batches", "to_jax", "to_torch", "from_jax"]


def device_batches(df) -> Iterator:
    """Iterate the query's device ``ColumnBatch``es partition by
    partition (no D2H).  The plan runs on the device backend regardless
    of fallback tagging for the FINAL operator chain only when the whole
    plan is device-capable; otherwise host batches are uploaded at the
    boundary (the reference's HostColumnarToGpu transition)."""
    from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
    # NOTE: execution resources (shuffle server sockets, spill files,
    # buffer catalog) are released when this generator is exhausted OR
    # closed; if you stop early, call .close() on the generator (or let
    # it fall out of scope promptly) rather than keeping it alive.
    _, meta = df._overridden(quiet=True)
    ctx = ExecCtx(backend=meta.backend, conf=df._s.conf)
    try:
        for b in meta.exec_node.execute(ctx):
            if meta.backend != "device":
                b = host_to_device(b)
            yield b
    finally:
        # runs on exhaustion AND on generator close/GC, so an abandoned
        # iterator still releases shuffle sockets, spill files, and the
        # catalog (review finding: don't defer resource teardown to GC
        # of an open `with` frame)
        ctx.close()


def to_jax(df, include_strings: bool = False) -> dict:
    """Run the query and return ``{name: (values, validity)}`` of jax
    arrays trimmed to the result's row count.  Numeric/temporal columns
    only unless ``include_strings`` (strings come back as python lists,
    via host)."""
    import jax.numpy as jnp
    from spark_rapids_tpu.exec.core import device_to_host

    out: dict = {}
    parts: dict = {}
    schema = df.schema
    # result schemas can legally carry duplicate names (e.g. after a
    # join), but chunk accumulation and the returned dict are name-keyed
    # — duplicates would silently merge mismatched columns, so they are
    # refused up front
    seen: dict = {}
    for i, f in enumerate(schema):
        if f.name in seen:
            raise ValueError(
                f"to_jax cannot export duplicate column name {f.name!r} "
                f"(positions {seen[f.name]} and {i}); alias one side")
        seen[f.name] = i
    want_strings = include_strings and any(
        isinstance(f.data_type, T.StringType) for f in schema)
    for b in device_batches(df):
        n = b.host_num_rows()
        # ONE D2H per batch when strings are requested, not one per
        # string column (each device_to_host copies every column)
        hb = device_to_host(b) if want_strings else None
        for i, (f, c) in enumerate(zip(schema, b.columns)):
            if isinstance(f.data_type, T.StringType):
                if not include_strings:
                    continue
                parts.setdefault(f.name, []).append(
                    ("str", hb.columns[i].to_list()))
            else:
                parts.setdefault(f.name, []).append(
                    ("num", (c.data[:n], c.validity[:n])))
    for name, chunks in parts.items():
        if chunks[0][0] == "str":
            vals: list = []
            for _, lst in chunks:
                vals.extend(lst)
            out[name] = vals
        else:
            out[name] = (jnp.concatenate([v for _, (v, _) in chunks]),
                         jnp.concatenate([m for _, (_, m) in chunks]))
    if not parts:  # empty result: zero-length arrays with the right dtypes
        for f in schema:
            if isinstance(f.data_type, T.StringType):
                if include_strings:
                    out[f.name] = []
                continue
            out[f.name] = (jnp.zeros((0,), f.data_type.np_dtype),
                           jnp.zeros((0,), bool))
    return out


def to_torch(df) -> dict:
    """Run the query and return ``{name: torch.Tensor}`` (CPU) for
    numeric/temporal columns; null validity is exported alongside as
    ``{name}__valid``."""
    import numpy as np
    import torch

    arrays = to_jax(df)
    out = {}
    for name, val in arrays.items():
        if isinstance(val, list):
            continue
        data, valid = val
        # copy: jax device_get hands back read-only buffers and torch
        # tensors are mutable views
        out[name] = torch.from_numpy(np.array(data))
        out[f"{name}__valid"] = torch.from_numpy(np.array(valid))
    return out


def from_jax(session, arrays: dict, schema: T.Schema | None = None,
             partitions: int = 1):
    """jax/numpy arrays -> DataFrame (the import direction).  ``arrays``
    maps column name to values or (values, validity)."""
    import numpy as np

    data = {}
    fields = []
    for name, val in arrays.items():
        validity = None
        if isinstance(val, tuple):
            val, validity = val
        a = np.asarray(val)
        if schema is not None:
            dt = schema.field(name).data_type
        else:
            dt = T.from_numpy_dtype(a.dtype)
        vals = a.tolist()
        if validity is not None:
            mask = np.asarray(validity, dtype=bool)
            vals = [v if m else None for v, m in zip(vals, mask)]
        data[name] = vals
        fields.append(T.StructField(name, dt, True))
    return session.from_pydict(data, schema or T.Schema(fields),
                               partitions=partitions)

"""Device manager: fail-fast runtime init + HBM pool sizing.

Reference: GpuDeviceManager.scala (initializeGpuAndMemory :120-127 —
device acquisition + memory-pool init at executor start;
computeRmmInitSizes :159-194 — alloc-fraction/reserve math) and
Plugin.scala's fail-fast discipline (checkCudfVersion :156-201 with an
override flag :198; executor init failure exits rather than hangs
:146-153).

TPU analog:

* validate the jax/pyarrow runtime once per process with CLEAR errors
  (instead of a version-skew crash deep inside a query), overridable via
  ``spark.rapids.tpu.allowIncompatibleRuntime``;
* acquire the accelerator under a DEADLINE — the tunneled PJRT backend
  can hang forever inside init, and the reference's contract is
  fail-fast-and-relaunch, not hang;
* derive the spill catalog's HBM budget from the device's actual
  ``memory_stats()`` via allocFraction/reserve instead of a fixed
  default (PJRT exposes ``bytes_limit`` on TPU; no stats -> conf
  default).
"""
from __future__ import annotations

import threading

from spark_rapids_tpu.conf import (ConfEntry, HBM_ALLOC_FRACTION, register,
                                   _bool, parse_bytes)

__all__ = ["TpuInitError", "initialize_device", "device_pool_limit",
           "device_info"]

MIN_JAX = (0, 4, 26)
MIN_PYARROW = (10, 0, 0)

INIT_TIMEOUT = register(ConfEntry(
    "spark.rapids.tpu.initTimeoutSeconds", 90,
    "Deadline for accelerator backend initialization. A tunneled/remote "
    "PJRT client can hang forever inside device acquisition; the "
    "reference treats executor init failure as fail-fast-and-relaunch "
    "(Plugin.scala:146-153), so a hang past this deadline raises "
    "TpuInitError instead of wedging the session.", conv=int))

ALLOW_INCOMPATIBLE = register(ConfEntry(
    "spark.rapids.tpu.allowIncompatibleRuntime", False,
    "Continue despite a jax/pyarrow version below the supported minimum "
    "(reference cudf version-check override, Plugin.scala:198).",
    conv=_bool))

DEVICE_RESERVE = register(ConfEntry(
    "spark.rapids.memory.tpu.reserve", 256 << 20,
    "HBM held back from the spill catalog's budget for XLA scratch and "
    "runtime allocations (reference RESERVE in computeRmmInitSizes, "
    "GpuDeviceManager.scala:159-194).", conv=parse_bytes))


class TpuInitError(RuntimeError):
    """Raised when the device runtime cannot be initialized (version
    skew, backend init failure, or init deadline exceeded)."""


class _State:
    lock = threading.Lock()
    initialized = False
    platform: str | None = None
    device_kind: str | None = None
    device_count = 0
    hbm_bytes_limit: int | None = None
    pool_limit: int | None = None


def _vtuple(v: str) -> tuple:
    out = []
    for part in str(v).split(".")[:3]:
        digits = "".join(ch for ch in part if ch.isdigit())
        out.append(int(digits or 0))
    return tuple(out)


def _check_versions(allow_incompatible: bool) -> None:
    import jax
    problems = []
    if _vtuple(jax.__version__) < MIN_JAX:
        problems.append(f"jax {jax.__version__} < required "
                        f"{'.'.join(map(str, MIN_JAX))}")
    try:
        import pyarrow
        if _vtuple(pyarrow.__version__) < MIN_PYARROW:
            problems.append(f"pyarrow {pyarrow.__version__} < required "
                            f"{'.'.join(map(str, MIN_PYARROW))}")
    except ImportError:
        problems.append("pyarrow is not installed")
    if problems:
        msg = ("incompatible runtime: " + "; ".join(problems)
               + " (set spark.rapids.tpu.allowIncompatibleRuntime=true "
                 "to continue anyway)")
        if not allow_incompatible:
            raise TpuInitError(msg)
        import warnings
        warnings.warn(msg, RuntimeWarning)


def _probe_devices():
    """Run in a worker thread: returns jax.devices() (may hang in a
    wedged PJRT client — the caller enforces the deadline).

    When the process explicitly requests the CPU platform
    (JAX_PLATFORMS=cpu or jax_platforms config), probe ONLY the cpu
    backend: the bare ``jax.devices()`` default-backend resolution goes
    through the accelerator plugin's client init, so a wedged tunnel
    would block even pure-CPU sessions (observed round 4: every
    JAX_PLATFORMS=cpu verification process hung in make_c_api_client
    when the axon relay went down mid-run)."""
    import os
    import jax
    env = (os.environ.get("JAX_PLATFORMS") or "").split(",")[0].strip()
    if env == "cpu":
        # the accelerator plugin's site hook rewrites jax_platforms to
        # "axon,cpu" AFTER the env var is read, so the env intent must
        # be re-asserted through the config (the authoritative path) or
        # backends() initializes the tunnel client first anyway
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
        return jax.devices("cpu")
    return jax.devices()


def _compute_pool_limit(bytes_limit: int, alloc_fraction: float,
                        reserve: int) -> int:
    """allocFraction/reserve math (computeRmmInitSizes analog): the
    catalog may fill alloc_fraction of HBM minus the runtime reserve,
    floored so a tiny/misconfigured limit still leaves a usable pool."""
    pool = int(bytes_limit * alloc_fraction) - reserve
    return max(pool, 64 << 20)


def initialize_device(conf=None, probe=None) -> None:
    """Idempotent per-process device init (reference
    initializeGpuAndMemory, called from RapidsExecutorPlugin.init).

    ``probe`` overrides the device query for tests.
    """
    with _State.lock:
        if _State.initialized:
            return
        settings = getattr(conf, "settings", None) or {}
        _check_versions(ALLOW_INCOMPATIBLE.get(settings))
        timeout = float(INIT_TIMEOUT.get(settings))
        result: dict = {}

        def work():
            try:
                result["devices"] = (probe or _probe_devices)()
            # enginelint: disable=RL001 (probe error is forwarded via the result dict and re-raised by the caller)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                result["error"] = e

        t = threading.Thread(target=work, daemon=True,
                             name="tpu-device-init")
        t.start()
        t.join(timeout)
        if t.is_alive():
            raise TpuInitError(
                f"accelerator backend initialization did not complete in "
                f"{timeout:.0f}s (wedged PJRT/tunnel client?); failing "
                "fast per spark.rapids.tpu.initTimeoutSeconds")
        if "error" in result:
            raise TpuInitError(
                f"accelerator backend initialization failed: "
                f"{result['error']}") from result.get("error")
        devices = result["devices"]
        if not devices:
            raise TpuInitError("no jax devices visible")
        d = devices[0]
        _State.platform = getattr(d, "platform", "unknown")
        _State.device_kind = getattr(d, "device_kind", "unknown")
        _State.device_count = len(devices)
        stats = {}
        try:
            stats = d.memory_stats() or {}
        # enginelint: disable=RL001 (memory_stats is an optional probe; absence leaves the HBM limit unknown)
        except Exception:
            pass
        limit = stats.get("bytes_limit")
        if limit:
            _State.hbm_bytes_limit = int(limit)
            _State.pool_limit = _compute_pool_limit(
                int(limit), HBM_ALLOC_FRACTION.get(settings),
                DEVICE_RESERVE.get(settings))
        _State.initialized = True


def device_pool_limit() -> int | None:
    """Catalog HBM budget from the initialized device's stats; None when
    uninitialized or the platform exposes no memory stats (callers fall
    back to spark.rapids.memory.tpu.spillStoreSize)."""
    return _State.pool_limit if _State.initialized else None


def device_info() -> dict:
    """Snapshot for logs/diagnostics (reference logs GPU + pool sizes at
    executor start)."""
    return {
        "initialized": _State.initialized,
        "platform": _State.platform,
        "device_kind": _State.device_kind,
        "device_count": _State.device_count,
        "hbm_bytes_limit": _State.hbm_bytes_limit,
        "pool_limit": _State.pool_limit,
    }


def _reset_for_tests() -> None:
    with _State.lock:
        _State.initialized = False
        _State.platform = _State.device_kind = None
        _State.device_count = 0
        _State.hbm_bytes_limit = _State.pool_limit = None

"""Differential-test harness: TPU path vs CPU oracle.

Reference: integration_tests/src/main/python/asserts.py —
``assert_gpu_and_cpu_are_equal_collect`` (:290) runs the same query on CPU
and GPU and compares collected rows, with ``ignore_order`` and
``approximate_float`` options (marks.py:17-25).  Here the two engines are
the two backends of the same plan tree.
"""
from __future__ import annotations

import math

from spark_rapids_tpu.exec.core import PlanNode, collect_device, collect_host

__all__ = ["assert_tpu_and_cpu_equal", "rows_equal"]


def _val_equal(a, b, approx: bool) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if math.isnan(fa) or math.isnan(fb):
            return math.isnan(fa) and math.isnan(fb)
        if approx:
            return math.isclose(fa, fb, rel_tol=1e-6, abs_tol=1e-9)
        return fa == fb
    return a == b


def rows_equal(r1, r2, approx: bool = False) -> bool:
    return len(r1) == len(r2) and all(
        _val_equal(a, b, approx) for a, b in zip(r1, r2))


def _sort_key(row):
    """Null-safe, type-aware row ordering for ignore_order comparison.
    Floats order numerically with -0.0 == 0.0 and NaN last, so rows that are
    equal under ``rows_equal`` land at matching positions on both backends."""
    out = []
    for v in row:
        if v is None:
            out.append((0, 0, ""))
        elif isinstance(v, bool):
            out.append((1, int(v), ""))
        elif isinstance(v, float):
            if math.isnan(v):
                out.append((3, 0, ""))
            else:
                out.append((2, v + 0.0, ""))  # -0.0 -> 0.0
        elif isinstance(v, int):
            # float() tier for cross-row ordering; str tiebreak keeps i64
            # values beyond 2^53 deterministically ordered
            out.append((2, float(v), str(v)))
        else:
            out.append((4, 0, str(v)))
    return out


def assert_tpu_and_cpu_equal(plan: PlanNode, ignore_order: bool = True,
                             approximate_float: bool = True,
                             conf=None) -> list[tuple]:
    """Run ``plan`` on both backends and compare collected rows.

    Returns the CPU rows (for further assertions). Mirrors
    assert_gpu_and_cpu_are_equal_collect (asserts.py:290).
    """
    cpu = collect_host(plan, conf)
    tpu = collect_device(plan, conf)
    assert len(cpu) == len(tpu), \
        f"row count mismatch: cpu={len(cpu)} tpu={len(tpu)}\n" \
        f"cpu={cpu[:10]}\ntpu={tpu[:10]}"
    c, t = (cpu, tpu) if not ignore_order else \
        (sorted(cpu, key=_sort_key), sorted(tpu, key=_sort_key))
    for i, (rc, rt) in enumerate(zip(c, t)):
        assert rows_equal(rc, rt, approximate_float), \
            f"row {i} differs:\n cpu={rc}\n tpu={rt}"
    return cpu

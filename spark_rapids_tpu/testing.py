"""Differential-test harness: TPU path vs CPU oracle.

Reference: integration_tests/src/main/python/asserts.py —
``assert_gpu_and_cpu_are_equal_collect`` (:290) runs the same query on CPU
and GPU and compares collected rows, with ``ignore_order`` and
``approximate_float`` options (marks.py:17-25).  Here the two engines are
the two backends of the same plan tree.
"""
from __future__ import annotations

import math

from spark_rapids_tpu.exec.core import PlanNode, collect_device, collect_host

__all__ = ["assert_tpu_and_cpu_equal", "rows_equal"]


def _val_equal(a, b, approx: bool) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if math.isnan(fa) or math.isnan(fb):
            return math.isnan(fa) and math.isnan(fb)
        if approx:
            return math.isclose(fa, fb, rel_tol=1e-6, abs_tol=1e-9)
        return fa == fb
    return a == b


def rows_equal(r1, r2, approx: bool = False) -> bool:
    return len(r1) == len(r2) and all(
        _val_equal(a, b, approx) for a, b in zip(r1, r2))


def _sort_key(row):
    """Null-safe, type-aware row ordering for ignore_order comparison.
    Floats order numerically with -0.0 == 0.0 and NaN last, so rows that are
    equal under ``rows_equal`` land at matching positions on both backends."""
    out = []
    for v in row:
        if v is None:
            out.append((0, 0, ""))
        elif isinstance(v, bool):
            out.append((1, int(v), ""))
        elif isinstance(v, float):
            if math.isnan(v):
                out.append((3, 0, ""))
            else:
                out.append((2, v + 0.0, ""))  # -0.0 -> 0.0
        elif isinstance(v, int):
            # float() tier for cross-row ordering; str tiebreak keeps i64
            # values beyond 2^53 deterministically ordered
            out.append((2, float(v), str(v)))
        else:
            out.append((4, 0, str(v)))
    return out


def assert_tpu_and_cpu_equal(plan: PlanNode, ignore_order: bool = True,
                             approximate_float: bool = True,
                             conf=None) -> list[tuple]:
    """Run ``plan`` on both backends and compare collected rows.

    Returns the CPU rows (for further assertions). Mirrors
    assert_gpu_and_cpu_are_equal_collect (asserts.py:290).
    """
    cpu = collect_host(plan, conf)
    tpu = collect_device(plan, conf)
    assert len(cpu) == len(tpu), \
        f"row count mismatch: cpu={len(cpu)} tpu={len(tpu)}\n" \
        f"cpu={cpu[:10]}\ntpu={tpu[:10]}"
    c, t = (cpu, tpu) if not ignore_order else \
        (sorted(cpu, key=_sort_key), sorted(tpu, key=_sort_key))
    for i, (rc, rt) in enumerate(zip(c, t)):
        assert rows_equal(rc, rt, approximate_float), \
            f"row {i} differs:\n cpu={rc}\n tpu={rt}"
    return cpu


# ---------------------------------------------------------------------------
# Typed fuzzed data generators (reference integration_tests data_gen.py:26+:
# per-type generators with deterministic seeds, null fractions, and
# special-value injection)
# ---------------------------------------------------------------------------

class DataGen:
    """Base typed generator: deterministic per (seed, n), ``nullable``
    gives the null fraction, special values are injected at a fixed
    rate like the reference's special_cases lists."""

    data_type = None
    special_values: list = []

    def __init__(self, nullable: float = 0.1, special_rate: float = 0.05):
        self.nullable = nullable
        self.special_rate = special_rate

    def generate(self, rng, n: int) -> list:
        vals = [self._one(rng) for _ in range(n)]
        if self.special_values and self.special_rate > 0:
            for i in range(n):
                if rng.random() < self.special_rate:
                    vals[i] = self.special_values[
                        int(rng.integers(0, len(self.special_values)))]
        if self.nullable > 0:
            vals = [None if rng.random() < self.nullable else v
                    for v in vals]
        return vals

    def _one(self, rng):
        raise NotImplementedError


class IntegerGen(DataGen):
    special_values = [0, 1, -1, 2**31 - 1, -(2**31)]

    def __init__(self, lo=-(2**31), hi=2**31 - 1, **kw):
        super().__init__(**kw)
        self.lo, self.hi = lo, hi

    @property
    def data_type(self):
        from spark_rapids_tpu import types as T
        return T.IntegerType()

    def _one(self, rng):
        import numpy as np
        # dtype=int64 enables the full 64-bit range; exclusive hi — the
        # exact boundary values come in via special_values
        return int(rng.integers(self.lo, self.hi, dtype=np.int64))


class LongGen(IntegerGen):
    special_values = [0, 1, -1, 2**63 - 1, -(2**63)]

    def __init__(self, **kw):
        super().__init__(lo=-(2**63), hi=2**63 - 1, **kw)

    @property
    def data_type(self):
        from spark_rapids_tpu import types as T
        return T.LongType()


class DoubleGen(DataGen):
    special_values = [0.0, -0.0, 1.0, -1.0, float("inf"), float("-inf"),
                      float("nan"), 1.7976931348623157e308,
                      4.9e-324]

    @property
    def data_type(self):
        from spark_rapids_tpu import types as T
        return T.DoubleType()

    def _one(self, rng):
        return float(rng.normal() * 10.0 ** int(rng.integers(-3, 6)))


class BooleanGen(DataGen):
    @property
    def data_type(self):
        from spark_rapids_tpu import types as T
        return T.BooleanType()

    def _one(self, rng):
        return bool(rng.integers(0, 2))


class StringGen(DataGen):
    """ASCII + unicode + empty + whitespace special cases (reference
    StringGen's sre_yield-driven generator with special_cases)."""

    special_values = ["", " ", "  \t", "NULL", "null", "0", "-1",
                      "éüñ", "你好", "a" * 60,
                      "CaSeD mIx", "line\nbreak"]

    def __init__(self, max_len: int = 12, **kw):
        super().__init__(**kw)
        self.max_len = max_len

    @property
    def data_type(self):
        from spark_rapids_tpu import types as T
        return T.StringType()

    def _one(self, rng):
        import string as _s
        n = int(rng.integers(0, self.max_len + 1))
        alphabet = _s.ascii_letters + _s.digits + "  _-"
        return "".join(alphabet[int(i)] for i in
                       rng.integers(0, len(alphabet), n))


class ArrayGen(DataGen):
    """Arrays of a fixed-width element generator (reference data_gen.py
    ArrayGen): empty and single-element arrays injected as specials."""

    def __init__(self, element_gen: DataGen | None = None, max_len: int = 6,
                 **kw):
        super().__init__(**kw)
        self.element = element_gen or IntegerGen(lo=-100, hi=100,
                                                 nullable=0.0)
        self.max_len = max_len

    @property
    def data_type(self):
        from spark_rapids_tpu import types as T
        return T.ArrayType(self.element.data_type)

    def _one(self, rng):
        k = int(rng.integers(0, self.max_len + 1))
        return [self.element._one(rng) for _ in range(k)]


class DateGen(DataGen):
    special_values = [0, -719162, 2932896, 1, -1]  # epoch, 0001, 9999

    @property
    def data_type(self):
        from spark_rapids_tpu import types as T
        return T.DateType()

    def _one(self, rng):
        return int(rng.integers(-25567, 47482))  # ~1900..2100


class TimestampGen(DataGen):
    special_values = [0, 1, -1, 253402300799_000000]

    @property
    def data_type(self):
        from spark_rapids_tpu import types as T
        return T.TimestampType()

    def _one(self, rng):
        return int(rng.integers(-2208988800, 4102444800)) * 1_000_000 \
            + int(rng.integers(0, 1_000_000))


def gen_df(session, columns, n: int = 256, seed: int = 0, partitions: int = 1,
           rows_per_batch: int | None = None):
    """Build a DataFrame of fuzzed columns: ``columns`` is a list of
    (name, DataGen) pairs (reference gen_df, data_gen.py)."""
    import numpy as np
    from spark_rapids_tpu import types as T
    rng = np.random.default_rng(seed)
    data = {}
    fields = []
    for name, g in columns:
        data[name] = g.generate(rng, n)
        fields.append(T.StructField(name, g.data_type, True))
    return session.from_pydict(data, T.Schema(fields), partitions,
                               rows_per_batch)


def assert_fallback(df, fallback_names, run: bool = True):
    """Assert the plan falls back to the host for the named exec/expr
    classes AND (optionally) that results still match between backends
    (reference assert_gpu_fallback_collect, asserts.py:241)."""
    ov, meta = df._overridden(quiet=True)
    text = ov.explain(meta)
    fallen = [ln for ln in text.splitlines() if ln.lstrip().startswith("!")]
    for name in ([fallback_names] if isinstance(fallback_names, str)
                 else fallback_names):
        assert any(name in ln for ln in fallen), \
            f"expected fallback of {name}; explain:\n{text}"
    if run:
        from spark_rapids_tpu.exec.core import collect_host
        dev = df.collect()
        host = collect_host(meta.exec_node, df._s.conf)
        assert len(dev) == len(host)
    return text

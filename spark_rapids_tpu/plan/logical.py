"""Logical plan nodes (mini-Catalyst).

The reference plugs into Spark Catalyst and never owns a logical plan;
this standalone engine needs one as the DataFrame API's backing tree.
Nodes are deliberately thin — resolution happens when the planner lowers
them onto the dual-backend physical execs.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import PlanNode
from spark_rapids_tpu.expr.core import Expression

__all__ = ["LogicalPlan", "Scan", "Project", "Filter", "Aggregate", "Join",
           "Sort", "Limit", "Union", "Window", "Repartition", "Expand",
           "Generate", "MapInPandas", "FlatMapGroupsInPandas",
           "AggregateInPandas", "FlatMapCoGroupsInPandas", "DataWrite"]


class LogicalPlan:
    children: tuple = ()

    @property
    def schema(self) -> T.Schema:
        raise NotImplementedError


@dataclass
class Scan(LogicalPlan):
    """Leaf wrapping a physical source exec (file scan / local scan)."""
    exec_node: PlanNode

    @property
    def children(self):
        return ()

    @property
    def schema(self) -> T.Schema:
        return self.exec_node.output_schema


@dataclass
class Project(LogicalPlan):
    exprs: list
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)


@dataclass
class Filter(LogicalPlan):
    condition: Expression
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)

    @property
    def schema(self):
        return self.child.schema


@dataclass
class Aggregate(LogicalPlan):
    group_exprs: list
    agg_exprs: list
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)


@dataclass
class Join(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    how: str
    left_on: list
    right_on: list
    condition: Expression | None = None

    @property
    def children(self):
        return (self.left, self.right)


@dataclass
class Sort(LogicalPlan):
    orders: list
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)

    @property
    def schema(self):
        return self.child.schema


@dataclass
class Limit(LogicalPlan):
    n: int
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)

    @property
    def schema(self):
        return self.child.schema


@dataclass
class Union(LogicalPlan):
    inputs: list

    @property
    def children(self):
        return tuple(self.inputs)

    @property
    def schema(self):
        return self.inputs[0].schema


@dataclass
class Window(LogicalPlan):
    window_exprs: list
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)


@dataclass
class Expand(LogicalPlan):
    """N projections per input row (rollup/cube/grouping sets;
    reference GpuExpandExec.scala:67)."""
    projections: list  # list of same-arity expression lists
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)


@dataclass
class Generate(LogicalPlan):
    """Generator (explode/posexplode) appended to or replacing the child
    output (reference GpuGenerateExec.scala:101)."""
    generator: Expression
    child: LogicalPlan
    outer: bool = False
    pos: bool = False
    output_names: list = field(default_factory=lambda: ["col"])

    @property
    def children(self):
        return (self.child,)


@dataclass
class MapInPandas(LogicalPlan):
    """fn(iterator of pandas DataFrames) -> iterator of DataFrames
    (reference GpuMapInPandasExec)."""
    fn: object
    out_schema: T.Schema
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)

    @property
    def schema(self):
        return self.out_schema


@dataclass
class FlatMapGroupsInPandas(LogicalPlan):
    """group_by(keys).apply_in_pandas(fn, schema) (reference
    GpuFlatMapGroupsInPandasExec)."""
    keys: list
    fn: object
    out_schema: T.Schema
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)

    @property
    def schema(self):
        return self.out_schema


@dataclass
class AggregateInPandas(LogicalPlan):
    """group_by(keys).agg(pandas_agg_udf...) (reference
    GpuAggregateInPandasExec)."""
    keys: list
    udfs: list  # (output name, PandasAggUDF)
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)


@dataclass
class FlatMapCoGroupsInPandas(LogicalPlan):
    """cogroup(...).apply_in_pandas(fn, schema) (reference
    GpuFlatMapCoGroupsInPandasExec)."""
    left_keys: list
    right_keys: list
    fn: object
    out_schema: T.Schema
    left: LogicalPlan
    right: LogicalPlan

    @property
    def children(self):
        return (self.left, self.right)

    @property
    def schema(self):
        return self.out_schema


@dataclass
class Repartition(LogicalPlan):
    num_partitions: int
    keys: list  # empty = round robin
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)

    @property
    def schema(self):
        return self.child.schema


@dataclass
class DataWrite(LogicalPlan):
    """Directory-write sink: CTAS/INSERT analog (reference
    GpuDataWritingCommandExec over GpuParquetFileFormat).  ``fmt`` is the
    file format name, ``path`` the output directory, ``partition_by``
    hive-style partition column names, ``options`` format writer
    options."""
    fmt: str
    path: str
    partition_by: list
    options: dict
    child: LogicalPlan

    @property
    def children(self):
        return (self.child,)

    @property
    def schema(self):
        return self.child.schema
